"""Crash-recovery soak over a REAL ``peer run`` process cluster (ISSUE 20).

The recovery subsystem's whole claim is about surviving SIGKILL — so its
acceptance harness runs actual OS processes, not an in-process cluster:
scaffold a testnet, run every replica with a durable ``--state-dir``
(and optionally under the seeded chaos wrap), drive pipelined client
load, ``kill -9`` one replica MID-LOAD, restart it against the same
store, and read the recovery clock off the restarted replica's own
``minbft_recovery_*`` Prometheus families.

What one soak run proves (``run_recovery_soak`` raises on any miss):

- **Zero committed loss** — every request the bench fired commits;
  a kill/restart cycle may slow the cluster, never un-commit it.
- **Durable restore happened** — the restarted replica reports
  ``minbft_recovery_restored_count`` (it resumed from its store, not a
  cold state fetch) and a finite ``minbft_recovery_time_ms``.
- **Store invariants** — every surviving store file decodes, its f+1
  certificate is structurally valid, and its snapshot recomputes to the
  certified digest (:class:`~minbft_tpu.testing.invariants.RecoveryInvariantChecker`).
- **Census honesty** (chaos mode) — each replica's live injected-fault
  census equals the count replayed from the seed and its recorded
  per-link frame totals alone: the faults the soak survived were
  exactly the deterministic schedule, no more, no fewer.

The report dict feeds the bench's ``chaos_recovery_*`` keys, which
``tools/benchgate`` gates (recovery-time on INCREASE, under-recovery
goodput on DROP) — the recovery-time SLO is a number in CI, not prose.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional

from .faultnet import SEEDED_KINDS, FaultNet, plan_from_spec
from .invariants import InvariantViolation, RecoveryInvariantChecker

#: Default chaos plan for the pinned soak: mild loss + delay so the
#: transfer/catch-up paths see real adversity without severing the
#: cluster (the soak asserts 100% commit).
DEFAULT_SOAK_PLAN = "drop=0.01,delay=0.05,duplicate=0.01"


def _peer_cmd(workdir: str, *tail: str) -> list:
    return [
        sys.executable, "-m", "minbft_tpu.sample.peer",
        "--keys", f"{workdir}/keys.yaml",
        "--config", f"{workdir}/consensus.yaml",
        "--transport", "tcp", *tail,
    ]


def _metrics_port(log_path: str, offset: int, timeout: float) -> int:
    """Parse the ``--metrics-port 0`` announcement from a replica's
    stderr log, reading only bytes past ``offset`` (a restarted replica
    appends a SECOND announcement to the same file)."""
    import re

    deadline = time.time() + timeout
    while time.time() < deadline:
        with open(log_path, "rb") as fh:
            fh.seek(offset)
            m = re.search(rb"metrics on http://[^:]+:(\d+)/metrics", fh.read())
        if m:
            return int(m.group(1))
        time.sleep(0.25)
    raise AssertionError(f"{log_path} never announced its metrics endpoint")


def _scrape_families(addr: str, timeout: float = 5.0) -> dict:
    from ..obs.prom import parse_exposition, scrape

    return parse_exposition(scrape(addr, timeout=timeout))


def _gauge(fams: dict, name: str) -> Optional[float]:
    fam = fams.get(name)
    if not fam or not fam["samples"]:
        return None
    return next(iter(fam["samples"].values()))


def _census_from_scrape(fams: dict) -> dict:
    """Rebuild (seeded counts, per-link frames) from the faultnet
    exposition families."""
    seeded = {k: 0 for k in SEEDED_KINDS}
    fam = fams.get("minbft_faultnet_injected_total")
    for key, v in (fam["samples"] if fam else {}).items():
        kind = dict(key).get("kind")
        if kind in seeded:
            seeded[kind] = int(v)
    frames: Dict[tuple, int] = {}
    fam = fams.get("minbft_faultnet_frames_total")
    for key, v in (fam["samples"] if fam else {}).items():
        link = dict(key).get("link", "")
        src, _, dst = link.partition(">")
        if src and dst:
            frames[(src, dst)] = int(v)
    return {"seeded": seeded, "frames": frames}


def run_recovery_soak(
    workdir: str,
    *,
    replicas: int = 4,
    requests: int = 200,
    clients: int = 8,
    depth: int = 4,
    kill_target: int = 3,
    checkpoint_period: int = 8,
    chunk_bytes: int = 4096,
    chaos_seed: Optional[int] = None,
    chaos_plan: str = "",
    down_s: float = 1.0,
    bench_timeout_s: float = 420.0,
) -> dict:
    """Run one kill-9-mid-load recovery soak; returns the report dict.

    Raises AssertionError/InvariantViolation on any acceptance miss —
    the caller (pytest, the bench phase, the CI tier) only has to
    propagate.  ``chaos_seed=None`` runs without the network-fault wrap
    (process chaos only); a pinned seed makes the whole fault schedule
    replayable and turns on the census-equality check.

    Size ``requests`` so the load OUTLIVES the outage: the recovery
    clock stops at the restarted replica's first executed request, and
    a bench that drains while the replica is still rebooting (a python
    interpreter restart is seconds) leaves the clock running until the
    180s wait gives up.  ~30s+ of load at the host's committed rate is
    the safe floor.
    """
    from ..recovery import store_path
    from ..utils.netports import free_base_port, wait_ports
    from .faultnet import ProcessChaos

    f = (replicas - 1) // 2
    state_dir = os.path.join(workdir, "state")
    base_port = free_base_port(replicas)

    # The peer subprocesses must import this checkout regardless of the
    # caller's cwd.
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(
        os.environ,
        PYTHONPATH=repo_root
        + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
        CONSENSUS_TIMEOUT_REQUEST="60s",
        CONSENSUS_TIMEOUT_PREPARE="30s",
        CONSENSUS_CHECKPOINT_PERIOD=str(checkpoint_period),
        MINBFT_STATE_DIR=state_dir,
        MINBFT_RECOVERY_CHUNK_BYTES=str(chunk_bytes),
    )
    env.pop("MINBFT_CHAOS_SEED", None)
    env.pop("MINBFT_CHAOS_PLAN", None)
    plan_spec = ""
    if chaos_seed is not None:
        plan_spec = chaos_plan or DEFAULT_SOAK_PLAN
        env["MINBFT_CHAOS_SEED"] = hex(chaos_seed)
        env["MINBFT_CHAOS_PLAN"] = plan_spec

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", str(replicas), "-d", workdir, "--base-port", str(base_port),
         "--clients", str(clients), "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    chaos = ProcessChaos()
    logs = []

    def start_replica(i: int):
        log = open(f"{workdir}/replica{i}.log", "ab")
        logs.append(log)
        return subprocess.Popen(
            _peer_cmd(workdir, "run", str(i), "--no-batch",
                      "--metrics-port", "0"),
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )

    report: dict = {
        "requested": 0, "committed": 0, "chaos_seed": chaos_seed,
        "chaos_plan": plan_spec,
    }
    bench = None
    try:
        for i in range(replicas):
            chaos.manage(f"r{i}", lambda i=i: start_replica(i))
        assert wait_ports(
            [base_port + i for i in range(replicas)]
        ), "replicas never bound"
        mports = {
            i: _metrics_port(f"{workdir}/replica{i}.log", 0, 30)
            for i in range(replicas)
        }

        bench = subprocess.Popen(
            _peer_cmd(workdir, "bench", "--clients", str(clients),
                      "--requests", str(requests), "--depth", str(depth),
                      "--tag", "soak"),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True,
        )

        # Kill only once the target has something durable to lose: its
        # store file exists after the first stable checkpoint persists.
        target_store = store_path(state_dir, kill_target)
        deadline = time.time() + 120
        while time.time() < deadline and not os.path.exists(target_store):
            assert bench.poll() is None, "bench finished before any " \
                "stable checkpoint persisted — raise requests or lower " \
                "checkpoint_period"
            time.sleep(0.25)
        assert os.path.exists(target_store), (
            f"replica {kill_target} never persisted a stable checkpoint"
        )

        # THE event: SIGKILL mid-load, a short outage, restart against
        # the same store.  The restarted replica must restore, catch up,
        # and execute again — its own metrics are the recovery clock.
        log_off = os.path.getsize(f"{workdir}/replica{kill_target}.log")
        t_kill = time.monotonic()
        chaos.kill(f"r{kill_target}")
        time.sleep(down_s)
        chaos.restart(f"r{kill_target}")
        assert wait_ports(
            [base_port + kill_target]
        ), "restarted replica never bound"
        mports[kill_target] = _metrics_port(
            f"{workdir}/replica{kill_target}.log", log_off, 30
        )

        addr = f"127.0.0.1:{mports[kill_target]}"
        restored = recovery_ms = None
        deadline = time.time() + 180
        while time.time() < deadline:
            try:
                fams = _scrape_families(addr)
            except OSError:
                time.sleep(0.5)
                continue
            restored = _gauge(fams, "minbft_recovery_restored_count")
            recovery_ms = _gauge(fams, "minbft_recovery_time_ms")
            if recovery_ms is not None:
                break
            time.sleep(0.5)
        assert restored is not None, (
            "restarted replica never reported minbft_recovery_restored_count "
            "— it did not restore from its durable store"
        )
        assert recovery_ms is not None, (
            "restarted replica never reported minbft_recovery_time_ms — "
            "it restored but never executed again (catch-up wedged)"
        )
        report["restored_count"] = int(restored)
        report["chaos_recovery_time_ms"] = round(float(recovery_ms), 2)
        report["wall_recovery_ms"] = round(
            (time.monotonic() - t_kill) * 1e3, 2
        )

        # Zero committed loss: the bench awaits EVERY request — a clean
        # exit with committed == requested is the loss proof.
        out, _ = bench.communicate(timeout=bench_timeout_s)
        assert bench.returncode == 0, "bench failed (request lost or wedged)"
        stats = json.loads(out.strip().splitlines()[-1])
        report["requested"] = (max(requests // clients, 1)) * clients
        report["committed"] = stats["committed"]
        assert stats["committed"] == report["requested"], (
            f"committed {stats['committed']} != requested "
            f"{report['requested']}: a committed request was lost"
        )
        report["chaos_recovery_goodput_per_sec"] = stats["req_per_sec"]

        # Durable-store invariants across every replica that persisted.
        checker = RecoveryInvariantChecker(f)
        report["stores"] = checker.check_all(
            {i: store_path(state_dir, i) for i in range(replicas)}
        )
        if kill_target not in report["stores"]:
            raise InvariantViolation(
                f"replica {kill_target}'s durable store vanished after "
                "the kill/restart cycle"
            )

        # Census equality (chaos mode): the live per-replica census must
        # equal the seed-replay over its recorded frame counts.  Scrape
        # until quiescent (two identical reads) — the census mutates
        # while checkpoint traffic drains.
        if chaos_seed is not None:
            replayer = FaultNet(
                seed=chaos_seed, default_plan=plan_from_spec(plan_spec)
            )
            census_ok = {}
            for i in range(replicas):
                a = f"127.0.0.1:{mports[i]}"
                prev = None
                deadline = time.time() + 60
                while time.time() < deadline:
                    cur = _census_from_scrape(_scrape_families(a))
                    if prev == cur:
                        break
                    prev = cur
                    time.sleep(1.0)
                replayed = replayer.replay_counts(prev["frames"])
                assert prev["seeded"] == replayed, (
                    f"replica {i}: live census {prev['seeded']} != "
                    f"seed-replayed {replayed} "
                    f"(seed {chaos_seed:#x}, plan {plan_spec})"
                )
                census_ok[i] = prev["seeded"]
            report["census"] = census_ok
        return report
    finally:
        if bench is not None and bench.poll() is None:
            bench.kill()
        chaos.terminate_all()
        for log in logs:
            log.close()
