"""Byzantine replica harnesses: real keys, real codec, hostile content.

The forged-message tests (tests/test_byzantine.py) throw garbage
signatures at the cluster; this module goes further — an
:class:`Adversary` holds a replica's GENUINE authenticator (its
signature keys and its USIG) and crafts protocol messages that are
well-formed and partially genuine, probing exactly the properties the
paper's argument leans on:

- **equivocation** (`equivocating_prepares`): two conflicting PREPAREs
  for one view — the first genuinely certified, the second reusing the
  SAME UI over different content.  USIG counter monotonicity is the
  defense: one counter value certifies one message, so the second can
  only be a cert forgery and must fail verification.
- **stale-UI replay** (`replay`): a genuine old certified message
  re-sent; per-peer in-order once-only capture must make it a no-op.
- **wrong-view PREPARE** (`wrong_view_prepare`): genuinely certified,
  but for a view the cluster is not in; it must never apply in the
  current view.
- **counter-gap COMMIT** (`counter_gap_commit`): a genuine cert whose
  counter skips a value (the adversary signed something it never sent).
  Receivers must not process past the gap — the skipped slot could hide
  anything.
- **conflicting REPLYs** (:class:`ConflictingReplyReplica`): a replica
  answering clients with correctly-signed WRONG results; the client's
  f+1 matching-reply quorum must keep a single liar's vote worthless.

The adversary is expected to own its identity exclusively while active
(crash the real replica first — its USIG counter is a shared serial
resource), which also keeps the cluster inside its f = 1 fault budget.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Iterable, List, Optional, Sequence

from .. import api
from ..core import usig_ui
from ..core import utils as core_utils
from ..messages import (
    Commit,
    Hello,
    Message,
    Prepare,
    Reply,
    Request,
    UI,
    authen_bytes,
    marshal,
    split_multi,
    unmarshal,
)


class Adversary:
    """Craft signed/certified messages under a replica's genuine keys."""

    def __init__(self, replica_id: int, authenticator: api.Authenticator, n: int):
        self.replica_id = replica_id
        self.n = n
        self._auth = authenticator
        self._assign_ui = usig_ui.make_ui_assigner(authenticator)

    # -- primitives ----------------------------------------------------

    def sign(self, msg: Message) -> Message:
        """Genuine plain signature (REPLICA role for replica-signed
        kinds; REPLYs are audience-keyed for MAC schemes)."""
        audience = msg.client_id if isinstance(msg, Reply) else -1
        msg.signature = self._auth.generate_message_authen_tag(
            core_utils.signing_role(msg), authen_bytes(msg), audience
        )
        return msg

    def certify(self, msg: Message) -> Message:
        """Genuine USIG certification — consumes the next counter."""
        self._assign_ui(msg)
        return msg

    def burn_counter(self) -> int:
        """Consume one USIG counter on a message that is never sent
        (the gap maker).  Returns the burned counter value."""
        ghost = Prepare(
            replica_id=self.replica_id, view=0, requests=(Request(
                client_id=0, seq=0, operation=b"burned"
            ),),
        )
        self.certify(ghost)
        return ghost.ui.counter

    # -- behaviors -----------------------------------------------------

    def equivocating_prepares(
        self, view: int, requests_a: Sequence[Request], requests_b: Sequence[Request]
    ) -> List[Prepare]:
        """A genuinely-certified PREPARE for ``requests_a`` plus a
        conflicting PREPARE for ``requests_b`` reusing the SAME UI —
        the equivocation attempt USIG monotonicity must reject past the
        first (the cert binds the authen bytes, so the copy's cert is a
        forgery)."""
        a = Prepare(
            replica_id=self.replica_id, view=view, requests=tuple(requests_a)
        )
        self.certify(a)
        b = Prepare(
            replica_id=self.replica_id,
            view=view,
            requests=tuple(requests_b),
            ui=UI(counter=a.ui.counter, cert=a.ui.cert),
        )
        return [a, b]

    def wrong_view_prepare(
        self, view: int, requests: Sequence[Request]
    ) -> Prepare:
        """A genuinely-certified PREPARE for a view the cluster is NOT
        in.  Pick a view whose primary this adversary actually is
        (``view % n == replica_id``) so the rejection under test is the
        view check, not the primary check."""
        if view % self.n != self.replica_id:
            raise ValueError(
                f"adversary {self.replica_id} is not the primary of view "
                f"{view} — use view {self.replica_id} (+ k*n)"
            )
        p = Prepare(replica_id=self.replica_id, view=view, requests=tuple(requests))
        return self.certify(p)

    def counter_gap_commit(self, prepare: Prepare) -> Commit:
        """A genuinely-certified COMMIT whose counter skips a value: one
        counter is burned unsent, so the receiver's in-order capture
        must park (and never process) this message — the gap could hide
        an equivocation."""
        self.burn_counter()
        c = Commit(replica_id=self.replica_id, prepare=prepare)
        return self.certify(c)

    def conflicting_reply(
        self, client_id: int, seq: int, result: bytes, read_only: bool = False
    ) -> Reply:
        """A correctly-signed REPLY carrying a WRONG result."""
        r = Reply(
            replica_id=self.replica_id,
            client_id=client_id,
            seq=seq,
            result=result,
            read_only=read_only,
        )
        return self.sign(r)

    @staticmethod
    def replay(msg: Message) -> Message:
        """A stale replay is just the message again (self-documenting
        call site; capture-side dedup is the property under test)."""
        return msg

    # -- delivery ------------------------------------------------------

    async def inject(
        self,
        victim_handler: api.MessageStreamHandler,
        payloads: Iterable[Message],
        hold_s: float = 0.5,
    ) -> None:
        """Open a peer stream to a victim (its
        ``peer_message_stream_handler()``) with this adversary's GENUINE
        signed HELLO — the handshake is authenticated, an outsider
        cannot even reach the dispatch — and pump the payloads through
        the real codec.  Holds the stream open ``hold_s`` so parked
        captures (gap messages) are observable, then withdraws."""
        done = asyncio.Event()

        async def outgoing() -> AsyncIterator[bytes]:
            hello = Hello(replica_id=self.replica_id)
            self.sign(hello)
            yield marshal(hello)
            for msg in payloads:
                yield marshal(msg)
            try:
                await asyncio.wait_for(done.wait(), hold_s)
            except asyncio.TimeoutError:
                return

        async def drain() -> None:
            async for _ in victim_handler.handle_message_stream(outgoing()):
                pass

        consumer = asyncio.ensure_future(drain())
        await asyncio.sleep(hold_s)
        done.set()
        consumer.cancel()
        try:
            await consumer
        except (asyncio.CancelledError, Exception):
            pass


class ConflictingReplyReplica:
    """A drop-in for a ReplicaStub's replica slot that answers every
    client REQUEST with a correctly-signed WRONG result (and serves no
    peer traffic): the conflicting-REPLY adversary.  The client's f+1
    matching quorum must never count it toward acceptance."""

    def __init__(
        self,
        adversary: Adversary,
        forged_result: bytes = b"\xde\xad" * 16,
    ):
        self.id = adversary.replica_id
        self._adv = adversary
        self.forged_result = forged_result
        self.replies_sent = 0

    def peer_message_stream_handler(self) -> api.MessageStreamHandler:
        return _SilentHandler()

    def client_message_stream_handler(self) -> api.MessageStreamHandler:
        return _ForgingClientHandler(self)

    async def start(self) -> None:  # api.Replica shape (stub assignment)
        return None

    async def stop(self) -> None:
        return None


class _SilentHandler(api.MessageStreamHandler):
    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        async for _ in in_stream:
            pass
        return
        yield b""  # pragma: no cover - makes this an async generator


class _ForgingClientHandler(api.MessageStreamHandler):
    def __init__(self, owner: ConflictingReplyReplica):
        self._owner = owner

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        owner = self._owner
        async for data in in_stream:
            try:
                frames = split_multi(data)
            except Exception:
                continue
            for fr in frames:
                try:
                    msg = unmarshal(fr)
                except Exception:
                    continue
                if not isinstance(msg, Request):
                    continue
                reply = owner._adv.conflicting_reply(
                    msg.client_id,
                    msg.seq,
                    owner.forged_result,
                    read_only=msg.is_fast_read,
                )
                owner.replies_sent += 1
                yield marshal(reply)


def take_over(replica, stub, adversary: Optional[Adversary] = None) -> Adversary:
    """Convert a running replica into an adversary identity: crash its
    streams, stop its tasks, and hand back an Adversary over its
    authenticator (counter continuity included — the next certified
    message extends the replica's genuine USIG sequence)."""
    stub.crash()
    adv = adversary or Adversary(
        replica.id, replica.handlers.authenticator, replica.n
    )
    return adv
