"""Deterministic, seeded fault injection for any ReplicaConnector.

The protocol's tolerance claims are about NETWORK misbehavior — drops,
delays, duplication, reordering, corruption, half-open stalls, and
partitions — yet transports deliver faithfully in tests.  This module
wraps any :class:`minbft_tpu.api.ReplicaConnector` (in-process, TCP, and
gRPC all flow through the same ``handle_message_stream`` interface) in a
:class:`FaultyConnector` that applies a per-directed-link
:class:`FaultPlan` to every transport frame.

Determinism contract: the fault decision for the k-th frame on a
directed link is a pure function of ``(seed, src, dst, k)`` — each link
owns a :class:`random.Random` seeded from a string of the three (string
seeding is hash-randomization-free), and every frame consumes a FIXED
number of draws regardless of which faults fire.  Replaying the same
frame sequence through the same seed therefore reproduces the identical
fault schedule byte-for-byte (``tests/test_chaos.py`` pins this), and
:meth:`FaultNet.replay_counts` recomputes a live run's per-kind census
from its recorded per-link frame counts alone.

Operator-driven faults — stall, partition/heal, stream reset, crash —
are test-scripted rather than drawn (their timing is wall-clock by
nature); they are censused under their own kinds so a chaos run's full
fault census is scrapeable from the Prometheus endpoint
(:func:`minbft_tpu.obs.prom.collect_faultnet`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import random
from typing import AsyncIterator, Dict, Optional, Tuple

from .. import api

CHAOS_SEED_ENV = "MINBFT_CHAOS_SEED"

# Strong refs to scheduled aclose() tasks (TL601): the loop keeps only
# a weak reference to a running task, so without this set a deferred
# close is GC-able before the inner generator finalizes.
_close_tasks: set = set()

# The seeded (schedule-driven) fault kinds, in the order their draws are
# consumed per frame — replay_counts depends on this order staying fixed.
SEEDED_KINDS = ("drop", "delay", "duplicate", "reorder", "corrupt", "reset")
# Operator-driven kinds (scripted by the test/CLI, not drawn) — censused
# separately from the seeded schedule so replay_counts stays exact.
SCRIPTED_KINDS = ("stall", "partition", "crash", "restart", "reset_all")


def chaos_seed(default: Optional[int] = None) -> int:
    """Resolve the chaos seed: ``MINBFT_CHAOS_SEED`` wins (replay), then
    ``default``, then a fresh random seed (exploration — the caller must
    print it on failure so the run can be replayed)."""
    env = os.environ.get(CHAOS_SEED_ENV)
    if env:
        return int(env, 0)
    if default is not None:
        return default
    return int.from_bytes(os.urandom(4), "big")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Per-directed-link fault probabilities (all per frame, independent).

    - ``drop``: frame vanishes;
    - ``delay``: frame is held ``uniform(*delay_s)`` seconds (later frames
      on the link queue behind it — link-FIFO is preserved, like a real
      congested path);
    - ``duplicate``: frame is delivered twice back-to-back;
    - ``reorder``: frame is held and delivered AFTER the next frame
      (adjacent swap — the building block of arbitrary reorderings);
    - ``corrupt``: one byte is flipped (the codec/authenticator must
      reject the frame — corruption must never become acceptance);
    - ``reset``: the stream ENDS (connection drop) — this is what
      exercises the redial + HELLO-replay recovery path, and what heals
      capture gaps left by dropped certified messages.
    """

    drop: float = 0.0
    delay: float = 0.0
    delay_s: Tuple[float, float] = (0.001, 0.02)
    duplicate: float = 0.0
    reorder: float = 0.0
    corrupt: float = 0.0
    reset: float = 0.0


#: Named chaos profiles for the CLI (``peer selftest --chaos-profile``)
#: and quick test wiring.  Probabilities are deliberately modest: chaos
#: soaks assert 100% commit, so the network must be hostile, not severed.
PROFILES: Dict[str, FaultPlan] = {
    "lossy": FaultPlan(drop=0.03, delay=0.15, duplicate=0.03, reorder=0.05),
    "flaky": FaultPlan(
        drop=0.03,
        delay=0.12,
        duplicate=0.03,
        reorder=0.05,
        corrupt=0.01,
        reset=0.005,
    ),
    "slow": FaultPlan(delay=0.6, delay_s=(0.005, 0.05)),
}

#: ``peer run`` chaos-plan override (with ``MINBFT_CHAOS_SEED`` set):
#: a profile name from PROFILES or inline ``kind=prob`` pairs.
CHAOS_PLAN_ENV = "MINBFT_CHAOS_PLAN"


def plan_from_spec(spec: str) -> FaultPlan:
    """Parse a chaos-plan spec: a PROFILES name (``"lossy"``) or inline
    comma-separated probabilities (``"drop=0.02,reset=0.01"``).  The
    inline form accepts exactly the seeded FaultPlan fields — an unknown
    kind or a non-numeric value fails loudly (a typo silently yielding
    the all-zero plan would make a chaos soak vacuous)."""
    spec = (spec or "").strip()
    if not spec:
        return PROFILES["lossy"]
    if spec in PROFILES:
        return PROFILES[spec]
    if "=" not in spec:
        raise ValueError(
            f"unknown chaos plan {spec!r}: not a profile "
            f"({', '.join(sorted(PROFILES))}) and not kind=prob pairs"
        )
    kw: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, val = part.partition("=")
        kind = kind.strip()
        if kind not in SEEDED_KINDS:
            raise ValueError(
                f"unknown chaos fault kind {kind!r} in plan {spec!r} "
                f"(choose from {', '.join(SEEDED_KINDS)})"
            )
        try:
            kw[kind] = float(val)
        except ValueError:
            raise ValueError(
                f"bad probability for {kind!r} in chaos plan {spec!r}: "
                f"{val!r}"
            ) from None
    return FaultPlan(**kw)


class FaultCensus:
    """Counters of injected faults, shaped for the Prometheus exposition
    (obs/prom.collect_faultnet): per-kind totals, per-(link, kind)
    breakdown, and per-link frame counts (the replay input).  All
    mutation happens on the event loop; scrapes read GIL-atomic ints
    (the standard obs consistency model, see obs/prom.py)."""

    def __init__(self):
        self.counters: Dict[str, int] = {}
        self.links: Dict[Tuple[str, str], Dict[str, int]] = {}
        self.frames: Dict[Tuple[str, str], int] = {}

    def inc(self, kind: str, link: Optional[Tuple[str, str]] = None) -> None:
        self.counters[kind] = self.counters.get(kind, 0) + 1
        if link is not None:
            per = self.links.setdefault(link, {})
            per[kind] = per.get(kind, 0) + 1

    def note_frame(self, link: Tuple[str, str]) -> None:
        self.frames[link] = self.frames.get(link, 0) + 1

    def seeded_counts(self) -> Dict[str, int]:
        return {k: self.counters.get(k, 0) for k in SEEDED_KINDS}

    def snapshot(self) -> dict:
        return {
            "counters": dict(self.counters),
            "frames_total": sum(self.frames.values()),
            "links": {
                f"{s}>{d}": dict(kinds) for (s, d), kinds in self.links.items()
            },
        }


class _LinkState:
    """Per-directed-link schedule state: the seeded RNG and the cumulative
    frame index.  ``next_decision`` consumes a FIXED number of draws per
    frame (the determinism contract in the module docstring)."""

    def __init__(self, chaos_seed: int, src: str, dst: str):
        self.src = src
        self.dst = dst
        self.rng = random.Random(f"faultnet:{chaos_seed}:{src}>{dst}")
        self.frame_idx = 0

    def next_decision(self, plan: FaultPlan) -> dict:
        self.frame_idx += 1
        r = self.rng
        draws = [r.random() for _ in range(7)]
        lo, hi = plan.delay_s
        return {
            "drop": draws[0] < plan.drop,
            "delay": draws[1] < plan.delay,
            "delay_s": lo + draws[2] * (hi - lo),
            "duplicate": draws[3] < plan.duplicate,
            "reorder": draws[4] < plan.reorder,
            "corrupt": draws[5] < plan.corrupt,
            "reset": draws[6] < plan.reset,
        }


def _corrupt(frame: bytes, rng_byte: int) -> bytes:
    """Flip one byte, position keyed to the frame so replay of the same
    bytes corrupts identically."""
    if not frame:
        return frame
    pos = (rng_byte + len(frame)) % len(frame)
    mut = bytearray(frame)
    mut[pos] ^= 0xA5
    return bytes(mut)


class FaultNet:
    """The shared fault fabric: one instance per simulated network,
    wrapped around every endpoint's connector so scripted faults (stall,
    partition) apply consistently across all links.

    Endpoints are strings: ``"r<id>"`` for replicas, ``"c<id>"`` for
    clients.  A directed link is ``(src, dst)``.
    """

    def __init__(
        self,
        seed: Optional[int] = None,
        default_plan: Optional[FaultPlan] = None,
        census: Optional[FaultCensus] = None,
    ):
        # Public by design: the replay token printed on failure (NOT key
        # material — the name carries "chaos" for the secret-hygiene pass).
        self.chaos_seed = chaos_seed() if seed is None else seed
        self.census = census or FaultCensus()
        self._default_plan = default_plan or FaultPlan()
        # (src|None, dst|None) -> plan; exact match wins, then src-only,
        # then dst-only, then the default.
        self._plans: Dict[Tuple[Optional[str], Optional[str]], FaultPlan] = {}
        self._links: Dict[Tuple[str, str], _LinkState] = {}
        # Scripted state: stall patterns, partition groups, reset epoch.
        self._stalled: set = set()  # of (src|None, dst|None)
        self._partition: Tuple[frozenset, ...] = ()
        self._reset_epoch = 0
        # Swapped+fired on every scripted-state change so parked pipes
        # (stall waits, idle streams pending a reset) re-evaluate.
        self._state_event = asyncio.Event()

    # -- wiring --------------------------------------------------------

    def wrap(self, connector: api.ReplicaConnector, src: str) -> "FaultyConnector":
        """Wrap ``connector`` as endpoint ``src`` ("r2", "c0", ...)."""
        return FaultyConnector(connector, self, src)

    def _link(self, src: str, dst: str) -> _LinkState:
        st = self._links.get((src, dst))
        if st is None:
            st = _LinkState(self.chaos_seed, src, dst)
            self._links[(src, dst)] = st
        return st

    # -- plans ---------------------------------------------------------

    def set_plan(
        self,
        plan: Optional[FaultPlan],
        src: Optional[str] = None,
        dst: Optional[str] = None,
    ) -> None:
        """Install ``plan`` for links matching (src, dst); ``None``
        endpoint = wildcard; ``src=dst=None`` replaces the default plan;
        ``plan=None`` removes the override."""
        if src is None and dst is None:
            self._default_plan = plan or FaultPlan()
            return
        if plan is None:
            self._plans.pop((src, dst), None)
        else:
            self._plans[(src, dst)] = plan

    def heal(self) -> None:
        """Back to a faithful network: clears every plan override, the
        default plan, all stalls, and any partition.  Live streams keep
        flowing (use :meth:`reset_all` to force clean redials too)."""
        self._plans.clear()
        self._default_plan = FaultPlan()
        self._stalled.clear()
        self._partition = ()
        self._kick()

    def plan_for(self, src: str, dst: str) -> FaultPlan:
        for key in ((src, dst), (src, None), (None, dst)):
            p = self._plans.get(key)
            if p is not None:
                return p
        return self._default_plan

    # -- scripted faults ----------------------------------------------

    def _kick(self) -> None:
        ev, self._state_event = self._state_event, asyncio.Event()
        ev.set()

    def stall(self, src: Optional[str] = None, dst: Optional[str] = None) -> None:
        """Half-open stall for links matching (src, dst): connections
        stay up, frames stop flowing until :meth:`unstall`."""
        self._stalled.add((src, dst))
        self._kick()

    def unstall(self, src: Optional[str] = None, dst: Optional[str] = None) -> None:
        self._stalled.discard((src, dst))
        self._kick()

    def stall_replica(self, replica_id: int) -> None:
        """Stall EVERY link touching a replica — the wedged-process /
        dead-NIC-but-open-socket scenario the request-timeout path must
        detect (a closed connection is the easy case)."""
        ep = f"r{replica_id}"
        self.stall(src=ep)
        self.stall(dst=ep)

    def unstall_replica(self, replica_id: int) -> None:
        ep = f"r{replica_id}"
        self.unstall(src=ep)
        self.unstall(dst=ep)

    def is_stalled(self, src: str, dst: str) -> bool:
        s = self._stalled
        return bool(s) and (
            (src, dst) in s or (src, None) in s or (None, dst) in s
        )

    def partition(self, *groups) -> None:
        """Split the listed endpoint groups: frames between different
        groups are dropped (censused as "partition") until :meth:`heal`
        or :meth:`heal_partition`.  Endpoints in NO group (typically
        clients) keep talking to everyone."""
        self._partition = tuple(frozenset(g) for g in groups)
        self._kick()

    def heal_partition(self) -> None:
        self._partition = ()
        self._kick()

    def is_partitioned(self, src: str, dst: str) -> bool:
        gs = self._partition
        if not gs:
            return False
        a = next((i for i, g in enumerate(gs) if src in g), None)
        b = next((i for i, g in enumerate(gs) if dst in g), None)
        return a is not None and b is not None and a != b

    def reset_all(self) -> None:
        """End every live stream flowing through this net (each counted
        as a "reset"): the callers' redial loops reconnect and the HELLO
        replay re-streams full logs — the convergence step after a chaos
        phase, and the recovery that heals any capture gap a dropped
        certified message left behind."""
        self._reset_epoch += 1
        self._kick()

    def crash(self, target, endpoint: str) -> None:
        """Crash a whole replica via its stub/handle (anything with a
        ``crash()`` — e.g. ``sample.conn.inprocess.ReplicaStub``),
        censused under "crash"."""
        target.crash()
        self.census.inc("crash", (endpoint, "*"))

    def restart(self, target, endpoint: str) -> None:
        """Revive a crashed stub (``revive()``), censused under
        "restart"; the caller re-assigns/starts the replica instance."""
        target.revive()
        self.census.inc("restart", (endpoint, "*"))

    # -- the frame pipe ------------------------------------------------

    async def pipe(
        self, src: str, dst: str, frames: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        """Apply the (src → dst) fault schedule to a frame stream.

        Ends (StopAsyncIteration to the consumer) on a drawn "reset" or a
        scripted :meth:`reset_all` — the transport above interprets that
        as a dropped connection and redials."""
        link = self._link(src, dst)
        census = self.census
        epoch = self._reset_epoch
        held: Optional[bytes] = None
        ait = frames.__aiter__()
        nxt: Optional[asyncio.Future] = None
        try:
            while True:
                nxt = asyncio.ensure_future(ait.__anext__())
                # Race the next frame against scripted-state changes so
                # an idle stream still honors reset_all promptly.
                while not nxt.done():
                    kick = asyncio.ensure_future(self._state_event.wait())
                    await asyncio.wait(
                        {nxt, kick}, return_when=asyncio.FIRST_COMPLETED
                    )
                    kick.cancel()
                    if self._reset_epoch != epoch:
                        census.inc("reset_all", (src, dst))
                        return
                try:
                    frame = nxt.result()
                except StopAsyncIteration:
                    break
                nxt = None

                census.note_frame((src, dst))
                d = link.next_decision(self.plan_for(src, dst))

                # Census the DRAWN schedule first — a pure function of
                # (seed, link, frame index), with reset > drop > rest
                # precedence, so replay_counts can recompute it from the
                # per-link frame counts alone.  A drawn fault can still
                # be a no-op in effect (a duplicate of a frame the
                # reorder is holding, a drop of a frame a partition
                # already discards): the census records the schedule,
                # scripted kinds record the effects.
                if d["reset"]:
                    census.inc("reset", (src, dst))
                elif d["drop"]:
                    census.inc("drop", (src, dst))
                else:
                    for kind in ("corrupt", "delay", "reorder", "duplicate"):
                        if d[kind]:
                            census.inc(kind, (src, dst))

                if d["reset"]:
                    return
                # Scripted stall: hold delivery, connection stays open.
                if self.is_stalled(src, dst):
                    census.inc("stall", (src, dst))
                    while self.is_stalled(src, dst):
                        await self._state_event.wait()
                        if self._reset_epoch != epoch:
                            census.inc("reset_all", (src, dst))
                            return
                if self.is_partitioned(src, dst):
                    census.inc("partition", (src, dst))
                    continue
                if d["drop"]:
                    continue
                if d["corrupt"]:
                    frame = _corrupt(frame, link.frame_idx)
                if d["delay"]:
                    await asyncio.sleep(d["delay_s"])
                if d["reorder"] and held is None:
                    held = frame
                    continue
                yield frame
                if held is not None:
                    out, held = held, None
                    yield out
                if d["duplicate"]:
                    yield frame
            if held is not None:
                yield held
        finally:
            if nxt is not None:
                if nxt.done():
                    # Retrieve the result/StopAsyncIteration a scripted
                    # reset abandoned, or asyncio logs "exception was
                    # never retrieved" at teardown.
                    try:
                        nxt.exception()
                    except asyncio.CancelledError:
                        pass
                else:
                    # cancel() can lose the race: the underlying asend
                    # may complete (e.g. with StopAsyncIteration when the
                    # source just ended) before the cancellation lands,
                    # and that exception would then be "never retrieved".
                    nxt.cancel()
                    nxt.add_done_callback(
                        lambda t: t.cancelled() or t.exception()
                    )

            # May run under GeneratorExit (consumer closed us), where
            # awaiting is not allowed: schedule the inner close instead
            # (the inprocess _DeferredHandler pattern).
            async def _close() -> None:
                try:
                    await ait.aclose()
                except BaseException:
                    pass

            if hasattr(ait, "aclose"):
                t = asyncio.get_running_loop().create_task(_close())
                _close_tasks.add(t)
                t.add_done_callback(_close_tasks.discard)

    # -- replay --------------------------------------------------------

    def replay_counts(
        self,
        frame_counts: Optional[Dict[Tuple[str, str], int]] = None,
        plan: Optional[FaultPlan] = None,
    ) -> Dict[str, int]:
        """Recompute the seeded per-kind injection counts for the given
        per-link frame counts (default: this net's recorded census) from
        the seed alone — fresh RNGs, no live state.  A live run's census
        matching this proves its injections followed the deterministic
        schedule; the same seed + the same frame counts always reproduce
        the same totals.  ``plan`` pins the plan the run used (pass it
        when replaying a snapshot taken before a heal — plan_for would
        otherwise see the healed, fault-free plan)."""
        frame_counts = (
            dict(self.census.frames) if frame_counts is None else frame_counts
        )
        totals = {k: 0 for k in SEEDED_KINDS}
        for (src, dst), count in frame_counts.items():
            link = _LinkState(self.chaos_seed, src, dst)
            link_plan = plan if plan is not None else self.plan_for(src, dst)
            for _ in range(count):
                d = link.next_decision(link_plan)
                if d["reset"]:
                    totals["reset"] += 1
                    continue
                if d["drop"]:
                    totals["drop"] += 1
                    continue
                for k in ("corrupt", "delay", "reorder", "duplicate"):
                    if d[k]:
                        totals[k] += 1
        return totals


class _FaultyStreamHandler(api.MessageStreamHandler):
    """One wrapped stream: outgoing frames ride the (src → dst) schedule,
    the peer's responses ride (dst → src)."""

    def __init__(
        self,
        inner: api.MessageStreamHandler,
        net: FaultNet,
        src: str,
        dst: str,
    ):
        self._inner = inner
        self._net = net
        self._src = src
        self._dst = dst

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        net, src, dst = self._net, self._src, self._dst
        out = self._inner.handle_message_stream(net.pipe(src, dst, in_stream))
        async for frame in net.pipe(dst, src, out):
            yield frame


class FaultyConnector(api.ReplicaConnector):
    """Wrap any ReplicaConnector so every stream it opens flows through
    the FaultNet's per-directed-link schedules.  Unknown attributes
    (``connect_replica``, ``close``, ...) delegate to the inner
    connector, so transport-specific wiring keeps working."""

    def __init__(self, inner: api.ReplicaConnector, net: FaultNet, src: str):
        self._inner = inner
        self._net = net
        self._src = src

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        handler = self._inner.replica_message_stream_handler(replica_id)
        if handler is None:
            return None
        return _FaultyStreamHandler(
            handler, self._net, self._src, f"r{replica_id}"
        )

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyConnectionHandler(api.ConnectionHandler):
    """Server-side sibling of :class:`FaultyConnector`: wraps an
    ``api.ConnectionHandler`` so ACCEPTED streams flow through the net —
    how a real transport server (TcpReplicaServer, gRPC) is put behind
    the fault fabric.  Dialer identities are unknown at accept time, so
    the far end is labeled generically ("peer"/"client")."""

    def __init__(self, inner: api.ConnectionHandler, net: FaultNet, endpoint: str):
        self._inner = inner
        self._net = net
        self._endpoint = endpoint

    def peer_message_stream_handler(self) -> api.MessageStreamHandler:
        return _FaultyStreamHandler(
            self._inner.peer_message_stream_handler(),
            self._net,
            "peer",
            self._endpoint,
        )

    def client_message_stream_handler(self) -> api.MessageStreamHandler:
        return _FaultyStreamHandler(
            self._inner.client_message_stream_handler(),
            self._net,
            "client",
            self._endpoint,
        )


class ProcessChaos:
    """SIGKILL + restart chaos for real-OS-process clusters.

    The in-process :class:`FaultNet` injects NETWORK faults; this is its
    PROCESS sibling for deployments made of real ``peer run`` processes
    (tests/test_process_cluster.py, the recovery soak): registered
    targets are killed with SIGKILL — no graceful close on any stream,
    no atexit, exactly a machine reset — and restarted through the same
    spawn factory.  Kills and restarts are censused under the scripted
    kinds ("crash"/"restart"), so a soak's fault history reads out of
    the same :class:`FaultCensus` surface as the network faults.

    Not seeded: kill timing is wall-clock by nature (the operator or
    the soak script decides WHEN); determinism in a recovery soak comes
    from the load schedule's seed and the durable store's contents, not
    from the kill instant.
    """

    def __init__(self, census: Optional[FaultCensus] = None):
        self.census = census or FaultCensus()
        self._procs: Dict[str, object] = {}
        self._spawn: Dict[str, object] = {}

    def manage(self, name: str, spawn, proc=None):
        """Register a target: ``spawn()`` must return a started
        ``subprocess.Popen``-alike (``kill``/``wait``/``poll``).  Pass
        ``proc`` when the first incarnation is already running;
        otherwise the factory is invoked once, immediately."""
        self._spawn[name] = spawn
        self._procs[name] = proc if proc is not None else spawn()
        return self._procs[name]

    def proc(self, name: str):
        return self._procs[name]

    def alive(self, name: str) -> bool:
        p = self._procs.get(name)
        return p is not None and p.poll() is None

    def kill(self, name: str, wait: float = 10.0):
        """SIGKILL the target and reap it.  Idempotent on an already-
        dead process (the census records the intent either way — a soak
        script's kill is a fault even if the target beat it to dying)."""
        p = self._procs[name]
        p.kill()
        p.wait(timeout=wait)
        self.census.inc("crash", link=(name, name))
        return p

    def restart(self, name: str):
        """Respawn a killed target through its registered factory."""
        self._procs[name] = self._spawn[name]()
        self.census.inc("restart", link=(name, name))
        return self._procs[name]

    def kill_restart(self, name: str, wait: float = 10.0):
        """The canonical crash-recovery event: SIGKILL, reap, respawn."""
        self.kill(name, wait=wait)
        return self.restart(name)

    def terminate_all(self, wait: float = 10.0) -> None:
        """Teardown helper: TERM every live target, escalate to KILL on
        a hung wait.  Never censused — shutdown is not a fault."""
        for p in self._procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self._procs.values():
            try:
                p.wait(timeout=wait)
            except Exception:  # noqa: BLE001 - teardown must reach kill
                p.kill()
