"""Deterministic fault injection, Byzantine adversaries, and safety
invariants (ISSUE 5).

Three modules, usable from tests AND from the ``peer selftest
--chaos-seed`` CLI smoke path:

- :mod:`~minbft_tpu.testing.faultnet` — a seeded, replayable
  fault-injection layer wrapping any :class:`minbft_tpu.api.ReplicaConnector`
  (in-process, TCP, and gRPC all flow through the same interface): drop,
  delay, duplicate, reorder, byte-corrupt, stream reset, half-open stall,
  partition/heal, with a scrapeable fault census;
- :mod:`~minbft_tpu.testing.adversary` — Byzantine replica harnesses
  that speak real signed/certified messages through the real codec
  (equivocation, stale-UI replay, wrong-view PREPARE, counter-gap COMMIT,
  conflicting REPLYs);
- :mod:`~minbft_tpu.testing.invariants` — cross-replica safety checks
  (prefix-consistent execution logs, gap-free monotonic UI sequences,
  client-accepted results present in every correct ledger), callable
  mid-run and at teardown.
"""

from .faultnet import (
    CHAOS_PLAN_ENV,
    CHAOS_SEED_ENV,
    PROFILES,
    FaultCensus,
    FaultNet,
    FaultPlan,
    FaultyConnectionHandler,
    FaultyConnector,
    ProcessChaos,
    chaos_seed,
    plan_from_spec,
)
from .invariants import (
    InvariantChecker,
    InvariantViolation,
    RecoveryInvariantChecker,
)

__all__ = [
    "CHAOS_PLAN_ENV",
    "CHAOS_SEED_ENV",
    "PROFILES",
    "FaultCensus",
    "FaultNet",
    "FaultPlan",
    "FaultyConnectionHandler",
    "FaultyConnector",
    "InvariantChecker",
    "InvariantViolation",
    "ProcessChaos",
    "RecoveryInvariantChecker",
    "chaos_seed",
    "plan_from_spec",
]
