"""Cross-replica safety invariants, checkable mid-run and at teardown.

What BFT safety means for this build, stated as executable checks over
an in-process cluster (the chaos soak and the adversary suite call these
while faults are still in flight, then again after convergence):

1. **Prefix consistency** — the executed-request logs of all CORRECT
   replicas are prefixes of one another.  SimpleLedger hash-chains its
   blocks, so equal digests at the shorter ledger's head imply equal
   prefixes (one comparison per pair, not one per block).
2. **UI integrity** — each correct replica's OWN certified-message log
   holds contiguous USIG counters from its truncation base (an omission
   or fork would show as a gap or duplicate), and every replica's
   per-peer accepted-UI watermark only ever moves forward (checked
   against the previous snapshot when called repeatedly).
3. **Committed results** — every result a client ACCEPTED (an f+1
   quorum) appears in every correct replica's ledger as the digest of a
   block carrying that operation: what the client believes committed IS
   what the cluster executed.

Violations raise :class:`InvariantViolation` (an AssertionError, so
pytest renders it as a failure with the offending detail).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

from ..messages import CERTIFIED_MESSAGES


class InvariantViolation(AssertionError):
    """A cross-replica safety invariant does not hold."""


class InvariantChecker:
    """Holds the cluster handles plus the previous watermark snapshot so
    repeated mid-run calls can assert monotonicity, not just shape.

    ``correct`` lists the replica indices to hold to the safety bar
    (default: all) — crashed or Byzantine replicas are excluded by the
    caller, exactly as the BFT property is stated.
    """

    def __init__(
        self,
        replicas: Sequence,
        ledgers: Sequence,
        correct: Optional[Iterable[int]] = None,
    ):
        self._replicas = list(replicas)
        self._ledgers = list(ledgers)
        self._correct = (
            sorted(correct) if correct is not None else list(range(len(replicas)))
        )
        # (observer_idx, peer_id) -> last seen accepted-UI watermark.
        self._prev_marks: Dict[Tuple[int, int], int] = {}

    def set_correct(self, correct: Iterable[int]) -> None:
        """Narrow the correct set mid-run (a replica just crashed or
        turned adversarial)."""
        self._correct = sorted(correct)

    # -- individual invariants ----------------------------------------

    def check_prefix_consistency(self) -> None:
        idxs = self._correct
        for a in range(len(idxs)):
            for b in range(a + 1, len(idxs)):
                ia, ib = idxs[a], idxs[b]
                la, lb = self._ledgers[ia], self._ledgers[ib]
                h = min(la.length, lb.length)
                da = la.block(h).digest()
                db = lb.block(h).digest()
                if da != db:
                    # Hash chaining makes the head compare sufficient;
                    # walk back for the FIRST diverging height and name
                    # the executed operations around it — the detail
                    # that turns "fork" into a debuggable report.
                    first = h
                    while first > 1 and (
                        la.block(first - 1).digest()
                        != lb.block(first - 1).digest()
                    ):
                        first -= 1
                    ops_a = [
                        la.block(k).payload
                        for k in range(first, min(h, first + 4) + 1)
                    ]
                    ops_b = [
                        lb.block(k).payload
                        for k in range(first, min(h, first + 4) + 1)
                    ]
                    raise InvariantViolation(
                        f"ledger fork: replicas {ia} and {ib} diverge from "
                        f"height {first} (checked at {h}: {da.hex()[:12]} vs "
                        f"{db.hex()[:12]}); executed there: "
                        f"r{ia}={ops_a} vs r{ib}={ops_b}"
                    )

    def check_ui_integrity(self) -> None:
        for i in self._correct:
            r = self._replicas[i]
            h = r.handlers
            base = h._own_log_base[0]
            counters = [
                m.ui.counter
                for m in h.message_log.snapshot()
                if isinstance(m, CERTIFIED_MESSAGES)
                and m.replica_id == r.id
                and m.ui is not None
            ]
            expect = list(range(base + 1, base + 1 + len(counters)))
            if counters != expect:
                raise InvariantViolation(
                    f"replica {r.id}: own certified log counters not "
                    f"contiguous from base {base}: {counters[:16]}..."
                )
            for peer_id, st in h.peer_states._peers.items():
                mark = st._next_cv
                key = (i, peer_id)
                prev = self._prev_marks.get(key, 0)
                if mark < prev:
                    raise InvariantViolation(
                        f"replica {r.id}: accepted-UI watermark for peer "
                        f"{peer_id} moved backwards ({prev} -> {mark})"
                    )
                self._prev_marks[key] = mark

    def check_committed_results(
        self, accepted: Iterable[Tuple[bytes, bytes]]
    ) -> None:
        for op, result in accepted:
            for i in self._correct:
                lg = self._ledgers[i]
                blocks = [
                    lg.block(height)
                    for height in range(1, lg.length + 1)
                ]
                match = [b for b in blocks if b.payload == op]
                if not match:
                    raise InvariantViolation(
                        f"replica {self._replicas[i].id}: client-accepted "
                        f"operation {op!r} missing from the ledger"
                    )
                if all(b.digest() != result for b in match):
                    raise InvariantViolation(
                        f"replica {self._replicas[i].id}: no block for "
                        f"{op!r} digests to the client-accepted result "
                        f"{result.hex()[:12]}"
                    )

    # -- the combined check -------------------------------------------

    def check(
        self, accepted: Iterable[Tuple[bytes, bytes]] = ()
    ) -> dict:
        """Run every invariant; returns a summary dict for logs/census.

        ``accepted`` is the client's view: (operation, accepted result)
        pairs for ORDERED requests that resolved (reads don't append
        blocks and are excluded by the caller)."""
        self.check_prefix_consistency()
        self.check_ui_integrity()
        accepted = list(accepted)
        self.check_committed_results(accepted)
        return {
            "correct": list(self._correct),
            "ledger_lengths": [
                self._ledgers[i].length for i in self._correct
            ],
            "accepted_checked": len(accepted),
        }


class RecoveryInvariantChecker:
    """Durable-store invariants for crash-recovery soaks (ISSUE 20).

    :class:`InvariantChecker` above reaches into in-process replica
    objects; a recovery soak runs REAL ``peer run`` processes, so its
    safety surface is what survives a SIGKILL: the on-disk durable
    stores (minbft_tpu/recovery).  Checked per store and across stores:

    1. **Store self-consistency** — the committed file decodes (torn or
       tampered bytes are an InvariantViolation, mirroring the fatal
       startup refusal), carries a structurally valid f+1 certificate
       (distinct claimants, all claims matching on position + digest),
       and the persisted snapshot + watermarks RECOMPUTE to exactly the
       certified composite digest — the store can never testify to
       state it does not actually hold.
    2. **Durable monotonicity** — a replica's persisted stable count
       and USIG watermark never move backwards across repeated checks
       (i.e. across kill/restart cycles): crash-recovery must not
       un-happen progress the cluster certified.
    3. **No checkpoint fork** — any two stores claiming the same stable
       count carry the same certified digest.

    Signature VALIDITY is deliberately out of scope here (the live
    ``restore_from_store`` path re-verifies every cert signature through
    the real authenticator); this checker is the offline, between-kills
    view of the same evidence.
    """

    def __init__(self, f: int, digest_fn=None):
        self._f = f
        if digest_fn is None:
            from ..sample.requestconsumer import SimpleLedger

            digest_fn = SimpleLedger().snapshot_digest
        self._digest_fn = digest_fn
        # replica_id -> (count, usig) high-water marks across checks.
        self._prev: Dict[int, Tuple[int, int]] = {}
        # stable count -> (digest, claiming replica) across ALL checks.
        self._digests: Dict[int, Tuple[bytes, int]] = {}

    def check_store(self, path: str, replica_id: int) -> Optional[dict]:
        """Validate one replica's durable store file; returns a summary
        dict, or None when the file does not exist yet (a replica that
        has not reached its first stable checkpoint has nothing durable
        to hold to the bar)."""
        import os as _os

        from ..core.checkpoint import checkpoint_digest
        from ..recovery import CorruptStoreError, DurableStore

        if not _os.path.exists(path):
            return None
        try:
            state = DurableStore(path, replica_id).load()
        except CorruptStoreError as e:
            raise InvariantViolation(
                f"replica {replica_id}: durable store {path} is corrupt: {e}"
            ) from e
        if state is None:
            return None

        cert = state.cert
        if len(cert) < self._f + 1:
            raise InvariantViolation(
                f"replica {replica_id}: durable cert has {len(cert)} "
                f"claims, needs f+1={self._f + 1}"
            )
        claimants = {c.replica_id for c in cert}
        if len(claimants) != len(cert):
            raise InvariantViolation(
                f"replica {replica_id}: durable cert has duplicate "
                f"claimants {sorted(c.replica_id for c in cert)}"
            )
        claim = (cert[0].count, cert[0].view, cert[0].cv, cert[0].digest)
        for c in cert[1:]:
            if (c.count, c.view, c.cv, c.digest) != claim:
                raise InvariantViolation(
                    f"replica {replica_id}: durable cert claims disagree"
                )
        if claim[:3] != (state.count, state.view, state.cv):
            raise InvariantViolation(
                f"replica {replica_id}: durable position "
                f"{(state.count, state.view, state.cv)} does not match "
                f"its certificate {claim[:3]}"
            )
        composite = checkpoint_digest(
            self._digest_fn(state.app_state),
            state.count, state.view, state.cv, state.watermarks,
        )
        if composite != cert[0].digest:
            raise InvariantViolation(
                f"replica {replica_id}: persisted snapshot at count "
                f"{state.count} recomputes to {composite.hex()[:12]}, "
                f"cert says {cert[0].digest.hex()[:12]}"
            )

        prev = self._prev.get(replica_id)
        if prev is not None:
            if state.count < prev[0]:
                raise InvariantViolation(
                    f"replica {replica_id}: durable stable count moved "
                    f"backwards ({prev[0]} -> {state.count})"
                )
            if state.count == prev[0] and state.usig_counter < prev[1]:
                raise InvariantViolation(
                    f"replica {replica_id}: durable USIG watermark moved "
                    f"backwards at count {state.count} "
                    f"({prev[1]} -> {state.usig_counter})"
                )
        self._prev[replica_id] = (state.count, state.usig_counter)

        seen = self._digests.get(state.count)
        if seen is not None and seen[0] != cert[0].digest:
            raise InvariantViolation(
                f"checkpoint fork at stable count {state.count}: replica "
                f"{replica_id} certifies {cert[0].digest.hex()[:12]}, "
                f"replica {seen[1]} certified {seen[0].hex()[:12]}"
            )
        self._digests.setdefault(state.count, (cert[0].digest, replica_id))

        return {
            "replica": replica_id,
            "count": state.count,
            "view": state.view,
            "cv": state.cv,
            "usig": state.usig_counter,
            "cert": len(cert),
        }

    def check_all(self, paths: Dict[int, str]) -> dict:
        """Check every registered store; returns a per-replica summary
        (missing stores excluded)."""
        out = {}
        for replica_id, path in sorted(paths.items()):
            summary = self.check_store(path, replica_id)
            if summary is not None:
                out[replica_id] = summary
        return out
