"""Backoff policies shared by the redial and retransmit loops.

Both `core.message_handling.run_peer_connection` and
`client.Client._run_connection` redial dropped streams (the reference
instead relies on operators restarting peers, core/message-handling.go:
316-350 HELLO replay handles only the receiving side), and
`client.Client._await_with_retransmit` re-sends unresolved requests.
The ladders live here once so the loops cannot drift apart.

Jitter: a partition heal (or a replica restart) ends MANY streams in the
same event-loop turn — identical deterministic ladders would then redial
in lockstep forever, hammering the recovered peer with synchronized
connection storms (the classic thundering herd).  Every delay is
therefore spread by a multiplicative jitter factor drawn from the
policy's own RNG; tests that pin exact ladder values pass
``jitter_frac=0``.
"""

from __future__ import annotations

import random
from typing import Optional


class ReconnectBackoff:
    """Exponential redial ladder with a lived-connection reset.

    A connection that survived longer than ``lived_reset_s`` was healthy
    (not a crash-looping peer whose replay counts as liveness every
    attempt), so the next failure restarts the ladder at ``start_s``.

    ``jitter_frac`` spreads each returned delay uniformly over
    ``[delay*(1-j), delay*(1+j)]`` (still capped at ``cap_s``) so
    simultaneous stream deaths — a healed partition, a bounced peer —
    do not produce a synchronized redial herd.  The ladder itself
    (the un-jittered ``_delay``) advances deterministically.
    """

    def __init__(
        self,
        start_s: float = 0.2,
        cap_s: float = 10.0,
        lived_reset_s: float = 5.0,
        factor: float = 2.0,
        jitter_frac: float = 0.25,
        rng: Optional[random.Random] = None,
    ):
        self._start = start_s
        self._cap = cap_s
        self._lived = lived_reset_s
        self._factor = factor
        self._delay = start_s
        self._jitter = max(0.0, min(jitter_frac, 1.0))
        self._rng = rng if rng is not None else random.Random()

    def next_delay(self, attempt_lived_s: float) -> float:
        """Delay before the next dial, given how long the last attempt
        lived.  Advances the ladder."""
        if attempt_lived_s > self._lived:
            self._delay = self._start
        delay = self._delay
        self._delay = min(self._delay * self._factor, self._cap)
        if self._jitter:
            delay *= 1.0 + self._jitter * (2.0 * self._rng.random() - 1.0)
        return min(delay, self._cap)


class RetransmitBackoff:
    """Capped exponential retransmit ladder with jitter (no lived-reset:
    a retransmit loop serves ONE request and dies with it).

    The client's request retransmitter used a fixed interval — under a
    lossy or partitioned network every unresolved pipelined request then
    re-broadcast in the same tick, and the whole fleet of clients
    re-synchronized on the heal.  This ladder starts at ``start_s``,
    doubles to ``cap_s`` (default ``8 * start_s``), and jitters each
    interval like :class:`ReconnectBackoff`."""

    def __init__(
        self,
        start_s: float,
        cap_s: Optional[float] = None,
        factor: float = 2.0,
        jitter_frac: float = 0.25,
        rng: Optional[random.Random] = None,
    ):
        if start_s <= 0:
            raise ValueError("retransmit start_s must be positive")
        self._start = start_s
        self._cap = cap_s if cap_s is not None else 8.0 * start_s
        self._factor = factor
        self._delay = start_s
        self._jitter = max(0.0, min(jitter_frac, 1.0))
        self._rng = rng if rng is not None else random.Random()

    def next_delay(self) -> float:
        """The wait before the next retransmission.  Advances the ladder."""
        delay = self._delay
        self._delay = min(self._delay * self._factor, self._cap)
        if self._jitter:
            delay *= 1.0 + self._jitter * (2.0 * self._rng.random() - 1.0)
        return min(delay, self._cap)
