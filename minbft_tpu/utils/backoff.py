"""Reconnect backoff policy shared by the peer and client redial loops.

Both `core.message_handling.run_peer_connection` and
`client.Client._run_connection` redial dropped streams (the reference
instead relies on operators restarting peers, core/message-handling.go:
316-350 HELLO replay handles only the receiving side).  The ladder lives
here once so the two loops cannot drift apart.
"""

from __future__ import annotations


class ReconnectBackoff:
    """Exponential redial ladder with a lived-connection reset.

    A connection that survived longer than ``lived_reset_s`` was healthy
    (not a crash-looping peer whose replay counts as liveness every
    attempt), so the next failure restarts the ladder at ``start_s``.
    """

    def __init__(
        self,
        start_s: float = 0.2,
        cap_s: float = 10.0,
        lived_reset_s: float = 5.0,
        factor: float = 2.0,
    ):
        self._start = start_s
        self._cap = cap_s
        self._lived = lived_reset_s
        self._factor = factor
        self._delay = start_s

    def next_delay(self, attempt_lived_s: float) -> float:
        """Delay before the next dial, given how long the last attempt
        lived.  Advances the ladder."""
        if attempt_lived_s > self._lived:
            self._delay = self._start
        delay = self._delay
        self._delay = min(self._delay * self._factor, self._cap)
        return delay
