"""Event-loop policy selection: optional uvloop for the hot loops.

The batch-ingest runtime moves decode/verify off the per-message task
path, which leaves asyncio's own per-task/per-callback bookkeeping as a
visible cost on the 1-core bench hosts.  uvloop (libuv's loop behind the
asyncio API) cuts exactly that constant — when it is installed (the
``perf`` extra in pyproject.toml) and the operator opts in.

Knob: ``MINBFT_UVLOOP``

- unset or ``auto`` — use uvloop when importable, silently fall back to
  the stdlib loop when not (the bare image does not ship it);
- ``1/true/yes`` — require it: a missing install logs a warning and
  falls back (never crashes a replica over a perf knob);
- ``0/false/no`` — stdlib loop, even when uvloop is installed.

Call :func:`maybe_enable_uvloop` BEFORE ``asyncio.run`` — it installs
the event-loop policy, which only affects loops created afterwards.
``peer run`` and bench.py both do; tests exercise both loops via the
same knob (tests/conftest.py, CI's uvloop step).
"""

from __future__ import annotations

import logging
import os

UVLOOP_ENV = "MINBFT_UVLOOP"


def uvloop_requested() -> "bool | None":
    """Tri-state read of MINBFT_UVLOOP: True (required), False (off),
    None (auto — use when available)."""
    val = os.environ.get(UVLOOP_ENV, "").strip().lower()
    if val in ("", "auto"):
        return None
    if val in ("0", "false", "no"):
        return False
    return True


def maybe_enable_uvloop() -> bool:
    """Install the uvloop event-loop policy per MINBFT_UVLOOP; returns
    True when uvloop will drive subsequently-created loops."""
    want = uvloop_requested()
    if want is False:
        return False
    try:
        import uvloop
    except ImportError:
        if want:  # explicitly required but absent: say so, don't crash
            logging.getLogger("minbft.loop").warning(
                "MINBFT_UVLOOP=1 but uvloop is not installed "
                "(pip install 'minbft_tpu[perf]'): using the stdlib loop"
            )
        return False
    import asyncio

    asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
    return True
