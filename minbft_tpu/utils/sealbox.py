"""Encrypted-at-rest sealing for key material.

The reference seals the USIG private key to the enclave identity with
``sgx_seal_data`` (reference usig/sgx/enclave/usig.c:107-116): a stolen
keys.yaml discloses nothing.  Without SGX the honest analogue is
symmetric encryption under an **operator-supplied secret**: AES-256-GCM
with a PBKDF2-HMAC-SHA256 key, random salt and nonce per use.

The secret is sourced from the environment (never stored in the repo or
the keystore):

- ``MINBFT_SEAL_SECRET``       — the secret itself (for dev/test), or
- ``MINBFT_SEAL_SECRET_FILE``  — path to a file holding it (deployment:
  mount a secret file; trailing whitespace is stripped).

With neither set, sealing degrades to the round-3 behavior (plaintext
fields, 0600 file permissions as the only protection) so existing
un-sealed deployments keep working; the keystore records whether a file
was written sealed and refuses to silently "open" a sealed file without
the secret.
"""

from __future__ import annotations

import hashlib
import os
import secrets
from typing import Optional

KDF = "pbkdf2-sha256"
ITERATIONS = 60_000  # one derivation per keystore FILE, not per field
SALT_LEN = 16
NONCE_LEN = 12


class SealError(Exception):
    pass


def seal_secret(env=None) -> Optional[bytes]:
    """The operator's sealing secret, or None when sealing is not
    configured (see module docstring)."""
    if env is None:
        env = os.environ
    v = env.get("MINBFT_SEAL_SECRET")
    if v:
        return v.encode()
    p = env.get("MINBFT_SEAL_SECRET_FILE")
    if p:
        with open(p, "rb") as fh:
            data = fh.read().strip()
        if not data:
            raise SealError(f"seal secret file {p!r} is empty")
        return data
    return None


def derive_key(secret: bytes, salt: bytes, iterations: int = ITERATIONS) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", secret, salt, iterations, dklen=32)


def _aesgcm(key: bytes):
    """AESGCM gated behind actual use: sealing is an OPT-IN feature (no
    MINBFT_SEAL_SECRET -> plaintext fields, 0600 perms), and the bare
    jax_graft image ships without the cryptography package — importing it
    at module load would take the whole keystore down for unsealed
    deployments too."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError as e:
        raise SealError(
            "keystore sealing requires the 'cryptography' package "
            "(unset MINBFT_SEAL_SECRET / _FILE to run unsealed)"
        ) from e
    return AESGCM(key)


def box(plain: bytes, key: bytes) -> bytes:
    """nonce(12) || AES-256-GCM(ciphertext || tag16)."""
    nonce = secrets.token_bytes(NONCE_LEN)
    return nonce + _aesgcm(key).encrypt(nonce, plain, b"")


def unbox(blob: bytes, key: bytes) -> bytes:
    if len(blob) < NONCE_LEN + 16:
        raise SealError("sealed blob too short")
    try:
        return _aesgcm(key).decrypt(blob[:NONCE_LEN], blob[NONCE_LEN:], b"")
    except SealError:
        raise
    except Exception as e:
        raise SealError(
            "sealed blob failed to decrypt (wrong secret or corrupted data)"
        ) from e
