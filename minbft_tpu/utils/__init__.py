"""Shared utilities: host-side crypto reference, logging, timing counters."""
