"""Protocol-level metrics (the observability the reference lacks — SURVEY.md
§5 notes its only instrumentation is leveled logging, while this build's
north star is a throughput number, so counters are first-class here).

Design: plain counters + a fixed-size latency reservoir, updated inline from
the asyncio pipelines (single event loop — no locks needed), snapshot-read
by benchmarks/operators.  The batch engine keeps its own
:class:`minbft_tpu.parallel.engine.VerifyStats`; this module covers the
protocol layer above it.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict


@dataclasses.dataclass
class LatencyReservoir:
    """Bounded sample of durations (seconds) with streaming count/sum."""

    capacity: int = 2048
    count: int = 0
    total_s: float = 0.0

    def __post_init__(self):
        import random

        self._samples: list = []
        # Sorted view of _samples, built lazily on the first percentile
        # call and reused until the next observe invalidates it: metric
        # snapshots ask for several percentiles back-to-back, and
        # re-sorting the full 2048-sample reservoir for each one made
        # every snapshot O(k · n log n) for no reason.
        self._sorted: "list | None" = None
        # Fixed seed: percentiles are statistics, but reproducible runs
        # help debugging.
        self._rng = random.Random(0x9E3779B97F4A7C15)

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self._sorted = None  # invalidate the cached sorted view
        if len(self._samples) < self.capacity:
            self._samples.append(seconds)
        else:
            # Algorithm R reservoir sampling: every observation ends up in
            # the sample with equal probability capacity/count, so a
            # long-run p99 reflects the whole run (a round-robin overwrite
            # would be recent-biased — the last `capacity` events only).
            j = self._rng.randrange(self.count)
            if j < self.capacity:
                self._samples[j] = seconds

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        s = self._sorted
        if s is None:
            s = self._sorted = sorted(self._samples)
        # nearest-rank: smallest value with at least q% of samples <= it.
        # Round away binary-float fuzz first (q=55, n=100 would otherwise
        # compute ceil(55.000000000000014) = 56).
        idx = max(0, math.ceil(round(q / 100.0 * len(s), 9)) - 1)
        return s[min(idx, len(s) - 1)]


class ReplicaMetrics:
    """Counters for one replica's protocol activity.

    Counter names (stable API for benchmarks/operators):

    - ``requests_received`` / ``requests_executed``
    - ``prepares_sent`` / ``prepares_accepted``
    - ``commits_sent`` / ``commitments_counted``
    - ``messages_handled`` / ``messages_dropped``
    - ``timeouts_request`` / ``timeouts_prepare``
    """

    def __init__(self, group=None):
        from ..obs.hist import Log2CountHistogram, Log2Histogram

        # Consensus-group id (multi-group runtime, minbft_tpu/groups):
        # None for an ungrouped replica.  Pure labeling — the Prometheus
        # exposition adds a ``group`` label and aggregate() callers can
        # keep per-group snapshots separable.
        self.group = group
        self.counters: Dict[str, int] = {}
        self.execute_latency = LatencyReservoir()
        # Streaming log2 histogram next to the reservoir (obs/hist.py):
        # mergeable across replicas and scrape-safe, it feeds the
        # Prometheus exposition; the reservoir keeps exact samples for
        # the snapshot()/bench percentiles.
        self.execute_hist = Log2Histogram()
        # Bundle-ingest fill distribution: one observation per ingest
        # tick, value = decoded frames in that tick's bundle (log2
        # buckets, mergeable, scraped as minbft_ingest_bundle_frames).
        # The companion counters (ingest_ticks / ingest_frames) ride the
        # ordinary counter map so snapshot()/aggregate() carry them.
        self.ingest_hist = Log2CountHistogram()
        # Event-loop scheduling lag (obs/looplag.py samples into this
        # from the replica's loop): GIL/loop saturation, scraped as
        # minbft_eventloop_lag_seconds and carried in trace dumps for
        # the critical-path loop_lag segment.
        self.loop_lag = Log2Histogram()
        self._started = time.monotonic()
        # Health-monitor state (ISSUE 14): monotonic stamps of the last
        # executed request and the last handled message, plus the
        # replica's current view.  A commit stall is "messages keep
        # arriving but nothing has executed for > T" — computable from
        # these two stamps by any stateless scrape, no detector thread.
        self.last_executed_mono = 0.0
        self.last_message_mono = 0.0
        self.current_view = 0
        # Admission-control state (ISSUE 15): the bundle ingestor's rx
        # queue depth/bound stamped per tick plus the high-water mark —
        # the "is the replica's inbound path saturating" gauges that back
        # the minbft_admission_* exposition and the BUSY retry-after
        # scaling.  The companion counters (admission_shed /
        # admission_busy_sent / admission_busy_suppressed) ride inc().
        self.admission_rx_depth = 0
        self.admission_rx_bound = 0
        self.admission_rx_peak = 0

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by
        # Stall-detector stamps inline with the two counters that define
        # progress (one string compare each on the hot path; the obs
        # overhead A/B test bounds the cost).
        if name == "requests_executed":
            self.last_executed_mono = time.monotonic()
        elif name == "messages_handled":
            self.last_message_mono = time.monotonic()

    def note_view(self, view: int) -> None:
        """Record the view this replica currently operates in (called
        from the new-view apply path; scraped as minbft_health_view)."""
        self.current_view = view

    def stalled(self, after_s: float = 30.0) -> bool:
        """Commit-stall detector: True when messages arrived more
        recently than the last execution AND nothing has executed for
        ``after_s`` — traffic without progress.  An idle replica (no
        traffic either) is healthy, not stalled."""
        if self.last_message_mono <= self.last_executed_mono:
            return False
        ref = self.last_executed_mono or self._started
        return time.monotonic() - ref > after_s

    def observe_execute(self, seconds: float) -> None:
        self.execute_latency.observe(seconds)
        self.execute_hist.observe(seconds)

    def note_admission_rx(self, depth: int, bound: int) -> None:
        """Stamp the ingest rx queue's occupancy (called once per ingest
        tick; the peak is the PR 9-style high-water mark the overload
        acceptance test asserts bounded)."""
        self.admission_rx_depth = depth
        self.admission_rx_bound = bound
        if depth > self.admission_rx_peak:
            self.admission_rx_peak = depth

    def admission_rx_saturation(self) -> float:
        """Last-stamped rx fill fraction in [0, 1]."""
        if self.admission_rx_bound <= 0:
            return 0.0
        return min(1.0, self.admission_rx_depth / self.admission_rx_bound)

    def observe_ingest(self, n_frames: int) -> None:
        """One bundle-ingest tick that decoded ``n_frames`` flat frames."""
        self.counters["ingest_ticks"] = self.counters.get("ingest_ticks", 0) + 1
        self.counters["ingest_frames"] = (
            self.counters.get("ingest_frames", 0) + n_frames
        )
        self.ingest_hist.observe_count(n_frames)

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._started

    def executed_per_sec(self) -> float:
        up = self.uptime_s
        return self.counters.get("requests_executed", 0) / up if up > 0 else 0.0

    def snapshot(self) -> dict:
        """Point-in-time view for logs / bench extras."""
        return {
            **self.counters,
            "uptime_s": round(self.uptime_s, 3),
            "execute_latency_mean_ms": round(self.execute_latency.mean_s * 1e3, 3),
            "execute_latency_p50_ms": round(
                self.execute_latency.percentile(50) * 1e3, 3
            ),
            "execute_latency_p99_ms": round(
                self.execute_latency.percentile(99) * 1e3, 3
            ),
        }


def aggregate(snapshots) -> dict:
    """Sum counter snapshots across replicas (latency fields are averaged)."""
    out: dict = {}
    n = 0
    for snap in snapshots:
        n += 1
        for k, v in snap.items():
            out[k] = out.get(k, 0) + v
    if n:
        for k in list(out):
            if k.startswith("execute_latency") or k == "uptime_s":
                out[k] = round(out[k] / n, 3)
    return out
