"""Port helpers shared by the bench harness and the process-cluster tests."""

from __future__ import annotations

import socket
import time


def free_base_port(count: int) -> int:
    """Find ``count`` consecutive free ports (probes close just before
    use — imperfect, but beats a fixed port colliding with a prior run)."""
    while True:
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            base = probe.getsockname()[1]
        if base + count < 65535:
            socks = []
            try:
                for i in range(count):
                    s = socket.socket()
                    socks.append(s)  # append first so it always gets closed
                    s.bind(("127.0.0.1", base + i))
                return base
            except OSError:
                continue
            finally:
                for s in socks:
                    s.close()


def wait_ports(ports, timeout: float = 180.0) -> bool:
    """Poll until every port accepts a connection (or timeout)."""
    deadline = time.time() + timeout
    pending = set(ports)
    while pending and time.time() < deadline:
        for port in list(pending):
            with socket.socket() as s:
                s.settimeout(0.2)
                try:
                    s.connect(("127.0.0.1", port))
                    pending.discard(port)
                except OSError:
                    pass
        if pending:
            time.sleep(0.3)
    return not pending
