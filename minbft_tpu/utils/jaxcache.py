"""Persistent JAX compilation cache, keyed to the kernel source tree.

The crypto kernels are compile-dominated on cold processes (a block-mode
ECDSA bucket is ~30-40s on TPU, minutes on CPU): every production entry
point (bench.py, ``peer run`` / ``peer bench``) should load yesterday's
executables instead of recompiling them.  JAX's cache is already keyed by
HLO, so correctness never depends on the directory key — but keying the
directory to a hash of the kernel sources (ops/ + parallel/) keeps one
tree's artifacts from unboundedly accreting into another's directory and
makes "did this run hit the cache?" a countable question: entry counts
before/after a run (``entry_count``) show near-zero new compiles on a
warm second run (the ``*_compile_s`` keys of BENCH_extras corroborate).
"""

from __future__ import annotations

import hashlib
import os

# Source roots whose content defines the cache key: everything that can
# change emitted HLO lives here (kernels, lowering modes, sharding).
_KERNEL_ROOTS = ("ops", "parallel")


def tree_key() -> str:
    """Short content hash of the kernel source tree."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for root in _KERNEL_ROOTS:
        base = os.path.join(pkg, root)
        for dirpath, _dirs, files in sorted(os.walk(base)):
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                h.update(name.encode())
                # noqa: AH102 - one-time startup hash of the kernel tree
                with open(path, "rb") as fh:
                    h.update(fh.read())
    return h.hexdigest()[:16]


def enable_compilation_cache(
    base_dir: str | None = None, min_compile_secs: int = 5
) -> str:
    """Point ``jax_compilation_cache_dir`` at a tree-keyed directory and
    return that directory.  Call before the first kernel compile (import
    time is fine — this only sets config, it never initializes a
    backend).  Override the root with MINBFT_JAX_CACHE_DIR; disable
    entirely with MINBFT_JAX_CACHE=0."""
    if os.environ.get("MINBFT_JAX_CACHE", "1") == "0":
        return ""
    import jax

    root = (
        base_dir
        or os.environ.get("MINBFT_JAX_CACHE_DIR")
        or os.path.expanduser("~/.cache/minbft_jax_cache")
    )
    cache_dir = os.path.join(root, tree_key())
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    return cache_dir


def entry_count(cache_dir: str) -> int:
    """Number of cached executables in ``cache_dir`` (0 when absent) —
    recorded before/after a bench run so the artifact proves whether the
    kernels compiled or loaded."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0
    return sum(1 for name in os.listdir(cache_dir) if not name.startswith("."))
