"""Host-side elliptic-curve crypto.

P-256 and Ed25519: key generation, signing (RFC 6979 deterministic nonces
for ECDSA), and a reference verifier.  Three jobs:

1. **Signing** — replicas/clients sign with host code (one signature per
   outgoing message; generation is inherently serial per-key because the
   USIG counter must increment atomically, reference usig/sgx/enclave/
   usig.c:66-69).
2. **Differential testing** — the TPU kernels (:mod:`minbft_tpu.ops.p256`,
   :mod:`minbft_tpu.ops.ed25519`) are tested bit-for-bit against the
   pure-Python functions here on random and adversarial inputs.
3. **Key generation** for the keystore/keytool (reference
   sample/authentication/keymanager.go:404-450).

Two tiers:

- A **pure-Python big-int implementation** (always available, standard
  library only) — the semantic reference the TPU kernels are diff-tested
  against, and the fallback everywhere else.
- An **OpenSSL-backed fast path** through the ``cryptography`` package for
  the hot host-side operations (sign/verify/public-key derivation), ~500x
  the pure-Python speed.  ECDSA signing via OpenSSL uses random nonces
  rather than RFC 6979 — both are valid ECDSA; use ``ecdsa_sign_py`` where
  deterministic output matters.  Ed25519 verification is **strict
  cofactorless** on every backend — sB == R + kA (the RFC 8032 §5.1.7
  group equation without the 8× multiplication), which is what OpenSSL
  implements, what the pure-Python oracle implements, and what the batch
  kernel (:mod:`minbft_tpu.ops.ed25519`) mirrors bit-for-bit (see the
  semantics note above ``ed25519_verify_py``).  The agreement matters for
  BFT: a cofactored verifier disagrees with a strict one on adversarial
  small-order inputs, and mixed acceptance semantics across replicas
  would let one crafted signature split the cluster.
"""

from __future__ import annotations

import functools
import hashlib
import hmac
import secrets
from typing import Tuple

try:  # OpenSSL fast path (baked into the image via `cryptography`)
    from cryptography.exceptions import InvalidSignature as _InvalidSignature
    from cryptography.hazmat.primitives import hashes as _ossl_hashes
    from cryptography.hazmat.primitives.asymmetric import ec as _ossl_ec
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _ossl_ed
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed as _Prehashed,
    )
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature as _decode_dss,
    )
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature as _encode_dss,
    )

    _HAVE_OSSL = True
except Exception:  # pragma: no cover - image always has cryptography
    _HAVE_OSSL = False

# ---------------------------------------------------------------------------
# NIST P-256.

P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

# Affine points as (x, y); None is the identity.
PointA = Tuple[int, int]


def _inv(x: int, m: int) -> int:
    # noqa: AH104 - deliberate host-crypto fallback; the hot path batches off-loop
    return pow(x, -1, m)


def point_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        return point_double(p)
    lam = ((y2 - y1) * _inv(x2 - x1, P)) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def point_double(p):
    if p is None:
        return None
    x1, y1 = p
    if y1 == 0:
        return None
    lam = ((3 * x1 * x1 + A) * _inv(2 * y1, P)) % P
    x3 = (lam * lam - 2 * x1) % P
    return x3, (lam * (x1 - x3) - y1) % P


def scalar_mult(k: int, p: PointA):
    """Double-and-add (host side is not secret-latency sensitive for tests;
    production signing uses the native module)."""
    acc = None
    addend = p
    while k:
        if k & 1:
            acc = point_add(acc, addend)
        addend = point_double(addend)
        k >>= 1
    return acc


if _HAVE_OSSL:
    _OSSL_CURVE = _ossl_ec.SECP256R1()
    _OSSL_SHA256 = _ossl_ec.ECDSA(_Prehashed(_ossl_hashes.SHA256()))

    @functools.lru_cache(maxsize=4096)
    def _ossl_priv(d: int):
        return _ossl_ec.derive_private_key(d, _OSSL_CURVE)

    @functools.lru_cache(maxsize=4096)
    def _ossl_pub(x: int, y: int):
        return _ossl_ec.EllipticCurvePublicNumbers(x, y, _OSSL_CURVE).public_key()


def keygen(rng=None) -> Tuple[int, PointA]:
    """-> (private scalar d, public point Q = d*G)."""
    d = (rng or secrets).randbelow(N - 1) + 1
    if _HAVE_OSSL:
        nums = _ossl_priv(d).public_key().public_numbers()
        return d, (nums.x, nums.y)
    return d, scalar_mult(d, (GX, GY))


def _rfc6979_k(d: int, z: int, order: int = N) -> int:
    """RFC 6979 deterministic nonce (HMAC-SHA256 DRBG)."""
    qlen = 32
    x = d.to_bytes(qlen, "big")
    h1 = (z % order).to_bytes(qlen, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < order:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def ecdsa_sign_py(d: int, digest: bytes) -> Tuple[int, int]:
    """Pure-Python ECDSA-P256 over a 32-byte digest -> (r, s).
    Deterministic (RFC 6979)."""
    z = int.from_bytes(digest[:32], "big") % N
    while True:
        k = _rfc6979_k(d, z)
        x1, _ = scalar_mult(k, (GX, GY))
        r = x1 % N
        if r == 0:
            z = (z + 1) % N  # astronomically unlikely; reroll deterministically
            continue
        s = (_inv(k, N) * (z + r * d)) % N
        if s == 0:
            z = (z + 1) % N
            continue
        return r, s


def ecdsa_sign(d: int, digest: bytes) -> Tuple[int, int]:
    """ECDSA-P256 over a 32-byte digest -> (r, s).  OpenSSL when available
    (random nonce), pure Python otherwise (RFC 6979)."""
    if _HAVE_OSSL:
        der = _ossl_priv(d).sign(digest[:32], _OSSL_SHA256)
        return _decode_dss(der)
    return ecdsa_sign_py(d, digest)


def ecdsa_verify_py(q: PointA, digest: bytes, sig: Tuple[int, int]) -> bool:
    """Pure-Python reference verifier — the oracle for the TPU kernel."""
    r, s = sig
    if not (0 < r < N and 0 < s < N):
        return False
    z = int.from_bytes(digest[:32], "big") % N
    w = _inv(s, N)
    u1 = (z * w) % N
    u2 = (r * w) % N
    pt = point_add(scalar_mult(u1, (GX, GY)), scalar_mult(u2, q))
    if pt is None:
        return False
    return pt[0] % N == r


def ecdsa_verify(q: PointA, digest: bytes, sig: Tuple[int, int]) -> bool:
    """ECDSA-P256 verify.  OpenSSL when available, pure Python otherwise
    (identical accept/reject behavior for on-curve keys; OpenSSL
    additionally rejects off-curve public keys at load)."""
    r, s = sig
    if not (0 < r < N and 0 < s < N):
        return False
    if _HAVE_OSSL:
        try:
            pub = _ossl_pub(*q)
        except ValueError:
            return False  # off-curve / out-of-range public key
        try:
            pub.verify(_encode_dss(r, s), digest[:32], _OSSL_SHA256)
            return True
        except _InvalidSignature:
            return False
    return ecdsa_verify_py(q, digest, sig)


# ---------------------------------------------------------------------------
# Wider NIST curves — host path only.  The reference's ECDSA keyspec
# accepts DER keys for P-224 through P-521 (reference
# sample/authentication/keymanager.go:169-241); this build serves P-384 and
# P-521 through OpenSSL with raw fixed-width encodings.  The TPU kernels
# stay P-256-only (the hot path); these curves never touch the device.

_NIST_CURVES: dict = {}
if _HAVE_OSSL:
    _NIST_CURVES = {
        "p384": (_ossl_ec.SECP384R1(), _ossl_hashes.SHA384(), 48),
        "p521": (_ossl_ec.SECP521R1(), _ossl_hashes.SHA512(), 66),
    }


def _nist_params(curve: str):
    params = _NIST_CURVES.get(curve)
    if params is None:
        raise ValueError(
            f"unsupported NIST curve {curve!r}"
            + ("" if _HAVE_OSSL else " (cryptography/OpenSSL unavailable)")
        )
    return params


def nist_scalar_bytes(curve: str) -> int:
    """Fixed scalar/coordinate width in bytes for ``curve``."""
    return _nist_params(curve)[2]


def nist_keygen(curve: str) -> Tuple[bytes, bytes]:
    """-> (private scalar bytes, public x||y bytes), fixed width."""
    c, _, nb = _nist_params(curve)
    nums = _ossl_ec.generate_private_key(c).private_numbers()
    pub = nums.public_numbers
    return (
        nums.private_value.to_bytes(nb, "big"),
        pub.x.to_bytes(nb, "big") + pub.y.to_bytes(nb, "big"),
    )


def nist_sign(curve: str, priv: bytes, msg: bytes) -> bytes:
    """ECDSA over the curve's matched hash -> raw r||s (fixed width)."""
    c, h, nb = _nist_params(curve)
    key = _ossl_ec.derive_private_key(int.from_bytes(priv, "big"), c)
    r, s = _decode_dss(key.sign(msg, _ossl_ec.ECDSA(h)))
    return r.to_bytes(nb, "big") + s.to_bytes(nb, "big")


def nist_verify(curve: str, pub: bytes, msg: bytes, sig: bytes) -> bool:
    c, h, nb = _nist_params(curve)
    if len(sig) != 2 * nb or len(pub) != 2 * nb:
        return False
    try:
        key = _ossl_ec.EllipticCurvePublicNumbers(
            int.from_bytes(pub[:nb], "big"),
            int.from_bytes(pub[nb:], "big"),
            c,
        ).public_key()
    except ValueError:
        return False  # off-curve / out-of-range public key
    r = int.from_bytes(sig[:nb], "big")
    s = int.from_bytes(sig[nb:], "big")
    try:
        key.verify(_encode_dss(r, s), msg, _ossl_ec.ECDSA(h))
        return True
    except _InvalidSignature:
        return False


# ---------------------------------------------------------------------------
# Ed25519 (RFC 8032). Used by the Ed25519 authenticator (BASELINE config[4]).

ED_P = 2**255 - 19
ED_L = 2**252 + 27742317777372353535851937790883648493
ED_D = (-121665 * pow(121666, -1, ED_P)) % ED_P
ED_BY = (4 * pow(5, -1, ED_P)) % ED_P


def _ed_recover_x(y: int, sign: int):
    xx = (y * y - 1) * pow(ED_D * y * y + 1, -1, ED_P) % ED_P
    x = pow(xx, (ED_P + 3) // 8, ED_P)
    if (x * x - xx) % ED_P != 0:
        x = x * pow(2, (ED_P - 1) // 4, ED_P) % ED_P
    if (x * x - xx) % ED_P != 0:
        return None
    if x == 0 and sign == 1:
        # RFC 8032 §5.1.3 step 4: x = 0 with the sign bit set is a
        # non-canonical encoding and must be rejected.
        return None
    if x & 1 != sign:
        x = ED_P - x
    return x


ED_BX = _ed_recover_x(ED_BY, 0)

# Extended coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.
EdPoint = Tuple[int, int, int, int]
ED_IDENT: EdPoint = (0, 1, 1, 0)
ED_BASE: EdPoint = (ED_BX, ED_BY, 1, ED_BX * ED_BY % ED_P)


def ed_add(p: EdPoint, q: EdPoint) -> EdPoint:
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % ED_P
    b = (y1 + x1) * (y2 + x2) % ED_P
    c = 2 * t1 * t2 * ED_D % ED_P
    d = 2 * z1 * z2 % ED_P
    e, f, g, h = b - a, d - c, d + c, b + a
    return e * f % ED_P, g * h % ED_P, f * g % ED_P, e * h % ED_P


def ed_scalar_mult(k: int, p: EdPoint) -> EdPoint:
    acc = ED_IDENT
    while k:
        if k & 1:
            acc = ed_add(acc, p)
        p = ed_add(p, p)
        k >>= 1
    return acc


def ed_compress(p: EdPoint) -> bytes:
    x, y, z, _ = p
    # noqa: AH104 - host-crypto fallback; keygen runs once at test-net setup
    zi = pow(z, -1, ED_P)
    x, y = x * zi % ED_P, y * zi % ED_P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def ed_decompress(data: bytes):
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= ED_P:
        return None
    x = _ed_recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % ED_P)


if _HAVE_OSSL:

    @functools.lru_cache(maxsize=4096)
    def _ossl_ed_priv(seed: bytes):
        return _ossl_ed.Ed25519PrivateKey.from_private_bytes(seed)


def ed25519_keygen(seed: bytes | None = None) -> Tuple[bytes, bytes]:
    """-> (seed32, public key 32B compressed)."""
    seed = seed if seed is not None else secrets.token_bytes(32)
    if _HAVE_OSSL:
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        pub = _ossl_ed_priv(seed).public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw
        )
        return seed, pub
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return seed, ed_compress(ed_scalar_mult(a, ED_BASE))


def ed25519_sign_py(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    pub = ed_compress(ed_scalar_mult(a, ED_BASE))
    r = int.from_bytes(hashlib.sha512(h[32:] + msg).digest(), "little") % ED_L
    rp = ed_compress(ed_scalar_mult(r, ED_BASE))
    k = int.from_bytes(hashlib.sha512(rp + pub + msg).digest(), "little") % ED_L
    s = (r + k * a) % ED_L
    return rp + s.to_bytes(32, "little")


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 Ed25519 signing (deterministic — OpenSSL and the pure
    implementation produce identical signatures)."""
    if _HAVE_OSSL:
        return _ossl_ed_priv(seed).sign(msg)
    return ed25519_sign_py(seed, msg)


# Verification semantics: **cofactorless, strict** — sB == R + kA checked
# as compress(sB - kA) == R-bytes.  This is what OpenSSL implements, and
# the byte comparison enforces canonical encodings for free.  Honest
# signatures verify identically under the cofactored RFC 8032 equation;
# the variants differ only on crafted mixed-order inputs, where strict is
# the *more* conservative choice.  Every verifier in this build — OpenSSL,
# the pure-Python fallback below, and the TPU kernel
# (minbft_tpu/ops/ed25519.py) — agrees on this semantics, which matters
# for BFT: replicas must not split on a crafted signature's validity.
# The strict form is also what makes the TPU path fast: the device
# compares its computed point against the signature's R *bytes*, so the
# host never decompresses R (a per-signature big-int sqrt that dominated
# the n=31 benchmark).

ed_decompress_cached = functools.lru_cache(maxsize=4096)(ed_decompress)


def ed25519_verify_py(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Pure-Python strict verifier (differential reference for the kernel)."""
    if len(sig) != 64:
        return False
    ap = ed_decompress_cached(pub)
    if ap is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= ED_L:
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % ED_L
    x, y, z, t = ap
    neg_a = (ED_P - x if x else 0, y, z, (ED_P - t) % ED_P)
    res = ed_add(ed_scalar_mult(s, ED_BASE), ed_scalar_mult(k, neg_a))
    return ed_compress(res) == sig[:32]


if _HAVE_OSSL:

    @functools.lru_cache(maxsize=4096)
    def _ossl_ed_pub(pub: bytes):
        return _ossl_ed.Ed25519PublicKey.from_public_bytes(pub)


def ed25519_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Ed25519 verification (strict cofactorless — see the semantics note
    above).

    The public key is gated through ``ed_decompress`` on every path:
    OpenSSL accepts some non-canonical key encodings (e.g. y >= p) that
    the pure-Python and TPU verifiers reject — without this gate a
    Byzantine principal could register such a key and split replicas by
    which verifier backend they run."""
    if ed_decompress_cached(pub) is None:
        return False
    if _HAVE_OSSL:
        try:
            _ossl_ed_pub(pub).verify(sig, msg)
            return True
        except Exception:
            return False
    return ed25519_verify_py(pub, msg, sig)
