"""minbft_tpu — a TPU-native BFT consensus framework.

A from-scratch rebuild of the capabilities of MinBFT (reference:
hyperledger-labs/minbft, a Go + SGX-C implementation) designed TPU-first:

- The per-message cryptographic verification hot path (client signatures,
  USIG UI certificates on PREPARE/COMMIT) is a **batched, data-parallel XLA
  kernel** (``minbft_tpu.ops``) dispatched through an asyncio batching engine
  (``minbft_tpu.parallel.engine``) instead of serial per-message CPU crypto.
- The protocol engine (``minbft_tpu.core``) is an asyncio re-design of the
  reference's goroutine/closure graph (reference core/message-handling.go),
  restructured so validation awaits one batched verify result per quorum
  instead of n serial verifies (reference core/commit.go:108-143).
- The trusted component (USIG) keeps the reference enclave's semantics
  (monotonic counter, epoch, increment-after-sign; reference
  usig/sgx/enclave/usig.c:36-76) with a C++ native implementation
  (``minbft_tpu/native``) plus a TPU batch verifier for UI certificates.

Layering mirrors the reference (SURVEY.md §1): messages / api / core /
client / sample, with the TPU compute stack in ops/parallel/models.
"""

__version__ = "0.1.0"
