"""Observability subsystem: the protocol flight recorder and the
cluster critical path.

Layers (ISSUEs 4 and 8; SURVEY.md §5 notes the reference's only
instrumentation is leveled logging):

- :mod:`~minbft_tpu.obs.trace` — per-request stage spans into
  preallocated ring buffers, with per-stage log2 histograms and the
  JSON trace dump (``MINBFT_TRACE_DUMP=path``) bench.py ingests;
- :mod:`~minbft_tpu.obs.hist` — fixed-bucket mergeable latency
  histograms (the streaming counterpart of the exact-but-unmergeable
  :class:`~minbft_tpu.utils.metrics.LatencyReservoir`), with negative
  durations counted, never silently clamped;
- :mod:`~minbft_tpu.obs.prom` — Prometheus text exposition served from
  an stdlib HTTP endpoint (``peer run --metrics-port`` / the
  ``peer metrics`` scrape subcommand, which can also merge several
  targets into one cluster aggregate);
- :mod:`~minbft_tpu.obs.clockalign` — NTP-free pairwise clock-offset
  estimation from the protocol's own matched send/recv span pairs;
- :mod:`~minbft_tpu.obs.critpath` — the cross-node trace merge: one
  causal timeline per committed request, with queue-wait and loop-lag
  attribution (perf/CRITICAL_PATH.md);
- :mod:`~minbft_tpu.obs.looplag` — event-loop scheduling-lag sampler
  (GIL/loop saturation as a first-class metric);
- :mod:`~minbft_tpu.obs.timeseries` — fixed-capacity per-interval
  counter-delta rings (the saturation timeline: shape-over-time, not
  just end-of-run means), mergeable like the histograms and dumped as
  ``{base}.ts.json`` next to the flight-recorder dumps;
- :mod:`~minbft_tpu.obs.ledger` — the device-utilization ledger: busy
  vs idle wall-seconds per engine queue, lanes classed useful /
  padding / memo-duplicate / host-fallback, and the multiplicative
  headroom decomposition against a calibrated per-backend ceiling
  (perf/UTILIZATION.md);
- :mod:`~minbft_tpu.obs.runinfo` — per-incarnation ``RUN_ID`` and the
  ``minbft_build_info`` attribution block every dump and exposition
  carries;
- :mod:`~minbft_tpu.obs.slo` — the latency-SLO engine: per-request
  finality budgets classified at commit-quorum time, multi-window
  error-budget burn rates over the telemetry rings, critpath breach
  attribution, and the breach-triggered forensic auto-dump
  (perf/SLO.md).

Nothing in this package is reachable from jitted code (enforced by the
``tools/analyze`` trace-purity pass), and with tracing disabled the
protocol pays one predicated attribute check per hook.
"""

from .hist import Log2Histogram
from .ledger import Decomposition, DeviceLedger, QueueWindow
from .prom import (
    MetricsServer,
    collect_faultnet,
    collect_replica,
    render_families,
    scrape,
)
from .slo import (
    BreachSpool,
    BudgetLedger,
    SLOPolicy,
    breach_report,
    build_bundle,
    burn_rates,
    register_slo_series,
    slo_enabled,
)
from .timeseries import (
    CounterSampler,
    IncarnationMismatch,
    TimeSeries,
    dump_timeseries,
    merge_timeseries_docs,
)
from .trace import (
    CLIENT_STAGES,
    REPLICA_STAGES,
    FlightRecorder,
    MTStageRing,
    StageRing,
    dump_recorder,
    load_dumps,
    stage_table,
    tracing_enabled,
)

__all__ = [
    "CLIENT_STAGES",
    "REPLICA_STAGES",
    "BreachSpool",
    "BudgetLedger",
    "CounterSampler",
    "Decomposition",
    "DeviceLedger",
    "FlightRecorder",
    "IncarnationMismatch",
    "Log2Histogram",
    "MTStageRing",
    "MetricsServer",
    "QueueWindow",
    "SLOPolicy",
    "StageRing",
    "TimeSeries",
    "breach_report",
    "build_bundle",
    "burn_rates",
    "collect_faultnet",
    "collect_replica",
    "dump_recorder",
    "dump_timeseries",
    "load_dumps",
    "merge_timeseries_docs",
    "register_slo_series",
    "render_families",
    "scrape",
    "slo_enabled",
    "stage_table",
    "tracing_enabled",
]
