"""Fixed-bucket log2 latency histograms.

The protocol's hot paths need percentiles that are cheap to record
(one array increment), mergeable across replicas/engines (bucket-wise
addition — a reservoir cannot be merged without re-weighting), and
bounded in memory regardless of run length.  The
:class:`minbft_tpu.utils.metrics.LatencyReservoir` keeps exact samples
for offline analysis; this histogram is the streaming counterpart the
flight recorder and the Prometheus exposition use.

Buckets are powers of two in MICROSECONDS: bucket ``i`` holds durations
``d`` with ``2**(i-1) < d_us <= 2**i`` (bucket 0 is ``<= 1us``).  64
buckets cover 1us..~585000 years, so nothing ever clips.  Relative
resolution is a factor of 2 — exactly the precision a "where does the
time go" attribution needs, and the reason merge is exact (identical
bucket edges everywhere, no re-binning).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

_N_BUCKETS = 64
_US = 1_000_000.0


class Log2Histogram:
    """Mergeable log2-bucket histogram of durations in seconds.

    Negative durations (clock weirdness: a monotonic source going
    backwards can only mean a broken pairing or a cross-clock subtraction
    that should have gone through :mod:`~minbft_tpu.obs.clockalign`) are
    COUNTED in ``negatives`` instead of silently clamped into bucket 0 —
    the count rides the dump/merge/Prometheus surfaces so the critpath
    merge can use it as a clock-sanity signal, and the percentile buckets
    stay unpolluted.
    """

    __slots__ = ("buckets", "count", "total_s", "negatives")

    def __init__(self, buckets: Optional[List[int]] = None,
                 count: int = 0, total_s: float = 0.0, negatives: int = 0):
        if buckets is None:
            buckets = [0] * _N_BUCKETS
        elif len(buckets) != _N_BUCKETS:
            raise ValueError(f"expected {_N_BUCKETS} buckets, got {len(buckets)}")
        self.buckets = buckets
        self.count = count
        self.total_s = total_s
        self.negatives = negatives

    def observe(self, seconds: float) -> None:
        if seconds < 0:
            self.negatives += 1
            return
        # Round UP to whole microseconds so a bucket's upper edge always
        # bounds its samples (1.2us must land above the <=1us bucket —
        # flooring would report percentiles BELOW the true value).
        us = -int(-seconds * _US // 1)
        # int.bit_length is the log2: bucket 0 <= 1us, bucket i covers
        # (2**(i-1), 2**i] us.
        idx = (us - 1).bit_length() if us > 1 else 0
        self.buckets[min(idx, _N_BUCKETS - 1)] += 1
        self.count += 1
        self.total_s += seconds

    def observe_ns(self, ns: int, n: int = 1) -> None:
        """Integer fast path for ring drains (timestamps in nanoseconds).
        ``n`` records the same duration n times at O(1) cost — the
        engine's per-batch service spans apply to every lane at once."""
        if ns < 0:
            self.negatives += n
            return
        us = -(-ns // 1000)  # ceil-divide: see observe()
        idx = (us - 1).bit_length() if us > 1 else 0
        self.buckets[min(idx, _N_BUCKETS - 1)] += n
        self.count += n
        self.total_s += ns * 1e-9 * n

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile in SECONDS, resolved to its bucket's
        upper edge (consistent with Prometheus's ``le`` semantics: the
        smallest bound at least q% of observations fall under)."""
        if not self.count:
            return 0.0
        rank = max(1, -(-int(q * self.count) // 100))  # ceil(q/100 * count)
        seen = 0
        for i, c in enumerate(self.buckets):
            seen += c
            if seen >= rank:
                return (1 << i) / _US
        return (1 << (_N_BUCKETS - 1)) / _US

    def merge(self, other: "Log2Histogram") -> "Log2Histogram":
        """Bucket-wise sum — exact, because every histogram shares the
        same fixed edges (the property reservoirs lack)."""
        self.count += other.count
        self.total_s += other.total_s
        self.negatives += other.negatives
        b, ob = self.buckets, other.buckets
        for i in range(_N_BUCKETS):
            b[i] += ob[i]
        return self

    @staticmethod
    def merged(hists: Iterable["Log2Histogram"]) -> "Log2Histogram":
        out = Log2Histogram()
        for h in hists:
            out.merge(h)
        return out

    # -- (de)serialization for the JSON trace dump -----------------------

    def to_dict(self) -> dict:
        # Sparse encoding: {bucket_index: count} — most of the 64 buckets
        # are empty for any one stage.  ``negatives`` only when nonzero
        # (dump compatibility both ways: old dumps simply lack the key).
        out = {
            "buckets": {str(i): c for i, c in enumerate(self.buckets) if c},
            "count": self.count,
            "total_s": self.total_s,
        }
        if self.negatives:
            out["negatives"] = self.negatives
        return out

    @staticmethod
    def from_dict(d: dict) -> "Log2Histogram":
        buckets = [0] * _N_BUCKETS
        for i, c in (d.get("buckets") or {}).items():
            buckets[int(i)] = int(c)
        return Log2Histogram(
            buckets, int(d.get("count", 0)), float(d.get("total_s", 0.0)),
            int(d.get("negatives", 0)),
        )

    def bucket_upper_bounds_s(self) -> List[float]:
        """Upper edge of each bucket in seconds (for Prometheus ``le``)."""
        return [(1 << i) / _US for i in range(_N_BUCKETS)]


class Log2CountHistogram(Log2Histogram):
    """Log2 histogram over a dimensionless COUNT axis (ingest bundle
    sizes) with the same storage, merge, and serialization as the
    duration base class.

    The ``_s``-suffixed members keep their names so the Prometheus
    renderer (obs/prom.py) works unchanged, but the axis is plain
    counts: ``observe_count(n)`` buckets by ceil-log2(n) (bucket i covers
    ``(2**(i-1), 2**i]`` items, same upper-edge convention as the base),
    ``total_s`` accumulates the raw counts (so ``_sum`` is total items
    and ``mean_s`` the mean bundle size), and the exposed ``le`` bounds
    are ``2**i`` items."""

    __slots__ = ()

    def observe_count(self, n: int) -> None:
        if n < 0:
            self.negatives += 1
            return
        idx = (n - 1).bit_length() if n > 1 else 0
        self.buckets[min(idx, _N_BUCKETS - 1)] += 1
        self.count += 1
        self.total_s += n

    @property
    def mean(self) -> float:
        """Mean bundle size (alias of the misleadingly-named mean_s)."""
        return self.mean_s

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile in ITEMS (bucket upper edge)."""
        if not self.count:
            return 0.0
        rank = max(1, -(-int(q * self.count) // 100))
        seen = 0
        for i, c in enumerate(self.buckets):
            seen += c
            if seen >= rank:
                return float(1 << i)
        return float(1 << (_N_BUCKETS - 1))

    def bucket_upper_bounds_s(self) -> List[float]:
        """Upper edge of each bucket in ITEMS (for Prometheus ``le``)."""
        return [float(1 << i) for i in range(_N_BUCKETS)]
