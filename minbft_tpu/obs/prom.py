"""Prometheus text exposition (format 0.0.4) over the stdlib — no
aiohttp, no client library: the endpoint is a daemon-thread
``http.server`` serving a render callback, and the render walks plain
counters/histograms.

Consistency model: the scrape thread reads ints the event loop (and the
engine's worker threads) are mutating.  Every exposed value is either a
GIL-atomic int/float store or a monotonic counter, so a scrape sees a
slightly stale but never torn value — the standard Prometheus contract
(scrapes are samples, not transactions).  Nothing here takes the event
loop's locks, so a slow scraper can never stall the protocol.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .hist import Log2Histogram

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# family = (name, type, help, [(labels, value)]) for counter/gauge;
# histogram families carry (labels, Log2Histogram) samples instead.
Family = Tuple[str, str, str, List[Tuple[Dict[str, str], object]]]


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, float):
        return repr(v)
    return str(v)


def render_families(families: Iterable[Family]) -> str:
    """Render metric families to Prometheus text format.

    Histogram samples with a nonzero ``negatives`` counter (clock
    weirdness — obs/hist.py) additionally emit a sibling
    ``{name}_negatives_total`` counter family: the count is part of the
    exposition, never silently dropped."""
    lines: List[str] = []
    for name, mtype, help_text, samples in families:
        if not samples:
            continue
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        if mtype == "histogram":
            neg_samples: List[Tuple[Dict[str, str], int]] = []
            for labels, hist in samples:
                assert isinstance(hist, Log2Histogram)
                bounds = hist.bucket_upper_bounds_s()
                # ONE snapshot of the bucket array, with count/+Inf
                # derived from it: reading live buckets and hist.count
                # separately could interleave with an observe() between
                # its two increments and emit a finite bucket above
                # +Inf — invalid per the histogram contract (le-series
                # must be monotone up to +Inf).
                buckets = list(hist.buckets)
                total = sum(buckets)
                cum = 0
                last_nonzero = -1
                for i, c in enumerate(buckets):
                    if c:
                        last_nonzero = i
                for i in range(last_nonzero + 1):
                    c = buckets[i]
                    cum += c
                    if c == 0 and i != last_nonzero:
                        continue  # empty buckets add no information
                    lb = dict(labels)
                    lb["le"] = repr(bounds[i])
                    lines.append(
                        f"{name}_bucket{_fmt_labels(lb)} {cum}"
                    )
                lb = dict(labels)
                lb["le"] = "+Inf"
                lines.append(f"{name}_bucket{_fmt_labels(lb)} {total}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} {_fmt_value(hist.total_s)}"
                )
                lines.append(f"{name}_count{_fmt_labels(labels)} {total}")
                neg = getattr(hist, "negatives", 0)
                if neg:
                    neg_samples.append((labels, neg))
            if neg_samples:
                lines.append(
                    f"# HELP {name}_negatives_total negative-duration "
                    "observations dropped from the histogram (clock sanity)"
                )
                lines.append(f"# TYPE {name}_negatives_total counter")
                for labels, neg in neg_samples:
                    lines.append(
                        f"{name}_negatives_total{_fmt_labels(labels)} {neg}"
                    )
        else:
            for labels, value in samples:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(value)}"
                )
    return "\n".join(lines) + "\n"


def collect_replica(
    metrics=None,
    recorder=None,
    engine=None,
    replica_id: Optional[int] = None,
    group: Optional[int] = None,
    timeseries=None,
    groups: Optional[int] = None,
    stall_after_s: float = 30.0,
    slo=None,
    slo_spool=None,
    recovery=None,
) -> List[Family]:
    """Build the metric families for one replica process.

    ``metrics`` is a :class:`minbft_tpu.utils.metrics.ReplicaMetrics`,
    ``recorder`` a :class:`minbft_tpu.obs.trace.FlightRecorder` (or
    None when tracing is off — the stage families simply vanish), and
    ``engine`` a :class:`minbft_tpu.parallel.BatchVerifier` (or None
    for ``--no-batch`` replicas).

    ``group`` labels every family with the consensus-group id (the
    multi-group runtime calls this once per group core; metrics that
    carry their own ``ReplicaMetrics.group`` stamp win when the caller
    passes none).  Merged scrapes stay group-separable: ``peer
    metrics``' cluster aggregate strips only the per-process ``replica``
    label, so the same group's series fold across replicas while
    distinct groups never merge.
    """
    if group is None and metrics is not None:
        group = getattr(metrics, "group", None)
    base = {} if replica_id is None else {"replica": str(replica_id)}
    if group is not None:
        base["group"] = str(group)
    fams: List[Family] = []
    if metrics is not None:
        # Incarnation attribution (ISSUE 14): which PROCESS produced
        # every series in this exposition.  Value is the constant 1 —
        # the information is the labels (the kube_state_metrics idiom),
        # so merged multi-target scrapes stay attributable per pid/rev.
        from . import runinfo

        info = runinfo.build_info(
            replica_id=replica_id, group=group, groups=groups
        )
        fams.append(
            (
                "minbft_build_info",
                "gauge",
                "process incarnation attribution (pid, run_id, backend, "
                "git rev); value is always 1",
                [({**base, **info}, 1)],
            )
        )
        # dict(...) snapshots the counter map once: the loop may insert
        # new counters mid-walk.
        for cname, v in sorted(dict(metrics.counters).items()):
            fams.append(
                (
                    f"minbft_{cname}_total",
                    "counter",
                    f"protocol counter {cname}",
                    [(base, v)],
                )
            )
        fams.append(
            (
                "minbft_uptime_seconds",
                "gauge",
                "seconds since the replica's metrics started",
                [(base, round(metrics.uptime_s, 3))],
            )
        )
        exec_hist = getattr(metrics, "execute_hist", None)
        if exec_hist is not None and exec_hist.count:
            fams.append(
                (
                    "minbft_execute_latency_seconds",
                    "histogram",
                    "request execution latency (deliver to the consumer)",
                    [(base, exec_hist)],
                )
            )
        ingest_hist = getattr(metrics, "ingest_hist", None)
        if ingest_hist is not None and ingest_hist.count:
            fams.append(
                (
                    "minbft_ingest_bundle_frames",
                    "histogram",
                    "frames decoded per ingest tick (le = bundle size in "
                    "frames, log2 buckets — the bundle-fill distribution)",
                    [(base, ingest_hist)],
                )
            )
        lag_hist = getattr(metrics, "loop_lag", None)
        if lag_hist is not None and (lag_hist.count or lag_hist.negatives):
            fams.append(
                (
                    "minbft_eventloop_lag_seconds",
                    "histogram",
                    "event-loop scheduling lag (scheduled-vs-actual wakeup "
                    "delta sampled by obs/looplag.py — GIL/loop saturation)",
                    [(base, lag_hist)],
                )
            )
        # Admission-control state (ISSUE 15): the ingest rx queue's
        # last-stamped occupancy, bound, high-water mark, and the derived
        # saturation fraction.  The companion shed counters
        # (minbft_admission_shed_total / minbft_admission_busy_sent_total
        # / minbft_admission_busy_suppressed_total) ride the counter loop
        # above.  Families appear once the ingestor has stamped at least
        # one tick (bound > 0) — an idle replica stays quiet.
        if getattr(metrics, "admission_rx_bound", 0):
            fams.append(
                (
                    "minbft_admission_rx_depth",
                    "gauge",
                    "ingest rx queue occupancy at the last ingest tick",
                    [(base, int(metrics.admission_rx_depth))],
                )
            )
            fams.append(
                (
                    "minbft_admission_rx_bound",
                    "gauge",
                    "ingest rx queue capacity (frames)",
                    [(base, int(metrics.admission_rx_bound))],
                )
            )
            fams.append(
                (
                    "minbft_admission_rx_peak",
                    "gauge",
                    "ingest rx queue high-water mark (bounded-queue-growth "
                    "witness for the overload tests)",
                    [(base, int(metrics.admission_rx_peak))],
                )
            )
            fams.append(
                (
                    "minbft_admission_rx_saturation",
                    "gauge",
                    "rx fill fraction in [0,1] — scales the BUSY "
                    "retry-after hint",
                    [(base, round(metrics.admission_rx_saturation(), 4))],
                )
            )
        # Health monitors (ISSUE 14): evaluated AT SCRAPE TIME from the
        # metrics' stamps — no detector thread to die silently.
        if hasattr(metrics, "current_view"):
            fams.append(
                (
                    "minbft_health_view",
                    "gauge",
                    "view this replica currently operates in",
                    [(base, int(metrics.current_view))],
                )
            )
        if hasattr(metrics, "stalled"):
            fams.append(
                (
                    "minbft_health_commit_stall",
                    "gauge",
                    "1 when messages keep arriving but nothing has "
                    f"executed for >{stall_after_s:g}s (commit stall); "
                    "an idle replica reads 0",
                    [(base, 1 if metrics.stalled(stall_after_s) else 0)],
                )
            )
    if timeseries is not None:
        # Recent-window readings from the telemetry rings
        # (obs/timeseries.py): rate series as per-second rates over the
        # last 10 completed intervals, gauge series as window means —
        # the live numbers `peer top --once` renders without needing two
        # scrapes to diff.
        win = timeseries.window(10 * timeseries.interval_s)
        for sname in sorted(win):
            fams.append(
                (
                    f"minbft_window_{sname}",
                    "gauge",
                    f"recent-window reading of the {sname} telemetry "
                    "ring (last 10 intervals)",
                    [(base, round(win[sname], 3))],
                )
            )
    if recorder is not None:
        samples = []
        for name, h in recorder.stage_hists().items():
            lb = dict(base)
            lb["stage"] = name
            samples.append((lb, h))
        fams.append(
            (
                "minbft_stage_latency_seconds",
                "histogram",
                "flight-recorder span: time from the previous capture "
                "point to this stage",
                samples,
            )
        )
    if engine is not None:
        fams.extend(_collect_engine(engine, base))
    if slo is not None:
        # ``slo`` is the replica's obs.slo.BudgetLedger; burn rates read
        # the same rings the minbft_window_* gauges render.
        fams.extend(
            collect_slo(
                [slo], timeseries=timeseries, spool=slo_spool, base=base
            )
        )
    if recovery is not None:
        fams.extend(collect_recovery([recovery], base=base))
    return fams


def collect_recovery(
    managers, base: Optional[Dict[str, str]] = None
) -> List[Family]:
    """Families for the crash-recovery subsystem
    (:class:`minbft_tpu.recovery.RecoveryManager`, one per replica core):
    the phase gauge, chunk/byte transfer counters split by direction,
    resume/failover counts, durable-store save counters, and — once a
    restarted replica executes its first request — the
    ``minbft_recovery_time_ms`` SLO gauge the chaos soak gates
    (benchgate key ``chaos_recovery_time_ms``)."""
    base = dict(base or {})
    fams: List[Family] = []

    def lb(m, **extra):
        out = dict(base)
        if m.group is not None:
            out["group"] = str(m.group)
        out.update(extra)
        return out

    fams.append(
        (
            "minbft_recovery_phase",
            "gauge",
            "recovery phase (0=idle 1=load 2=fetch 3=install 4=catchup "
            "5=done)",
            [(lb(m), m.phase) for m in managers],
        )
    )
    fams.append(
        (
            "minbft_recovery_chunks_total",
            "counter",
            "state-transfer chunks moved, by direction (rx=fetched and "
            "verified, tx=served)",
            [
                s
                for m in managers
                for s in (
                    (lb(m, dir="rx"), m.chunks_rx),
                    (lb(m, dir="tx"), m.chunks_tx),
                )
            ],
        )
    )
    fams.append(
        (
            "minbft_recovery_bytes_total",
            "counter",
            "state-transfer payload bytes moved, by direction",
            [
                s
                for m in managers
                for s in (
                    (lb(m, dir="rx"), m.bytes_rx),
                    (lb(m, dir="tx"), m.bytes_tx),
                )
            ],
        )
    )
    fams.append(
        (
            "minbft_recovery_resume_total",
            "counter",
            "chunked transfers resumed from a verified offset after an "
            "interruption (same source, no bytes re-downloaded)",
            [(lb(m), m.resumes) for m in managers],
        )
    )
    fams.append(
        (
            "minbft_recovery_failover_total",
            "counter",
            "chunked transfers failed over to another source (stalled or "
            "Byzantine-corrupt stream)",
            [(lb(m), m.failovers) for m in managers],
        )
    )
    fams.append(
        (
            "minbft_recovery_saves_total",
            "counter",
            "durable checkpoint saves committed (atomic write-rename)",
            [(lb(m), m.saves) for m in managers],
        )
    )
    restored = [
        (lb(m), m.restored_count)
        for m in managers
        if m.restored_count is not None
    ]
    if restored:
        fams.append(
            (
                "minbft_recovery_restored_count",
                "gauge",
                "stable execution count restored from the durable store "
                "at startup",
                restored,
            )
        )
    times = [
        (lb(m), round(m.recovery_time_ms, 3))
        for m in managers
        if m.recovery_time_ms is not None
    ]
    if times:
        fams.append(
            (
                "minbft_recovery_time_ms",
                "gauge",
                "restart-to-first-executed-request time (the recovery SLO "
                "the chaos soak gates as chaos_recovery_time_ms)",
                times,
            )
        )
    return fams


def collect_slo(ledgers, timeseries=None, spool=None,
                base: Optional[Dict[str, str]] = None,
                now: Optional[float] = None) -> List[Family]:
    """Families for the latency-SLO engine (obs/slo.py): per-group
    good/breached counters, the policy knobs, remaining error-budget
    fraction, the fast/slow burn rates (read from the telemetry rings —
    omitted when no ring is attached), and the breach-dump spool
    counters.  A stale group stops committing, its good counter stops
    moving, and its windowed breach fraction reads budget burn — the
    per-group labels are what make that legible."""
    from . import slo as obs_slo

    base = dict(base or {})
    ledgers = [lg for lg in ledgers if lg is not None]
    if not ledgers:
        return []

    def lb(lg) -> Dict[str, str]:
        if lg.group is None or "group" in base:
            return base
        return {**base, "group": str(lg.group)}

    fams: List[Family] = [
        ("minbft_slo_good_total", "counter",
         "requests that committed inside the finality budget "
         "(recv-origin, classified at commit quorum)",
         [(lb(lg), lg.good) for lg in ledgers]),
        ("minbft_slo_breached_total", "counter",
         "requests that committed past the finality budget",
         [(lb(lg), lg.breached) for lg in ledgers]),
        ("minbft_slo_target_ms", "gauge",
         "finality budget per request (SLOPolicy.target_ms)",
         [(lb(lg), lg.policy.target_ms) for lg in ledgers]),
        ("minbft_slo_objective", "gauge",
         "fraction of requests that must meet the budget",
         [(lb(lg), lg.policy.objective) for lg in ledgers]),
        ("minbft_slo_budget_remaining", "gauge",
         "remaining error-budget fraction this incarnation (1 = "
         "untouched, negative = overspent — not clamped)",
         [(lb(lg), round(lg.budget_remaining(), 4)) for lg in ledgers]),
        ("minbft_slo_burn_threshold", "gauge",
         "fast-window burn multiple that trips breach forensics and "
         "the `peer top` BREACH flag",
         [(lb(lg), lg.policy.burn_threshold) for lg in ledgers]),
    ]
    if timeseries is not None:
        burn_samples = []
        for lg in ledgers:
            b = obs_slo.burn_rates(
                timeseries, lg.policy, now=now, group=lg.group
            )
            for window in ("fast", "slow"):
                burn_samples.append(
                    ({**lb(lg), "window": window}, b[f"{window}_burn"])
                )
        fams.append(
            ("minbft_slo_burn_rate", "gauge",
             "error-budget burn multiple over the window (1.0 spends "
             "the budget exactly as fast as the objective allows)",
             burn_samples)
        )
    if spool is not None:
        fams.append(
            ("minbft_slo_breach_dumps_total", "counter",
             "breach forensic bundles written to the spool",
             [(base, spool.written)])
        )
        fams.append(
            ("minbft_slo_breach_dumps_suppressed_total", "counter",
             "breach dumps refused by the token bucket or the spool "
             "bound (a signal of sustained breach, not an error)",
             [(base, spool.suppressed)])
        )
    return fams


def merge_family_lists(lists: Iterable[List[Family]]) -> List[Family]:
    """Fold several family lists into one exposition-valid list: a
    family name may appear only once per exposition, so per-group
    ``collect_replica`` outputs (multi-group runtime — same families,
    distinct ``group`` labels) concatenate their SAMPLES under one
    family block instead of repeating the block."""
    merged: Dict[str, list] = {}
    order: List[str] = []
    for fams in lists:
        for name, mtype, help_text, samples in fams:
            ent = merged.get(name)
            if ent is None:
                merged[name] = [mtype, help_text, list(samples)]
                order.append(name)
            else:
                ent[2].extend(samples)
    return [
        (name, merged[name][0], merged[name][1], merged[name][2])
        for name in order
    ]


def collect_engine_pool(pool, base: Optional[Dict[str, str]] = None
                        ) -> List[Family]:
    """Families for a :class:`minbft_tpu.parallel.EnginePool`: pool
    width, per-chip utilization (busy fraction and fill efficiency over
    the window since the LAST scrape — the call rolls the pool's
    utilization windows, same reset-on-read contract as the depth-peak
    gauges), per-chip queue depth and liveness, and each group's home
    chip.  ``peer top`` renders these as per-chip sub-rows under the
    (replica, group) identity; a chip whose every queue wrote its device
    off reads ``minbft_engine_pool_chip_up`` 0 (rendered DOWN)."""
    base = dict(base or {})
    rows = pool.chip_utilization()
    busy, fill, depth, up = [], [], [], []
    for row in rows:
        lb = {**base, "chip": str(row["chip"])}
        busy.append((lb, row["busy"]))
        fill.append((lb, row["fill"]))
        depth.append((lb, row["depth"]))
        up.append((lb, 1 if pool.chip_up(row["chip"]) else 0))
    home = [
        ({**base, "group": str(g)}, c)
        for g, c in sorted(pool.placement().items())
    ]
    return [
        ("minbft_engine_pool_chips", "gauge",
         "home chips in the engine pool (requested clamps to visible "
         "devices)", [(base, pool.chips)]),
        ("minbft_engine_pool_chip_busy", "gauge",
         "per-chip busy fraction since the last scrape (PR-9 ledger "
         "window over the chip's engine)", busy),
        ("minbft_engine_pool_chip_fill", "gauge",
         "per-chip fill efficiency since the last scrape (1.0 under a "
         "self ceiling)", fill),
        ("minbft_engine_pool_chip_depth", "gauge",
         "items pending across the chip engine's verify+sign queues",
         depth),
        ("minbft_engine_pool_chip_up", "gauge",
         "0 when every queue on the chip has written its device off "
         "(host-fallback only — the chip is effectively DOWN)", up),
        ("minbft_engine_pool_home_chip", "gauge",
         "each consensus group's home chip (placement map)", home),
    ]


def collect_group_runtime(runtime, engine=None, replica_id=None,
                          timeseries=None, engine_pool=None,
                          slo_spool=None) -> List[Family]:
    """Families for a :class:`minbft_tpu.groups.GroupRuntime`: one
    ``collect_replica`` per group core (every series carries its
    ``group`` label), the shared engine's families once (its queues
    really are shared — splitting them per group would double-count).
    The time-series rings and the stale-group health gauge are
    process-level and likewise emitted once.  ``engine_pool`` (explicit,
    or the runtime's own ``engine_pool`` attribute) adds the
    ``minbft_engine_pool_*`` per-chip families."""
    n_groups = len(runtime.cores)
    lists = [
        collect_replica(
            metrics=core.metrics,
            recorder=core.handlers.trace,
            replica_id=replica_id,
            group=core.group,
            groups=n_groups,
        )
        for core in runtime.cores
    ]
    if engine is not None:
        lists.append(collect_replica(engine=engine, replica_id=replica_id))
    if timeseries is not None:
        lists.append(
            collect_replica(timeseries=timeseries, replica_id=replica_id)
        )
    # One collect_slo across every core's ledger: the per-group burn
    # rates all read the ONE process-level ring (series are per-group
    # suffixed), and the spool counters are process-level.
    slo_ledgers = [
        core.handlers.slo for core in runtime.cores
        if getattr(core.handlers, "slo", None) is not None
    ]
    if slo_ledgers:
        base = {} if replica_id is None else {"replica": str(replica_id)}
        lists.append(
            collect_slo(
                slo_ledgers, timeseries=timeseries, spool=slo_spool,
                base=base,
            )
        )
    # One collect_recovery across every core's manager: each carries its
    # own group label (like the SLO ledgers).
    recovery_managers = [
        core.recovery for core in runtime.cores
        if getattr(core, "recovery", None) is not None
    ]
    if recovery_managers:
        base = {} if replica_id is None else {"replica": str(replica_id)}
        lists.append(collect_recovery(recovery_managers, base=base))
    fams = merge_family_lists(lists)
    if engine_pool is None:
        engine_pool = getattr(runtime, "engine_pool", None)
    if engine_pool is not None:
        base = {} if replica_id is None else {"replica": str(replica_id)}
        fams.extend(collect_engine_pool(engine_pool, base))
    stale_fn = getattr(runtime, "stale_groups", None)
    if stale_fn is not None:
        base = {} if replica_id is None else {"replica": str(replica_id)}
        stale = stale_fn()
        fams.append(
            (
                "minbft_health_stale_group",
                "gauge",
                "1 when this group core has made no progress while a "
                "sibling group on the same process has (stale-group "
                "detector, groups/runtime.py)",
                [
                    ({**base, "group": str(core.group)},
                     1 if core.group in stale else 0)
                    for core in runtime.cores
                ],
            )
        )
    return fams


def collect_faultnet(census, base: Optional[Dict[str, str]] = None) -> List[Family]:
    """Metric families for a fault-injection census
    (:class:`minbft_tpu.testing.faultnet.FaultCensus`, duck-typed:
    ``counters`` per-kind totals, ``links`` per-(src,dst) kind maps,
    ``frames`` per-link frame counts).  Lets a chaos run's fault census
    ride the same Prometheus endpoint as the protocol counters — the
    injected-fault ground truth next to the recovery metrics it caused.
    """
    base = dict(base or {})
    fams: List[Family] = []
    totals = [
        ({**base, "kind": kind}, v)
        for kind, v in sorted(dict(census.counters).items())
    ]
    fams.append(
        (
            "minbft_faultnet_injected_total",
            "counter",
            "faults injected by kind (faultnet census)",
            totals,
        )
    )
    per_link = []
    for (src, dst), kinds in sorted(dict(census.links).items()):
        for kind, v in sorted(dict(kinds).items()):
            per_link.append(
                ({**base, "link": f"{src}>{dst}", "kind": kind}, v)
            )
    fams.append(
        (
            "minbft_faultnet_link_injected_total",
            "counter",
            "faults injected per directed link and kind",
            per_link,
        )
    )
    fams.append(
        (
            "minbft_faultnet_frames_total",
            "counter",
            "frames that traversed each directed link (replay input)",
            [
                ({**base, "link": f"{src}>{dst}"}, v)
                for (src, dst), v in sorted(dict(census.frames).items())
            ],
        )
    )
    return fams


def _collect_engine(engine, base: Dict[str, str]) -> List[Family]:
    fams: List[Family] = []
    peak_fn = getattr(engine, "queue_depth_peaks", None)
    sign_peak_fn = getattr(engine, "sign_queue_depth_peaks", None)
    for side, stats_map, depths, peaks in (
        ("verify", engine.stats, engine.queue_depths(),
         peak_fn() if peak_fn else {}),
        ("sign", engine.sign_stats, engine.sign_queue_depths(),
         sign_peak_fn() if sign_peak_fn else {}),
    ):
        counters: Dict[str, List] = {
            "items": [],
            "batches": [],
            "padded_lanes": [],
            "dispatch_timeouts": [],
        }
        seconds: Dict[str, List] = {"device": [], "host_prep": []}
        flushes: List = []
        occupancy: List = []
        depth_samples: List = []
        wait_samples: List = []
        service_samples: List = []
        for qname, st in sorted(stats_map.items()):
            lb = dict(base)
            lb["queue"] = qname
            for k in counters:
                counters[k].append((lb, getattr(st, k, 0)))
            seconds["device"].append((lb, st.device_time_s))
            seconds["host_prep"].append((lb, st.host_prep_time_s))
            qw = getattr(st, "queue_wait", None)
            if qw is not None and (qw.count or qw.negatives):
                wait_samples.append((lb, qw))
            qs = getattr(st, "queue_service", None)
            if qs is not None and (qs.count or qs.negatives):
                service_samples.append((lb, qs))
            # dict(...) snapshots before iterating: the event loop
            # inserts new reasons/buckets while this thread walks.
            for reason, cnt in sorted(
                dict(getattr(st, "flush_reasons", {})).items()
            ):
                lbr = dict(lb)
                lbr["reason"] = reason
                flushes.append((lbr, cnt))
            for log2_size, cnt in sorted(
                dict(getattr(st, "occupancy", {})).items()
            ):
                lbo = dict(lb)
                # upper bound of the log2 occupancy bucket, in items
                lbo["le_items"] = str(1 << int(log2_size))
                occupancy.append((lbo, cnt))
        peak_samples: List = []
        for qname, depth in sorted(depths.items()):
            lb = dict(base)
            lb["queue"] = qname
            depth_samples.append((lb, depth))
            peak_samples.append((lb, peaks.get(qname, depth)))
        p = f"minbft_{side}_queue"
        fams.append((f"{p}_items_total", "counter",
                     f"{side} items dispatched", counters["items"]))
        fams.append((f"{p}_batches_total", "counter",
                     f"{side} batches dispatched", counters["batches"]))
        fams.append((f"{p}_padded_lanes_total", "counter",
                     "bucket-padding lanes wasted", counters["padded_lanes"]))
        fams.append((f"{p}_dispatch_timeouts_total", "counter",
                     "hung dispatches rescued on host",
                     counters["dispatch_timeouts"]))
        fams.append((f"{p}_device_seconds_total", "counter",
                     "seconds awaiting dispatches", seconds["device"]))
        fams.append((f"{p}_host_prep_seconds_total", "counter",
                     "host share of dispatch time (prep/pack/finish)",
                     seconds["host_prep"]))
        fams.append((f"{p}_flushes_total", "counter",
                     "queue flushes by reason (full/idle/timer/completion)",
                     flushes))
        fams.append((f"{p}_batch_occupancy_total", "counter",
                     "batches by log2 occupancy bucket (pre-padding)",
                     occupancy))
        fams.append((f"{p}_wait_seconds", "histogram",
                     "per-item wait from enqueue to dispatch (the "
                     "batch-formation / queue-wait attribution)",
                     wait_samples))
        fams.append((f"{p}_service_seconds", "histogram",
                     "dispatch to completion (kernel + transfer + host "
                     "prep, shared by every lane of the batch)",
                     service_samples))
        fams.append((f"{p}_depth", "gauge",
                     "items pending in the queue right now", depth_samples))
        fams.append((f"{p}_depth_peak", "gauge",
                     "high-water mark of the queue depth since the last "
                     "scrape (peak backlog the point-in-time gauge misses)",
                     peak_samples))
    return fams


class MetricsServer:
    """``/metrics`` on a daemon thread (stdlib ThreadingHTTPServer).

    ``render`` is called per scrape on a SERVER thread — it must only
    read (see the module docstring's consistency model).  ``start``
    returns the bound port (pass 0 to pick a free one).  Binds loopback
    by default: the endpoint is unauthenticated, so exposing it beyond
    the host is an explicit operator decision (``--metrics-host``)."""

    def __init__(self, render: Callable[[], str],
                 host: str = "127.0.0.1", port: int = 0):
        self._render = render
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        render = self._render

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API name
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = render().encode()
                except Exception as e:  # noqa: BLE001 - a scrape bug
                    # must report, not kill the handler thread silently
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log events
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="minbft-metrics",
            daemon=True,
        )
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def scrape(url: str, timeout: float = 5.0) -> str:
    """One-shot metrics fetch (the ``peer metrics`` subcommand).
    ``url`` may be a bare ``host:port`` — ``/metrics`` is implied."""
    from urllib.request import urlopen

    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


# ---------------------------------------------------------------------------
# Cluster aggregation: parse expositions back and merge them.
#
# The log2 histograms are exactly mergeable BY DESIGN (identical fixed
# bucket edges everywhere — obs/hist.py), so N replicas' scrapes fold
# into one cluster exposition with no re-binning: per-``le`` bucket
# counts add, ``_sum``/``_count`` add, counters add.  Gauges
# (depths, uptime) are point-in-time per process and are summed too —
# a cluster-total reading (document accordingly; a mean would be wrong
# for depths and a max wrong for uptime, total is at least well-defined).

_SAMPLE_RE = None  # compiled lazily (parsing is a cold operator path)


def _parse_labels(inner: str) -> Dict[str, str]:
    import re

    return {
        m.group(1): m.group(2).replace('\\"', '"').replace("\\\\", "\\")
        for m in re.finditer(r'(\w+)="((?:[^"\\]|\\.)*)"', inner or "")
    }


def parse_exposition(text: str) -> Dict[str, dict]:
    """Parse Prometheus text (format 0.0.4) into
    ``{family: {"type", "help", "samples"}}``.

    Histogram families collapse their ``_bucket``/``_sum``/``_count``
    series back into per-sample ``{"buckets": {le: cumulative}, "sum",
    "count"}`` keyed by the non-``le`` labels; counter/gauge samples map
    labels→value.  Built for OUR exposition (render_families output) —
    a general scraper it is not."""
    import re

    global _SAMPLE_RE
    if _SAMPLE_RE is None:
        _SAMPLE_RE = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
        )
    fams: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            types[name] = mtype
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            helps[name] = help_text
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        sname, inner, raw = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(inner)
        value = float("inf") if raw == "+Inf" else float(raw)
        # Histogram series fold back under their family name.
        fam_name, part = sname, "value"
        for suffix in ("_bucket", "_sum", "_count"):
            base = sname[: -len(suffix)]
            if sname.endswith(suffix) and types.get(base) == "histogram":
                fam_name, part = base, suffix[1:]
                break
        mtype = types.get(fam_name, "untyped")
        fam = fams.setdefault(
            fam_name,
            {"type": mtype, "help": helps.get(fam_name, ""), "samples": {}},
        )
        if mtype == "histogram":
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            sample = fam["samples"].setdefault(
                key, {"buckets": {}, "sum": 0.0, "count": 0}
            )
            if part == "bucket" and le is not None:
                sample["buckets"][
                    float("inf") if le == "+Inf" else float(le)
                ] = int(value)
            elif part == "sum":
                sample["sum"] = value
            elif part == "count":
                sample["count"] = int(value)
        else:
            key = tuple(sorted(labels.items()))
            fam["samples"][key] = value
    return fams


def merge_expositions(texts: Iterable[str],
                      drop_labels: Tuple[str, ...] = ("replica",)) -> str:
    """Merge several scraped expositions into ONE cluster aggregate.

    ``drop_labels`` (default: the per-process ``replica`` id) are
    stripped before merging so the same logical series from different
    replicas folds together.  Histograms merge exactly (cumulative
    counts are diffed to per-bucket, summed per ``le``, re-accumulated
    over the union grid); counters and gauges sum."""
    merged: Dict[str, dict] = {}
    for text in texts:
        for name, fam in parse_exposition(text).items():
            out = merged.setdefault(
                name, {"type": fam["type"], "help": fam["help"], "samples": {}}
            )
            for key, value in fam["samples"].items():
                key = tuple(
                    (k, v) for k, v in key if k not in drop_labels
                )
                if fam["type"] == "histogram":
                    agg = out["samples"].setdefault(
                        key, {"buckets": {}, "sum": 0.0, "count": 0}
                    )
                    # cumulative -> per-bucket before summing: targets
                    # skip empty buckets, so their ``le`` grids differ.
                    prev = 0
                    for le in sorted(value["buckets"]):
                        c = value["buckets"][le]
                        agg["buckets"][le] = (
                            agg["buckets"].get(le, 0) + (c - prev)
                        )
                        prev = c
                    agg["sum"] += value["sum"]
                    agg["count"] += value["count"]
                else:
                    out["samples"][key] = out["samples"].get(key, 0) + value
    # Render back to exposition text.
    lines: List[str] = []
    for name in sorted(merged):
        fam = merged[name]
        if not fam["samples"]:
            continue
        lines.append(f"# HELP {name} {fam['help']}".rstrip())
        lines.append(f"# TYPE {name} {fam['type']}")
        for key in sorted(fam["samples"]):
            labels = dict(key)
            value = fam["samples"][key]
            if fam["type"] == "histogram":
                cum = 0
                for le in sorted(value["buckets"]):
                    cum += value["buckets"][le]
                    lb = dict(labels)
                    lb["le"] = "+Inf" if le == float("inf") else repr(le)
                    lines.append(f"{name}_bucket{_fmt_labels(lb)} {cum}")
                if float("inf") not in value["buckets"]:
                    lb = dict(labels)
                    lb["le"] = "+Inf"
                    lines.append(f"{name}_bucket{_fmt_labels(lb)} {cum}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(value['sum'])}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {value['count']}"
                )
            else:
                v = value
                if fam["type"] == "counter" and float(v).is_integer():
                    v = int(v)
                lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")
    return "\n".join(lines) + "\n"
