"""NTP-free pairwise clock-offset estimation from trace dumps.

Every flight-recorder dump timestamps with its OWN process's
``time.monotonic_ns()`` — two processes' clocks share neither epoch nor
offset, so merging dumps into one causal timeline (obs/critpath.py)
needs the pairwise offsets first.  The protocol itself provides the
probe traffic: every request is a client→replica message (``broadcast``
noted on the client, ``ingest``/``recv`` on the replica) and every reply
a replica→client message (``reply_sent`` on the replica, ``quorum`` on
the client), all keyed by the same ``(client_id, seq)`` pair — matched
send/recv span pairs with no wire change and no extra traffic.

Estimation is Cristian-style over the matched pairs.  Writing ``o`` for
the replica clock minus the client clock (so ``t_replica = t_client +
o`` for a simultaneous instant):

- a client→replica pair gives ``d1 = t_recv - t_send = o + delay >= o``
  — every forward pair UPPER-bounds the offset, and the minimum over
  many pairs (min-RTT filtering: queueing inflates d1, never deflates
  it) is the tightest bound ``U = min d1``;
- a replica→client pair gives ``d2 = t_recv - t_send = -o + delay``
  — a LOWER bound ``L = -min d2``.

The estimate is the interval midpoint ``(U + L) / 2`` with uncertainty
``(U - L) / 2`` — half the best observed round-trip residual, the
classical Cristian bound.  The uncertainty is carried into the merged
timeline: a cross-node segment can never honestly be reported tighter
than it.

Caveats (documented, deliberate):

- The client's ``quorum`` note fires when the f+1-th MATCHING reply
  arrives; for a replica whose reply arrived after the quorum formed,
  ``d2`` under-measures and can violate the bound.  ``min d2`` can
  therefore be contaminated by up to ``n - (f+1)`` late repliers; when
  the bounds cross (``L > U``) the estimate keeps the midpoint and
  reports ``|U - L| / 2`` as the uncertainty — inconsistent bounds are
  a confidence signal, not a crash.
- Clock DRIFT over a long run widens the residual; the estimator is a
  single static offset per pair, which is the right model for the
  minutes-long traced bench passes it serves.

Replica↔replica offsets are derived through a client hub: replicas only
exchange PREPARE/COMMIT traffic whose capture points (prepare, commit
quorum) are aggregate events, not matched unicast pairs — the client's
REQUEST/REPLY pairs are the clean probes.  ``align`` picks the hub with
the smallest combined uncertainty per replica.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

# Dump-doc stage names (obs/trace.py REPLICA_STAGES / CLIENT_STAGES).
_ENTRY_STAGES = ("ingest", "recv")


@dataclasses.dataclass(frozen=True)
class PairEstimate:
    """Offset of a replica clock RELATIVE to a client clock:
    ``t_replica ≈ t_client + offset_ns ± err_ns``."""

    offset_ns: float
    err_ns: float
    forward_pairs: int
    backward_pairs: int
    min_rtt_ns: float  # best observed round-trip residual (U - L)
    consistent: bool  # False when the bounds crossed (see module doc)


@dataclasses.dataclass(frozen=True)
class ClockAlignment:
    """Mapping of one recorder's clock onto the reference timeline:
    ``t_ref ≈ t_local + offset_ns ± err_ns``."""

    offset_ns: float
    err_ns: float


def event_times(doc: dict) -> Dict[Tuple[int, int], Dict[str, int]]:
    """``(client_id, seq) -> {stage_name: first_noted_t_ns}`` for one
    dump doc.  FIRST occurrence wins: retransmissions re-note entry
    stages, and the causal timeline wants the original arrival."""
    stages = doc.get("stages") or ()
    out: Dict[Tuple[int, int], Dict[str, int]] = {}
    for row in doc.get("events") or ():
        try:
            cid, seq, stage_idx, t_ns = row
            name = stages[stage_idx]
        except (ValueError, IndexError, TypeError):
            continue
        per_req = out.setdefault((int(cid), int(seq)), {})
        if name not in per_req:
            per_req[name] = int(t_ns)
    return out


def entry_time(stages: Dict[str, int]) -> Optional[int]:
    ts = [stages[s] for s in _ENTRY_STAGES if s in stages]
    return min(ts) if ts else None


def estimate_pair(client_doc: dict, replica_doc: dict) -> Optional[PairEstimate]:
    """Cristian-style offset of ``replica_doc``'s clock relative to
    ``client_doc``'s, from their matched (client_id, seq) span pairs.
    None when either direction has no matched pair."""
    ce = event_times(client_doc)
    re_ = event_times(replica_doc)
    d1s: List[int] = []
    d2s: List[int] = []
    for key, cstages in ce.items():
        rstages = re_.get(key)
        if not rstages:
            continue
        send = cstages.get("broadcast")
        entry = entry_time(rstages)
        if send is not None and entry is not None:
            d1s.append(entry - send)
        rsent = rstages.get("reply_sent")
        crecv = cstages.get("quorum")
        if rsent is not None and crecv is not None:
            d2s.append(crecv - rsent)
    if not d1s or not d2s:
        return None
    upper = min(d1s)
    lower = -min(d2s)
    offset = (upper + lower) / 2.0
    err = (upper - lower) / 2.0
    return PairEstimate(
        offset_ns=offset,
        err_ns=abs(err),
        forward_pairs=len(d1s),
        backward_pairs=len(d2s),
        min_rtt_ns=float(upper - lower),
        consistent=upper >= lower,
    )


def align(docs: Iterable[dict]) -> Dict[Tuple[str, int], ClockAlignment]:
    """Map every replica/client dump onto ONE reference timeline.

    Reference clock: the lowest-id replica dump (falling back to the
    lowest-id client when no replica dumped).

    Dumps stamped with the SAME ``clock_domain`` (obs/trace.py: the
    host, because ``time.monotonic`` is the system-wide boot-relative
    CLOCK_MONOTONIC) literally share a clock — they align with offset 0
    and uncertainty 0, EXACTLY.  Estimation is reserved for genuinely
    cross-domain dumps: Cristian's asymmetric-delay bias (a loaded
    ingress path makes the forward bound loose) would otherwise smear
    co-resident recorders apart by hundreds of milliseconds of honest
    but needless uncertainty.

    Cross-domain clients align to the reference directly through their
    own pair estimate; cross-domain replicas align through the client
    hub whose combined uncertainty is smallest (a hub sharing the
    replica's domain contributes zero extra error; estimation errors
    add through the hub — carried, never dropped).

    Returns ``{(kind, id): ClockAlignment}`` — only for docs that could
    be aligned (the reference itself maps with offset 0, err 0).
    Unalignable docs are simply absent; callers skip them.
    """
    docs = list(docs)
    replicas = {d["id"]: d for d in docs if d.get("kind") == "replica"}
    clients = {d["id"]: d for d in docs if d.get("kind") == "client"}
    out: Dict[Tuple[str, int], ClockAlignment] = {}
    if not replicas:
        # Replica-less dumps (client-only traces): nothing to cross-align
        # — every client keeps its own clock as a local reference.
        for cid in clients:
            out[("client", cid)] = ClockAlignment(0.0, 0.0)
        return out
    ref_id = min(replicas)
    ref_doc = replicas[ref_id]
    ref_dom = ref_doc.get("clock_domain")
    out[("replica", ref_id)] = ClockAlignment(0.0, 0.0)

    def shares_ref_domain(doc: dict) -> bool:
        d = doc.get("clock_domain")
        return d is not None and d == ref_dom

    # Clients: t_ref = t_client + o(ref, client).
    client_align: Dict[int, ClockAlignment] = {}
    for cid, cdoc in clients.items():
        if shares_ref_domain(cdoc):
            al = ClockAlignment(0.0, 0.0)
        else:
            est = estimate_pair(cdoc, ref_doc)
            if est is None:
                continue
            al = ClockAlignment(est.offset_ns, est.err_ns)
        client_align[cid] = al
        out[("client", cid)] = al
    # Other replicas, through the best client hub:
    # t_ref = t_r - o(r, hub) + o(ref, hub).
    for rid, rdoc in replicas.items():
        if rid == ref_id:
            continue
        if shares_ref_domain(rdoc):
            out[("replica", rid)] = ClockAlignment(0.0, 0.0)
            continue
        rdom = rdoc.get("clock_domain")
        best: Optional[ClockAlignment] = None
        for cid, cal in client_align.items():
            cdom = clients[cid].get("clock_domain")
            if cdom is not None and cdom == rdom:
                # Hub and replica share a clock: o(r, hub) == 0 exactly.
                cand = cal
            else:
                est = estimate_pair(clients[cid], rdoc)
                if est is None:
                    continue
                cand = ClockAlignment(
                    offset_ns=cal.offset_ns - est.offset_ns,
                    err_ns=cal.err_ns + est.err_ns,
                )
            if best is None or cand.err_ns < best.err_ns:
                best = cand
        if best is not None:
            out[("replica", rid)] = best
    return out
