"""Process-incarnation identity for every observability surface.

Two problems share one fix (ISSUE 14 satellites 1 and 2):

- A merged multi-target scrape or a cross-node dump merge is only
  attributable if every sample says WHICH process produced it — a
  restarted replica keeps its replica id but is a different process
  with fresh counters and a fresh (client_id, seq) keyspace.
- The critpath/time-series mergers must be able to REFUSE splicing two
  incarnations of the same replica id into one timeline (the chimera
  problem): that requires a per-incarnation stamp that changes on every
  restart and never within one process lifetime.

``RUN_ID`` is that stamp: pid + wall-clock start nanoseconds, fixed at
first import.  ``build_info()`` is the attribution block (pid, backend,
git rev) rendered as the ``minbft_build_info`` gauge labels and merged
into trace/time-series dump metadata.  The module stays import-light:
jax is consulted only if something else already imported it — an
observability stamp must never pull the accelerator stack in.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, Optional

# Per-incarnation run id: monotone across restarts of the same replica
# id (wall-clock start stamp), unique across concurrent processes (pid).
RUN_ID: str = f"{os.getpid()}-{time.time_ns()}"

_git_rev: Optional[str] = None


def git_rev() -> str:
    """Short git revision of the running tree, memoized.  Falls back to
    ``MINBFT_GIT_REV`` (container builds without a .git directory), then
    ``unknown`` — an attribution label, so it must never raise."""
    global _git_rev
    if _git_rev is not None:
        return _git_rev
    rev = os.environ.get("MINBFT_GIT_REV")
    if not rev:
        try:
            # noqa: AH101 - one-shot and cached (5s cap); attribution only
            rev = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=5,
            ).stdout.strip() or "unknown"
        except (OSError, subprocess.SubprocessError):
            rev = "unknown"
    _git_rev = rev
    return rev


def backend() -> str:
    """The jax backend IF jax is already loaded; ``unloaded`` otherwise.
    Importing jax from an obs module would force the accelerator stack
    into processes (``peer top``, dump mergers) that never touch it."""
    jax = sys.modules.get("jax")
    if jax is None:
        return "unloaded"
    try:
        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - a dead backend is still a label
        return "error"


def build_info(
    replica_id: Optional[int] = None,
    group: Optional[int] = None,
    groups: Optional[int] = None,
) -> Dict[str, str]:
    """The attribution block: every value a STRING (Prometheus label
    values and JSON dump metadata share it verbatim)."""
    info = {
        "pid": str(os.getpid()),
        "run_id": RUN_ID,
        "backend": backend(),
        "git_rev": git_rev(),
    }
    if replica_id is not None:
        info["replica"] = str(replica_id)
    if group is not None:
        info["group"] = str(group)
    if groups is not None:
        info["groups"] = str(groups)
    return info
