"""Device-utilization ledger: where every engine wall-second and every
dispatched lane went, and which factor loses the 100x.

ROADMAP's headline gap — the chip verifies ~164k ECDSA sigs/s while the
best end-to-end config commits ~1.1k req/s — has only ever been an
INFERENCE from two unrelated numbers.  The ledger turns it into a
measured decomposition.  Over a window ``[t0, t1]`` (captured with
:meth:`DeviceLedger.snapshot`), each engine queue's accounting splits:

- **wall time** into *busy* (the sum of ``_run``'s dispatch spans,
  ``VerifyStats.device_time_s``, clamped to wall — ``max_inflight``
  overlap can legitimately stack spans past the clock) and *idle*;
- **lanes** into *useful* (real protocol items dispatched), *padding*
  (bucket fill lanes), *memo-duplicate* (logical verifies the dedup
  memo absorbed before they could cost a lane), and *host-fallback*
  (sign items served by host crypto) — the four classes sum to the
  total lane demand by construction, and the test suite pins it.

The headline is the multiplicative headroom identity

    effective_rate = ceiling × busy_fraction × fill_efficiency × useful_fraction

where ``ceiling`` is the CALIBRATED full-batch lane rate for the
backend (one-shot probe on CPU; the committed ``last_tpu`` block on the
chip — bench.py supplies it), and the three factors are defined so the
product is EXACT, not approximate:

- ``busy_fraction  = busy_s / wall_s``              (idle loses the rest)
- ``fill_efficiency = dispatched_lanes / (ceiling × busy_s)``
  — how close busy time ran to the calibrated lane rate.  Sub-bucket
  dispatches are its dominant loss (the calibration point is a FULL
  bucket, so a batch of 3 pays the same round trip for 0.6% of the
  lanes); per-dispatch host overhead inside the span is the rest.  May
  exceed 1.0 when the live run beats a noisy CPU probe — left
  unclamped, because clamping would break the identity.
- ``useful_fraction = useful_lanes / dispatched_lanes``
  (padding is the loss)

so ``ceiling × busy × fill × useful ≡ useful_lanes / wall_s`` — the
factor-product invariant tests/test_ledger.py pins to fp tolerance.
Reading it is perf/UTILIZATION.md's job; emitting it into the bench
artifact (``*_util_*`` keys) is bench.py's.

Multichip readiness: the ledger carries ``n_devices`` (the engine's
mesh width) and reports per-device rates alongside the pooled ones, so
the multichip engine pool lands into an accounting that already has the
axis.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional


@dataclasses.dataclass
class QueueWindow:
    """One queue's accounting over the snapshot window (all fields are
    deltas against the ledger's baseline)."""

    name: str
    side: str  # "verify" | "sign"
    wall_s: float
    busy_s: float  # clamped to wall_s; raw overlap kept alongside
    device_time_s: float  # unclamped dispatch-span sum (may exceed wall)
    useful_lanes: int
    padded_lanes: int
    memo_lanes: int
    fallback_lanes: int
    batches: int

    @property
    def idle_s(self) -> float:
        return max(self.wall_s - self.busy_s, 0.0)

    @property
    def dispatched_lanes(self) -> int:
        return self.useful_lanes + self.padded_lanes

    @property
    def total_lanes(self) -> int:
        """Every lane of demand the window saw: dispatched (useful +
        padding) plus the lanes dedup absorbed and host crypto served.
        The four classes sum to this BY DEFINITION — the invariant test
        exists to catch a future field being added to one side only."""
        return (self.useful_lanes + self.padded_lanes
                + self.memo_lanes + self.fallback_lanes)

    @property
    def mean_batch(self) -> float:
        return self.useful_lanes / self.batches if self.batches else 0.0


@dataclasses.dataclass
class Decomposition:
    """The headroom identity, evaluated for one queue window."""

    ceiling_per_sec: float
    ceiling_source: str
    busy_fraction: float
    fill_efficiency: float
    useful_fraction: float
    effective_per_sec: float
    n_devices: int

    @property
    def per_device_effective_per_sec(self) -> float:
        return self.effective_per_sec / max(self.n_devices, 1)

    def product(self) -> float:
        """``ceiling × busy × fill × useful`` — equals
        ``effective_per_sec`` to fp tolerance (the pinned invariant)."""
        return (self.ceiling_per_sec * self.busy_fraction
                * self.fill_efficiency * self.useful_fraction)


class DeviceLedger:
    """Windowed utilization accounting over one engine.

    Construct AFTER any warm-up stats reset (the baseline is captured at
    construction); call :meth:`snapshot` at the end of the measured
    window.  Purely read-side: the ledger only ever reads the engine's
    existing stats snapshots (GIL-atomic dict/int reads, the same
    contract the Prometheus scrape uses), so attaching one costs the
    hot path nothing — the disabled-path A/B test pins that.
    """

    def __init__(self, engine, now: Optional[float] = None):
        self.engine = engine
        self._t0 = time.monotonic() if now is None else now
        self._base = self._capture()
        # BatchVerifier stores its mesh as ``mesh``; synthetic test
        # engines (and the original ledger contract) use ``_mesh`` —
        # honour both so a mesh-routed engine reports its real width.
        mesh = getattr(engine, "mesh", None)
        if mesh is None:
            mesh = getattr(engine, "_mesh", None)
        self.n_devices = int(mesh.size) if mesh is not None else 1
        self._ceilings: Dict[str, tuple] = {}  # name -> (rate, source)

    def _capture(self) -> Dict[tuple, dict]:
        snap: Dict[tuple, dict] = {}
        for name, st in self.engine.stats.items():
            snap[("verify", name)] = {
                "items": st.items, "batches": st.batches,
                "padded": st.padded_lanes, "memo": st.memo_hits,
                "fallback": 0, "device_s": st.device_time_s,
            }
        for name, st in self.engine.sign_stats.items():
            snap[("sign", name)] = {
                "items": st.items, "batches": st.batches,
                "padded": st.padded_lanes, "memo": 0,
                "fallback": st.host_fallback_items,
                "device_s": st.device_time_s,
            }
        return snap

    def set_ceiling(self, queue: str, lanes_per_sec: float,
                    source: str) -> None:
        """Record the calibrated full-batch lane rate for ``queue``.
        ``source`` says where the number came from (``cpu-probe`` /
        ``last_tpu:BENCH_rNN.json``) — a ceiling without provenance is
        how CPU and chip numbers get confused (the standing VERDICT
        caution)."""
        if lanes_per_sec <= 0:
            raise ValueError("ceiling must be positive")
        self._ceilings[queue] = (float(lanes_per_sec), source)

    @staticmethod
    def probe_ceiling(dispatch, pad_item, bucket: int) -> float:
        """One-shot CPU calibration: time one full-bucket dispatch of
        pad items through the queue's own dispatch function.  Run it on
        a WARM queue (after the kernel compiled) or the probe times the
        compiler."""
        t = time.perf_counter()
        dispatch([pad_item] * bucket)
        dt = time.perf_counter() - t
        return bucket / dt if dt > 0 else float(bucket)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, QueueWindow]:
        """Per-queue window accounting since construction, keyed
        ``{side}:{name}``."""
        wall = max((time.monotonic() if now is None else now) - self._t0,
                   1e-9)
        cur = self._capture()
        out: Dict[str, QueueWindow] = {}
        for key, c in cur.items():
            side, name = key
            b = self._base.get(key, {
                "items": 0, "batches": 0, "padded": 0, "memo": 0,
                "fallback": 0, "device_s": 0.0,
            })
            d = {k: c[k] - b[k] for k in c}
            if d["items"] <= 0 and d["batches"] <= 0:
                continue
            fallback = max(d["fallback"], 0)
            # Sign items count EVERY accepted item; host-fallback items
            # never crossed the device, so useful device lanes exclude
            # them (verify's fallback is always 0).
            useful = max(d["items"] - fallback, 0)
            out[f"{side}:{name}"] = QueueWindow(
                name=name, side=side, wall_s=wall,
                busy_s=min(max(d["device_s"], 0.0), wall),
                device_time_s=max(d["device_s"], 0.0),
                useful_lanes=useful,
                padded_lanes=max(d["padded"], 0),
                memo_lanes=max(d["memo"], 0),
                fallback_lanes=fallback,
                batches=max(d["batches"], 0),
            )
        return out

    def decompose(self, win: QueueWindow,
                  ceiling: Optional[float] = None,
                  source: Optional[str] = None) -> Decomposition:
        """Evaluate the headroom identity for one queue window.  With no
        calibrated ceiling available the window's OWN achieved busy lane
        rate is used (source ``self``): the decomposition then reads
        fill_efficiency = 1.0 by construction and still attributes busy
        vs idle vs padding honestly."""
        if ceiling is None:
            stored = self._ceilings.get(win.name)
            if stored is not None:
                ceiling, source = stored
        if ceiling is None or ceiling <= 0:
            busy = max(win.busy_s, 1e-9)
            ceiling = win.dispatched_lanes / busy
            source = "self"
            if ceiling <= 0:
                ceiling = 1.0
        busy_fraction = win.busy_s / win.wall_s
        denom = ceiling * win.busy_s
        fill = win.dispatched_lanes / denom if denom > 0 else 0.0
        useful = (win.useful_lanes / win.dispatched_lanes
                  if win.dispatched_lanes else 0.0)
        return Decomposition(
            ceiling_per_sec=ceiling,
            ceiling_source=source or "unknown",
            busy_fraction=busy_fraction,
            fill_efficiency=fill,
            useful_fraction=useful,
            effective_per_sec=win.useful_lanes / win.wall_s,
            n_devices=self.n_devices,
        )

    def util_keys(self, prefix: str, queue: str,
                  now: Optional[float] = None) -> Dict[str, object]:
        """The bench-artifact key block for one queue: the decomposition
        factors, the lane classes, and the provenance stamps — the
        ``*_util_*`` schema bench.py documents and benchgate gates."""
        wins = self.snapshot(now=now)
        win = wins.get(f"verify:{queue}") or wins.get(f"sign:{queue}")
        if win is None:
            return {}
        dec = self.decompose(win)
        return {
            f"{prefix}_util_busy": round(dec.busy_fraction, 4),
            f"{prefix}_util_fill": round(dec.fill_efficiency, 4),
            f"{prefix}_util_useful": round(dec.useful_fraction, 4),
            f"{prefix}_util_effective_per_sec": round(
                dec.effective_per_sec, 1
            ),
            f"{prefix}_util_per_device_per_sec": round(
                dec.per_device_effective_per_sec, 1
            ),
            f"{prefix}_util_ceiling_per_sec": round(dec.ceiling_per_sec, 1),
            f"{prefix}_util_ceiling_source": dec.ceiling_source,
            f"{prefix}_util_idle_s": round(win.idle_s, 3),
            f"{prefix}_util_lanes_useful": win.useful_lanes,
            f"{prefix}_util_lanes_padding": win.padded_lanes,
            f"{prefix}_util_lanes_memo": win.memo_lanes,
            f"{prefix}_util_lanes_fallback": win.fallback_lanes,
        }


class PoolLedger:
    """Per-chip utilization ledgers over an
    :class:`~minbft_tpu.parallel.pool.EnginePool`, plus the pool
    aggregate.

    One :class:`DeviceLedger` per home-chip engine (and one for the
    striped engine when the pool has one), all sharing a single window
    start.  Three read-outs:

    - :meth:`chip_scores` — the per-chip ``busy × fill`` load scores the
      placement rebalance hook consumes;
    - :meth:`window` — ONE merged :class:`QueueWindow` for a queue
      across the whole pool, with mean-across-chips busy semantics (a
      striped dispatch occupies every chip for its span, so its busy
      seconds weigh ``chips``×);
    - :meth:`util_keys` — the bench-artifact block: per-chip
      ``{prefix}_chip{c}_util_busy``/``_util_fill`` + lane census, and
      the POOL-AGGREGATE block in the exact :meth:`DeviceLedger.util_keys`
      schema, where the aggregate ceiling is the per-chip ceiling ×
      pool width and ``effective_per_sec`` is the pool total.  The
      headroom identity holds for the aggregate by the same algebra
      (``ceiling×C × Σbusy/(C·wall) × lanes/(ceiling×Σbusy) ×
      useful/lanes ≡ useful/wall``), and a 1-chip pool's aggregate
      block is EXACTLY a bare DeviceLedger's — the differential test
      pins it.
    """

    def __init__(self, pool, now: Optional[float] = None):
        t = time.monotonic() if now is None else now
        self.pool = pool
        self.chips = len(pool.engines)
        self.chip_ledgers = [DeviceLedger(e, now=t) for e in pool.engines]
        striped = getattr(pool, "striped_engine", None)
        self.striped_ledger = (
            DeviceLedger(striped, now=t) if striped is not None else None
        )
        self._ceilings: Dict[str, tuple] = {}

    def set_ceiling(self, queue: str, lanes_per_sec: float,
                    source: str) -> None:
        """Per-CHIP calibrated lane rate (the aggregate scales it by the
        pool width); fans out to every chip ledger."""
        if lanes_per_sec <= 0:
            raise ValueError("ceiling must be positive")
        self._ceilings[queue] = (float(lanes_per_sec), source)
        for led in self.chip_ledgers:
            led.set_ceiling(queue, lanes_per_sec, source)
        if self.striped_ledger is not None:
            self.striped_ledger.set_ceiling(queue, lanes_per_sec, source)

    def _queue_win(self, led: "DeviceLedger", queue: str, now: float):
        wins = led.snapshot(now=now)
        return wins.get(f"verify:{queue}") or wins.get(f"sign:{queue}")

    def window(self, queue: str,
               now: Optional[float] = None) -> Optional[QueueWindow]:
        """The pool-merged window for ``queue``: lanes/batches summed,
        ``busy_s`` the mean across the pool's chips (striped spans weigh
        ``chips``×), so ``busy_s/wall_s`` reads as pool utilization and
        ``mean_batch`` as the pool-wide fill."""
        t = time.monotonic() if now is None else now
        parts = []  # (window, busy_weight)
        for led in self.chip_ledgers:
            win = self._queue_win(led, queue, t)
            if win is not None:
                parts.append((win, 1))
        if self.striped_ledger is not None:
            win = self._queue_win(self.striped_ledger, queue, t)
            if win is not None:
                parts.append((win, self.chips))
        if not parts:
            return None
        wall = max(w.wall_s for w, _ in parts)
        busy_chip_s = sum(w.busy_s * wt for w, wt in parts)
        return QueueWindow(
            name=queue,
            side=parts[0][0].side,
            wall_s=wall,
            busy_s=min(busy_chip_s / self.chips, wall),
            device_time_s=sum(w.device_time_s * wt for w, wt in parts),
            useful_lanes=sum(w.useful_lanes for w, _ in parts),
            padded_lanes=sum(w.padded_lanes for w, _ in parts),
            memo_lanes=sum(w.memo_lanes for w, _ in parts),
            fallback_lanes=sum(w.fallback_lanes for w, _ in parts),
            batches=sum(w.batches for w, _ in parts),
        )

    def chip_scores(self, queue: Optional[str] = None,
                    now: Optional[float] = None) -> list:
        """Per-chip ``busy × fill`` (the PR-9 product) since
        construction — the rebalance feed.  An untouched chip scores
        0.0.  ``queue=None`` aggregates each chip's active queues
        (busy summed and clamped, fill lane-weighted)."""
        t = time.monotonic() if now is None else now
        scores = []
        for led in self.chip_ledgers:
            wins = led.snapshot(now=t)
            if queue is not None:
                wins = {k: w for k, w in wins.items() if w.name == queue}
            if not wins:
                scores.append(0.0)
                continue
            wall = max(w.wall_s for w in wins.values())
            busy = min(sum(w.busy_s for w in wins.values())
                       / max(wall, 1e-9), 1.0)
            lanes = sum(w.dispatched_lanes for w in wins.values())
            if lanes > 0:
                fill = sum(
                    led.decompose(w).fill_efficiency * w.dispatched_lanes
                    for w in wins.values()
                ) / lanes
            else:
                fill = 1.0
            scores.append(round(busy * fill, 4))
        return scores

    def util_keys(self, prefix: str, queue: str,
                  now: Optional[float] = None) -> Dict[str, object]:
        """Per-chip attribution + the pool-aggregate ``*_util_*`` block
        (DeviceLedger schema, so the same benchgate suffix rules gate
        it)."""
        t = time.monotonic() if now is None else now
        out: Dict[str, object] = {}
        for c, led in enumerate(self.chip_ledgers):
            win = self._queue_win(led, queue, t)
            if win is None:
                continue
            dec = led.decompose(win)
            out[f"{prefix}_chip{c}_util_busy"] = round(dec.busy_fraction, 4)
            out[f"{prefix}_chip{c}_util_fill"] = round(dec.fill_efficiency, 4)
            out[f"{prefix}_chip{c}_util_lanes_useful"] = win.useful_lanes
            out[f"{prefix}_chip{c}_util_lanes_padding"] = win.padded_lanes
            out[f"{prefix}_chip{c}_util_lanes_memo"] = win.memo_lanes
            out[f"{prefix}_chip{c}_util_lanes_fallback"] = win.fallback_lanes
        if self.striped_ledger is not None:
            win = self._queue_win(self.striped_ledger, queue, t)
            if win is not None:
                out[f"{prefix}_stripe_util_lanes_useful"] = win.useful_lanes
                out[f"{prefix}_stripe_util_batches"] = win.batches
        merged = self.window(queue, now=t)
        if merged is None:
            return {}
        stored = self._ceilings.get(queue)
        if stored is not None:
            rate, source = stored
            if self.chips > 1:
                source = f"{source} x{self.chips}"
            dec = self.chip_ledgers[0].decompose(
                merged, ceiling=rate * self.chips, source=source
            )
        else:
            dec = self.chip_ledgers[0].decompose(merged)
        dec = dataclasses.replace(dec, n_devices=self.chips)
        out.update({
            f"{prefix}_util_busy": round(dec.busy_fraction, 4),
            f"{prefix}_util_fill": round(dec.fill_efficiency, 4),
            f"{prefix}_util_useful": round(dec.useful_fraction, 4),
            f"{prefix}_util_effective_per_sec": round(
                dec.effective_per_sec, 1
            ),
            f"{prefix}_util_per_device_per_sec": round(
                dec.per_device_effective_per_sec, 1
            ),
            f"{prefix}_util_ceiling_per_sec": round(dec.ceiling_per_sec, 1),
            f"{prefix}_util_ceiling_source": dec.ceiling_source,
            f"{prefix}_util_idle_s": round(merged.idle_s, 3),
            f"{prefix}_util_lanes_useful": merged.useful_lanes,
            f"{prefix}_util_lanes_padding": merged.padded_lanes,
            f"{prefix}_util_lanes_memo": merged.memo_lanes,
            f"{prefix}_util_lanes_fallback": merged.fallback_lanes,
        })
        return out
