"""Event-loop lag sampler: GIL/loop saturation as a first-class metric.

A periodic task sleeps a fixed interval and measures how late the loop
woke it (scheduled-vs-actual delta).  On a healthy loop the lag is
microseconds; when pure-Python crypto, a long handler, or GIL pressure
from engine worker threads holds the loop, every timer, heartbeat, and
protocol coroutine is delayed by exactly this much — the blind spot
that made host saturation invisible in the per-stage trace.

Samples land in a mergeable :class:`~minbft_tpu.obs.hist.Log2Histogram`
(one observe per tick — ~20 Hz by default, unmeasurable overhead),
exposed over Prometheus as ``minbft_eventloop_lag_seconds`` (prom.py)
and carried in the flight-recorder dump (``loop_lag`` extra) so the
cluster critical-path merge (obs/critpath.py) can attribute a
loop-saturation segment.

``MINBFT_LOOPLAG_INTERVAL`` overrides the sampling interval in seconds;
``0`` disables the sampler entirely.
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional

from .hist import Log2Histogram

INTERVAL_ENV = "MINBFT_LOOPLAG_INTERVAL"
DEFAULT_INTERVAL = 0.05


class LoopLagSampler:
    """Samples the owning event loop's scheduling lag into ``hist``.

    Single-task, loop-confined: ``start`` must run on the loop being
    measured; ``stop`` cancels the task.  The histogram may be a shared
    one (ReplicaMetrics.loop_lag) — observes are loop-side, scrape
    threads only read (the standard monitoring contract).
    """

    def __init__(self, hist: Optional[Log2Histogram] = None,
                 interval: float = DEFAULT_INTERVAL):
        self.hist = hist if hist is not None else Log2Histogram()
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="minbft-looplag"
            )

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.interval
        hist = self.hist
        while True:
            target = loop.time() + interval
            await asyncio.sleep(interval)
            # sleep() never wakes early; a negative delta here is loop
            # clock weirdness and lands in the hist's negatives counter.
            hist.observe(loop.time() - target)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


def maybe_sampler(hist: Log2Histogram) -> Optional[LoopLagSampler]:
    """A sampler at the env-configured interval, or None when disabled
    (``MINBFT_LOOPLAG_INTERVAL=0``)."""
    try:
        interval = float(os.environ.get(INTERVAL_ENV, "") or DEFAULT_INTERVAL)
    except ValueError:
        interval = DEFAULT_INTERVAL
    if interval <= 0:
        return None
    return LoopLagSampler(hist, interval)
