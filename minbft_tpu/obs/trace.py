"""Protocol flight recorder: per-request stage spans.

Every pipeline hook notes a (request-key, stage, monotonic-ns) event:

- **replica** capture points: ``recv`` → ``verify_enqueue`` →
  ``verify_done`` → ``prepare`` → ``commit_quorum`` → ``execute`` →
  ``reply_sign`` → ``reply_sent``;
- **client** capture points: ``start`` → ``sign`` → ``broadcast`` →
  ``first_reply`` → ``quorum``.

Two artifacts come out of a note:

1. the raw event lands in a **preallocated ring buffer** (forensics:
   the JSON trace dump carries the tail of the run, request by request);
2. the duration since the request's PREVIOUS noted point is folded into
   that stage's :class:`~minbft_tpu.obs.hist.Log2Histogram` — so
   ``stage_commit_quorum`` reads "time from prepare to commit quorum",
   and the histograms answer "where does a committed request's time go"
   without post-processing (and merge across replicas, unlike a
   reservoir).

Cost discipline (the ISSUE's contract): with tracing disabled every hook
is ONE predicated attribute check (``if tr is not None``) — the recorder
simply doesn't exist.  Enabled, a note is two dict operations, four
array stores into the preallocated ring, and one histogram increment; no
per-event object survives the call.

Threading: a :class:`StageRing` has a SINGLE writer (the event loop) and
is deliberately lock-free — asyncio callbacks never preempt mid-push.
Engine worker threads must never touch it; they get their own
:class:`MTStageRing`, whose push/drain are serialized by its lock (the
same locked-writes discipline as the engine's ``_stats_lock`` stats;
``tools/analyze`` lock-discipline enforces both).
"""

from __future__ import annotations

import json
import os
import time
from array import array
from typing import Dict, Iterable, List, Optional, Tuple

from .hist import Log2Histogram

# Replica capture points, in pipeline order.  ``ingest`` is the
# bundle-runtime entry (the tick that decoded this request's frame
# bundle); ``recv`` is the legacy per-task entry (MINBFT_BUNDLE_INGEST=0)
# — both are ENTRY stages (they open spans, never record durations), so
# retransmit gaps can't pollute the cost table on either path.
REPLICA_STAGES: Tuple[str, ...] = (
    "ingest",
    "recv",
    "verify_enqueue",
    "verify_done",
    "prepare",
    "commit_quorum",
    "execute",
    "reply_sign",
    "reply_sent",
)
R_INGEST = 0
R_RECV = 1
R_VERIFY_ENQUEUE = 2
R_VERIFY_DONE = 3
R_PREPARE = 4
R_COMMIT_QUORUM = 5
R_EXECUTE = 6
R_REPLY_SIGN = 7
R_REPLY_SENT = 8
# Stages that never close a span (see FlightRecorder.note).
_REPLICA_ENTRY_STAGES = frozenset((R_INGEST, R_RECV))

# Client capture points ("start" is the implicit entry of request()).
CLIENT_STAGES: Tuple[str, ...] = (
    "start",
    "sign",
    "broadcast",
    "first_reply",
    "quorum",
)
C_START = 0
C_SIGN = 1
C_BROADCAST = 2
C_FIRST_REPLY = 3
C_QUORUM = 4

# Environment knobs (read once per recorder construction, never per event).
TRACE_ENV = "MINBFT_TRACE"
TRACE_DUMP_ENV = "MINBFT_TRACE_DUMP"
_RING_ENV = "MINBFT_TRACE_RING"

_DEFAULT_RING = 1 << 15
# In-flight pairing state is bounded: a key whose final stage never
# arrives (dropped request) would leak its entry, so past this many keys
# the map is reset wholesale — losing pairing for the requests in flight
# at that instant, never memory.
_MAX_INFLIGHT_KEYS = 1 << 16


def tracing_enabled() -> bool:
    """True when the operator asked for tracing: ``MINBFT_TRACE`` set to
    anything but the usual falsy spellings (so ``MINBFT_TRACE=0``
    DISABLES, matching the repo's env-flag convention), or a
    ``MINBFT_TRACE_DUMP`` path (any non-empty value — it names a file
    prefix, not a flag)."""
    flag = os.environ.get(TRACE_ENV, "")
    if flag.lower() not in ("", "0", "false", "no"):
        return True
    return bool(os.environ.get(TRACE_DUMP_ENV))


class StageRing:
    """Preallocated single-writer ring of (a, b, stage, t_ns) events.

    Four parallel ``array('q')`` columns: a push is four C-level stores
    plus two int updates — no allocation, no lock.  ONLY the owning
    event loop may push; cross-thread producers use :class:`MTStageRing`.
    """

    __slots__ = ("_a", "_b", "_c", "_t", "_cap", "_idx", "_n")

    def __init__(self, capacity: int = _DEFAULT_RING):
        cap = 1
        while cap < max(2, capacity):
            cap <<= 1
        self._cap = cap
        self._a = array("q", bytes(8 * cap))
        self._b = array("q", bytes(8 * cap))
        self._c = array("q", bytes(8 * cap))
        self._t = array("q", bytes(8 * cap))
        self._idx = 0  # next write slot
        self._n = 0  # valid entries (saturates at _cap)

    def push(self, a: int, b: int, c: int, t_ns: int) -> None:
        i = self._idx
        self._a[i] = a
        self._b[i] = b
        self._c[i] = c
        self._t[i] = t_ns
        self._idx = (i + 1) & (self._cap - 1)
        if self._n < self._cap:
            self._n += 1

    def __len__(self) -> int:
        return self._n

    @property
    def capacity(self) -> int:
        return self._cap

    def snapshot(self, limit: Optional[int] = None) -> List[Tuple[int, int, int, int]]:
        """Events oldest→newest (optionally only the newest ``limit``)."""
        n = self._n
        if limit is not None:
            n = min(n, limit)
        start = (self._idx - n) & (self._cap - 1)
        out = []
        for k in range(n):
            i = (start + k) & (self._cap - 1)
            out.append((self._a[i], self._b[i], self._c[i], self._t[i]))
        return out


class MTStageRing(StageRing):
    """Multi-producer sibling of :class:`StageRing`: engine worker
    threads (up to ``max_inflight`` concurrent dispatchers) push under
    the ring's lock, and drains hold the same lock — the locked-writes
    discipline ``tools/analyze`` enforces for every cross-thread
    mutation in this codebase.  Same storage/wrap semantics as the
    base; only the lock wrapping differs."""

    __slots__ = ("_lock",)

    def __init__(self, capacity: int = 4096):
        import threading

        super().__init__(capacity)
        self._lock = threading.Lock()

    def push(self, a: int, b: int, c: int, t_ns: int) -> None:
        with self._lock:
            super().push(a, b, c, t_ns)

    def __len__(self) -> int:
        with self._lock:
            return super().__len__()

    def snapshot(self, limit: Optional[int] = None) -> List[Tuple[int, int, int, int]]:
        with self._lock:
            return super().snapshot(limit)


class FlightRecorder:
    """Stage-span recorder for one replica or client.

    ``note(stage, cid, seq)`` is THE hot-path entry point; everything
    else (snapshots, dumps, tables) is cold-path reporting.  Histograms
    may be read by a scrape thread while the loop writes — int mutations
    are GIL-atomic, so a reader sees a slightly stale but never torn
    view (standard monitoring semantics).
    """

    def __init__(
        self,
        kind: str,
        ident: int,
        stages: Tuple[str, ...],
        ring_capacity: Optional[int] = None,
        entry_stages: Optional[frozenset] = None,
        group: Optional[int] = None,
    ):
        if ring_capacity is None:
            ring_capacity = int(os.environ.get(_RING_ENV, _DEFAULT_RING))
        self.kind = kind  # "replica" | "client" | "engine"
        self.ident = ident
        # Consensus-group id (multi-group runtime): stamped into dumps so
        # stage_table/critpath_table can filter one group's spans out of
        # a shared-process dump set; None = ungrouped.
        self.group = group
        self.stages = stages
        self.ring = StageRing(ring_capacity)
        self.hists: List[Log2Histogram] = [Log2Histogram() for _ in stages]
        self._final = len(stages) - 1
        # Pipeline entries: stages that open a span but never close one
        # (a retransmission re-noting an entry mid-pipeline must not fold
        # its gap into the cost table).  Default: stage 0 only.
        self._entries = frozenset((0,)) if entry_stages is None else entry_stages
        # (cid, seq) -> monotonic-ns of the previous noted point.
        self._last: Dict[Tuple[int, int], int] = {}

    @staticmethod
    def for_replica(
        replica_id: int, group: Optional[int] = None
    ) -> "FlightRecorder":
        return FlightRecorder(
            "replica",
            replica_id,
            REPLICA_STAGES,
            entry_stages=_REPLICA_ENTRY_STAGES,
            group=group,
        )

    @staticmethod
    def for_client(
        client_id: int, group: Optional[int] = None
    ) -> "FlightRecorder":
        return FlightRecorder("client", client_id, CLIENT_STAGES, group=group)

    def note(self, stage: int, cid: int, seq: int) -> None:
        t = time.monotonic_ns()
        self.ring.push(cid, seq, stage, t)
        key = (cid, seq)
        last = self._last
        prev = last.get(key)
        if prev is not None and stage not in self._entries:
            # Entry stages (ingest/recv on replicas, start on clients)
            # open spans but never close one — a client retransmission
            # re-noting an entry mid-pipeline would otherwise fold the
            # 30s retransmit gap into the cost table as "recv time".
            # (The raw ring still keeps the duplicate arrival for
            # forensics.)
            self.hists[stage].observe_ns(t - prev)
        if stage == self._final:
            last.pop(key, None)
        else:
            if len(last) >= _MAX_INFLIGHT_KEYS:
                last.clear()
            last[key] = t

    # -- reporting ------------------------------------------------------

    def stage_hists(self) -> Dict[str, Log2Histogram]:
        """Stage name -> histogram of "time from the previous noted
        point to this point" (entry points with no predecessor record
        nothing)."""
        return {
            name: h
            for name, h in zip(self.stages, self.hists)
            if h.count
        }

    def to_dict(self, max_events: int = 4096) -> dict:
        doc = {
            "kind": self.kind,
            "id": self.ident,
            "stages": list(self.stages),
            "clock_domain": clock_domain(),
            "hists": {n: h.to_dict() for n, h in self.stage_hists().items()},
            "events": [
                list(e) for e in self.ring.snapshot(limit=max_events)
            ],
        }
        if self.group is not None:
            doc["group"] = self.group
        return doc


# ---------------------------------------------------------------------------
# JSON trace dumps (MINBFT_TRACE_DUMP=path) and the bench stage table.


def clock_domain() -> str:
    """Identity of this process's monotonic-clock domain, stamped into
    every dump: ``time.monotonic`` reads the system-wide boot-relative
    CLOCK_MONOTONIC, so EVERY process on one host (one boot) shares the
    epoch — dumps with equal domains merge with zero offset and zero
    uncertainty, and only genuinely cross-host dumps pay the
    Cristian-style estimation (obs/clockalign.py).  Containers with
    private hostnames conservatively fall into separate domains even
    when the kernel clock is shared — estimation is the safe default,
    exactness the proven special case."""
    import socket

    return socket.gethostname()


def dump_path_for(
    kind: str,
    ident: int,
    base: Optional[str] = None,
    group: Optional[int] = None,
) -> Optional[str]:
    """Per-process-safe dump path: ``{base}.{r|c}{id}.json`` (multiple
    replicas/clients — in one process or many — never clobber).  Grouped
    recorders append ``g{group}``: a GroupRuntime's G cores share one
    replica id, so the group must be part of the filename or the cores'
    dumps clobber each other."""
    base = base if base is not None else os.environ.get(TRACE_DUMP_ENV)
    if not base:
        return None
    tag = {"replica": "r", "client": "c"}.get(kind, kind)
    gtag = "" if group is None else f"g{group}"
    return f"{base}.{tag}{ident}{gtag}.json"


def dump_recorder(rec: FlightRecorder, base: Optional[str] = None,
                  extra: Optional[dict] = None) -> Optional[str]:
    """Write one recorder's dump; returns the path (None when the dump
    env/base is unset — the recorder may be enabled for live scraping
    only)."""
    path = dump_path_for(rec.kind, rec.ident, base, group=rec.group)
    if path is None:
        return None
    doc = rec.to_dict()
    # Incarnation attribution (ISSUE 14): every dump says which process
    # produced it, so cross-node mergers can refuse to splice a restarted
    # replica onto its predecessor's timeline (obs/critpath.py) and a
    # merged artifact's numbers stay traceable to concrete pids/revs.
    # ``extra`` may override (tests construct synthetic incarnations).
    from . import runinfo

    doc.setdefault("run_id", runinfo.RUN_ID)
    doc.setdefault("build", runinfo.build_info())
    if extra:
        doc.update(extra)
    # noqa: AH102 - one-shot crash/shutdown dump; forensics cannot rely on executors
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def load_dumps(base: str) -> List[dict]:
    """Load every ``{base}.*.json`` trace dump (bench ingestion)."""
    import glob

    docs = []
    for path in sorted(glob.glob(base + ".*.json")):
        try:
            # noqa: AH102 - one-shot ingestion at bench report time
            with open(path) as fh:
                docs.append(json.load(fh))
        except (OSError, ValueError):
            continue
    return docs


def filter_group(docs: Iterable[dict], group: Optional[int]) -> List[dict]:
    """Restrict a dump set to one consensus group: docs stamped with a
    DIFFERENT group are dropped; unstamped docs (ungrouped recorders,
    shared engine docs, clients without a group label) are kept — the
    engine queues really are shared across groups, so excluding their
    doc would just lose the queue-wait attribution.  ``group=None`` is
    the identity."""
    docs = list(docs)
    if group is None:
        return docs
    return [d for d in docs if d.get("group") in (None, group)]


def merged_stage_hists(docs: Iterable[dict]) -> Dict[str, Log2Histogram]:
    """Merge dumped stage histograms across recorders.  Client stages
    are namespaced (``client_sign``...) so the one table carries both
    sides without key collisions; replica stages keep their bare names."""
    out: Dict[str, Log2Histogram] = {}
    for doc in docs:
        prefix = "client_" if doc.get("kind") == "client" else ""
        for name, hd in (doc.get("hists") or {}).items():
            h = Log2Histogram.from_dict(hd)
            key = prefix + name
            if key in out:
                out[key].merge(h)
            else:
                out[key] = h
    return out


def stage_table(
    docs: Iterable[dict], prefix: str, group: Optional[int] = None
) -> dict:
    """The bench's per-stage cost-breakdown keys:

    - ``{prefix}_stage_{name}_p50_ms`` — median time from the previous
      capture point to ``name`` (merged across every dumped recorder);
    - ``{prefix}_stage_{name}_share`` — that stage's fraction of the
      total replica-side recorded time (client stages overlap the
      replica pipeline by construction, so shares are computed over the
      replica stages only — they sum to 1.0).

    ``group`` restricts the table to one consensus group's recorders
    (see :func:`filter_group`) — the multi-group runtime dumps every
    core into one dump set.

    Returns {} when no dump carries histogram data, so a tracing-disabled
    bench emits byte-identical keys to a tracing-absent one.
    """
    hists = merged_stage_hists(filter_group(docs, group))
    if not hists:
        return {}
    out: dict = {}
    replica_total = sum(
        h.total_s for n, h in hists.items() if not n.startswith("client_")
    )
    for name, h in sorted(hists.items()):
        out[f"{prefix}_stage_{name}_p50_ms"] = round(h.percentile(50) * 1e3, 3)
        if not name.startswith("client_") and replica_total > 0:
            out[f"{prefix}_stage_{name}_share"] = round(
                h.total_s / replica_total, 4
            )
    return out
