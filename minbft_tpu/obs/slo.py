"""Latency-SLO engine: per-request finality budgets, multi-window
burn-rate telemetry, and breach-triggered forensic auto-dump.

The flight recorder (obs/trace.py), the critical path (obs/critpath.py),
and the telemetry rings (obs/timeseries.py) record *where time goes*;
this module is the first layer that says whether a request *met its
deadline*.  Four pieces, each riding an existing surface:

- :class:`SLOPolicy` — the budget: target finality milliseconds plus an
  objective fraction (the classic "99% of writes commit inside 1s").
  Configured per group via consensus.yaml (``protocol.slo_target`` /
  ``protocol.slo_objective``) or the ``MINBFT_SLO_*`` env knobs; the
  env value accepts a comma list so a grouped runtime can give group 0
  a tighter budget than its batch-tolerant siblings.
- :class:`BudgetLedger` — the per-request classifier.  ``arrive`` stamps
  a request's first entry into the replica (recv-origin — the honest
  default when no load-generator metadata exists); ``commit`` pops the
  stamp at commit-quorum time and classes the request good/breached
  against the budget.  Single-writer (the replica's event loop), two int
  increments on the hot path, and — exactly like the flight recorder —
  a *disabled* SLO engine costs the pipeline one predicated attribute
  check per hook (``if sl is not None``), nothing else.
- **Burn-rate telemetry** — ``register_slo_series`` feeds the good /
  breached counters into the PR-9 :class:`~.timeseries.TimeSeries`
  rings as rate series, so :func:`burn_rates` can read a fast (~5s) and
  a slow (~60s) window and report each as a multiple of the sustainable
  error-budget spend rate (burn 1.0 = exactly exhausting the budget;
  the alerting convention from the SRE workbook).  Because the rings
  merge slot-wise exactly, cluster-level burn is computable from
  per-process dumps with no approximation.
- **Breach forensics** — :class:`BreachSpool` writes ONE bounded
  snapshot bundle (flight-recorder docs + timeseries ring + util block
  + the breach attribution below + build stamp) when the fast-window
  burn crosses ``policy.burn_threshold``, behind a token bucket
  (default: one bundle, refilled every ``MINBFT_SLO_DUMP_REFILL_S``)
  and a spool-size bound, so a sustained breach can never fill a disk.

Breach attribution (:func:`breach_report`): every breached request's
budget spend is split across the PR-7 critpath segments — so a breach
names its thief (queue_wait vs commit vs reply_sign).  When client
trace dumps exist the full client-origin :func:`~.critpath.cluster_paths`
merge is used; a replica-only dump set (the loadgen harness keeps no
client recorders) falls back to recv-origin paths built from the
replica stages alone.  When a load-generator metadata doc is present
(``kind: "loadgen"``, written by the open-loop harness), classification
switches to SCHEDULED-origin latencies — the coordinated-omission rule
from perf/LOAD.md — and the pre-entry wait is attributed to an explicit
``sched_wait`` segment, so per-request segments still sum exactly to
the classified spend (the invariant tests/test_slo.py pins).
"""

from __future__ import annotations

import asyncio
import dataclasses
import glob
import json
import os
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from . import clockalign, runinfo
from .critpath import RequestPath, cluster_paths
from .trace import filter_group

# Environment knobs (tools/analyze/ENV_VARS.md registers every one).
SLO_ENV = "MINBFT_SLO"
TARGET_ENV = "MINBFT_SLO_TARGET_MS"
OBJECTIVE_ENV = "MINBFT_SLO_OBJECTIVE"
FAST_WINDOW_ENV = "MINBFT_SLO_FAST_WINDOW_S"
SLOW_WINDOW_ENV = "MINBFT_SLO_SLOW_WINDOW_S"
BURN_THRESHOLD_ENV = "MINBFT_SLO_BURN_THRESHOLD"
DUMP_ENV = "MINBFT_SLO_DUMP"
DUMP_MAX_ENV = "MINBFT_SLO_DUMP_MAX"
DUMP_REFILL_ENV = "MINBFT_SLO_DUMP_REFILL_S"

# In-flight origin stamps are bounded exactly like the flight recorder's
# pairing map: a request that never commits (shed, timed out client)
# would leak its stamp, so past this many keys the map resets wholesale.
_MAX_INFLIGHT_KEYS = 1 << 16

# The replica-origin attribution segments (a strict subset of
# critpath.SEGMENTS, in the same causal order) plus the two extras this
# module owns: ``sched_wait`` (scheduled arrival -> replica entry, only
# when loadgen metadata supplies scheduled origins) and the telescoping
# ``unattributed`` residual.
REPLICA_SEGMENTS: Tuple[str, ...] = (
    "preverify",
    "verify",
    "prepare_wait",
    "commit",
    "execute",
    "reply_sign",
    "reply_send",
    "unattributed",
)
SCHED_WAIT_SEGMENT = "sched_wait"


def _flag_truthy(value: str) -> bool:
    return value.lower() not in ("", "0", "false", "no")


def slo_enabled(configer=None) -> bool:
    """True when the operator asked for SLO accounting: ``MINBFT_SLO``
    set truthy (``MINBFT_SLO=0`` disables, the repo's env-flag
    convention), a ``MINBFT_SLO_DUMP`` spool path, an explicit
    ``MINBFT_SLO_TARGET_MS``, or a configer carrying ``slo_target_ms``
    (consensus.yaml ``protocol.slo_target``)."""
    if _flag_truthy(os.environ.get(SLO_ENV, "")):
        return True
    if os.environ.get(DUMP_ENV) or os.environ.get(TARGET_ENV):
        return True
    return getattr(configer, "slo_target_ms", None) is not None


def _group_entry(raw: str, group: Optional[int], default: float) -> float:
    """Parse a scalar-or-comma-list env value per group: ``"1000"``
    applies everywhere, ``"1000,500"`` gives group 0 the first entry,
    group 1 (and every later group) the last — a short list extends its
    final entry rather than erroring, so adding a group never silently
    drops SLO coverage."""
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    if not parts:
        return default
    idx = 0 if group is None else min(group, len(parts) - 1)
    try:
        return float(parts[idx])
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """One group's finality budget and its alerting windows."""

    target_ms: float = 1000.0
    objective: float = 0.99  # fraction of requests that must meet target
    fast_window_s: float = 5.0
    slow_window_s: float = 60.0
    # Fast-window burn multiple that trips forensics / the `peer top`
    # BREACH flag.  8x mirrors the short-window page threshold from the
    # multiwindow burn-rate alerting recipe: fast enough to catch a
    # wedge in seconds, high enough that a single straggler cannot.
    burn_threshold: float = 8.0

    @property
    def budget_ns(self) -> float:
        return self.target_ms * 1e6

    @property
    def error_budget(self) -> float:
        """Allowed breach fraction (0.01 for a 99% objective); floored
        so a 100% objective cannot divide burn by zero."""
        return max(1.0 - self.objective, 1e-9)

    @staticmethod
    def from_env(group: Optional[int] = None,
                 configer=None) -> "SLOPolicy":
        """Resolve the policy for one group: configer fields (parsed
        from consensus.yaml) first, ``MINBFT_SLO_*`` env on top — the
        same layering every other protocol knob uses."""
        target = getattr(configer, "slo_target_ms", None)
        objective = getattr(configer, "slo_objective", None)
        target = float(target) if target is not None else 1000.0
        objective = float(objective) if objective is not None else 0.99
        raw = os.environ.get(TARGET_ENV, "")
        if raw:
            target = _group_entry(raw, group, target)
        raw = os.environ.get(OBJECTIVE_ENV, "")
        if raw:
            objective = _group_entry(raw, group, objective)
        return SLOPolicy(
            target_ms=target,
            objective=objective,
            fast_window_s=float(
                os.environ.get(FAST_WINDOW_ENV, "") or 5.0
            ),
            slow_window_s=float(
                os.environ.get(SLOW_WINDOW_ENV, "") or 60.0
            ),
            burn_threshold=float(
                os.environ.get(BURN_THRESHOLD_ENV, "") or 8.0
            ),
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class BudgetLedger:
    """Per-replica-core good/breached accounting (recv-origin).

    Single-writer: only the owning event loop calls :meth:`arrive` /
    :meth:`commit` (the StageRing discipline; tools/analyze pins it).
    Scrape threads read the int counters GIL-atomically, the same
    slightly-stale-never-torn contract every other metric keeps.
    """

    __slots__ = (
        "policy", "group", "good", "breached", "breached_budget_ns",
        "_origin",
    )

    def __init__(self, policy: SLOPolicy, group: Optional[int] = None):
        self.policy = policy
        self.group = group
        self.good = 0
        self.breached = 0
        # Summed recv-origin latency of every breached request — the
        # "budget spend" the breach attribution must account for.
        self.breached_budget_ns = 0
        self._origin: Dict[Tuple[int, int], int] = {}

    def arrive(self, cid: int, seq: int) -> None:
        """Stamp a request's FIRST entry (recv/ingest).  Retransmissions
        keep the original stamp — the client has been waiting since the
        first arrival, and resetting the clock would be coordinated
        omission at the replica."""
        origin = self._origin
        if (cid, seq) not in origin:
            if len(origin) >= _MAX_INFLIGHT_KEYS:
                origin.clear()
            origin[(cid, seq)] = time.monotonic_ns()

    def commit(self, cid: int, seq: int) -> Optional[bool]:
        """Classify at commit-quorum time; returns True (good) / False
        (breached) / None (origin unknown: stamp evicted, or the commit
        arrived via state transfer without a client arrival)."""
        t0 = self._origin.pop((cid, seq), None)
        if t0 is None:
            return None
        lat_ns = time.monotonic_ns() - t0
        if lat_ns <= self.policy.budget_ns:
            self.good += 1
            return True
        self.breached += 1
        self.breached_budget_ns += lat_ns
        return False

    @property
    def total(self) -> int:
        return self.good + self.breached

    def good_fraction(self) -> float:
        t = self.total
        return self.good / t if t else 1.0

    def budget_remaining(self) -> float:
        """Remaining error-budget fraction over this ledger's lifetime:
        1.0 = untouched, 0.0 = exactly spent, negative = overspent (the
        overshoot is informative, so it is not clamped)."""
        t = self.total
        if t == 0:
            return 1.0
        return 1.0 - (self.breached / t) / self.policy.error_budget


def series_name(base: str, group: Optional[int]) -> str:
    """Ring-series name for one group's SLO counter (the
    ``register_replica_series`` suffix convention)."""
    return base if group is None else f"{base}_g{group}"


def register_slo_series(sampler, ledger: BudgetLedger) -> None:
    """Feed one ledger's cumulative counters into the sampler's ring as
    rate series (``slo_good`` / ``slo_breached``, per-group suffixed).
    Counter deltas into slot-exact rings: cluster burn rates merge
    across processes with zero approximation."""
    sampler.add_rate(
        series_name("slo_good", ledger.group), lambda: ledger.good
    )
    sampler.add_rate(
        series_name("slo_breached", ledger.group), lambda: ledger.breached
    )


def _series_sum(window: Dict[str, float], base: str,
                group: Optional[int]) -> float:
    if group is not None:
        return window.get(f"{base}_g{group}", 0.0)
    return sum(
        v for name, v in window.items()
        if name == base or name.startswith(base + "_g")
    )


def burn_rates(ts, policy: SLOPolicy, now: Optional[float] = None,
               group: Optional[int] = None) -> dict:
    """Multi-window burn rates from a (possibly merged) ring.

    Burn = (breached fraction in the window) / (allowed breach
    fraction): 1.0 spends the error budget exactly as fast as the
    objective allows, ``policy.burn_threshold`` (default 8x) trips
    forensics.  An idle window burns 0 — no traffic spends no budget —
    but a window where EVERY request breached burns ``1/error_budget``
    regardless of rate, so a stalled-but-trickling group still pages.
    ``group=None`` aggregates every group's series (cluster burn)."""
    out = {
        "fast_window_s": policy.fast_window_s,
        "slow_window_s": policy.slow_window_s,
        "burn_threshold": policy.burn_threshold,
    }
    for tag, seconds in (
        ("fast", policy.fast_window_s), ("slow", policy.slow_window_s)
    ):
        win = ts.window(seconds, now=now)
        good = _series_sum(win, "slo_good", group)
        breached = _series_sum(win, "slo_breached", group)
        total = good + breached
        frac = breached / total if total > 0 else 0.0
        out[f"{tag}_good_per_sec"] = round(good, 3)
        out[f"{tag}_breached_per_sec"] = round(breached, 3)
        out[f"{tag}_burn"] = round(frac / policy.error_budget, 3)
    return out


# ---------------------------------------------------------------------------
# Breach attribution: where did the breached requests' budget go?


def _replica_paths(docs: List[dict],
                   quorum: Optional[int] = None) -> List[RequestPath]:
    """Recv-origin request paths from replica dumps alone (no client
    recorders — the loadgen shape).  Origin is the PRIMARY's entry note;
    the tail stages are rank-(f+1) across every replica that observed
    them (the critpath rank coupling); segments telescope so they sum to
    the total by construction."""
    replica_docs = [d for d in docs if d.get("kind") == "replica"]
    if not replica_docs:
        return []
    if quorum is None:
        fs = [d["f"] for d in replica_docs if isinstance(d.get("f"), int)]
        if fs:
            quorum = max(fs) + 1
        else:
            quorum = (max(len(replica_docs) - 1, 0)) // 2 + 1
    alignment = clockalign.align(replica_docs)
    events: Dict[int, Dict[Tuple[int, int], Dict[str, float]]] = {}
    err: Dict[int, float] = {}
    for d in replica_docs:
        al = alignment.get(("replica", d["id"]))
        if al is None:
            continue
        err[d["id"]] = al.err_ns
        events[d["id"]] = {
            key: {s: t + al.offset_ns for s, t in stages.items()}
            for key, stages in clockalign.event_times(d).items()
        }
    keys = sorted({k for ev in events.values() for k in ev})
    head = ("verify_enqueue", "verify_done", "prepare")
    tail_stages = ("commit_quorum", "execute", "reply_sign", "reply_sent")
    paths: List[RequestPath] = []
    for cid, seq in keys:
        primary = None
        pstages = None
        best_prep = None
        involved_err = 0.0
        for rid, ev in events.items():
            stages = ev.get((cid, seq))
            if not stages:
                continue
            prep = stages.get("prepare")
            if prep is None:
                continue
            if best_prep is None or prep < best_prep:
                best_prep = prep
                primary = rid
                pstages = stages
        if pstages is None:
            continue
        entry = clockalign.entry_time(pstages)
        if entry is None or any(s not in pstages for s in head):
            continue
        involved_err = max(involved_err, err.get(primary, 0.0))
        tail: Dict[str, float] = {}
        ok = True
        for stage in tail_stages:
            vals = []
            for rid, ev in events.items():
                t = ev.get((cid, seq), {}).get(stage)
                if t is not None:
                    vals.append(t)
                    involved_err = max(involved_err, err.get(rid, 0.0))
            if len(vals) < quorum:
                ok = False
                break
            tail[stage] = sorted(vals)[quorum - 1]
        if not ok:
            continue

        def span(a: float, b: float) -> float:
            return max(b - a, 0.0)

        segments = {
            "preverify": span(entry, pstages["verify_enqueue"]),
            "verify": span(pstages["verify_enqueue"],
                           pstages["verify_done"]),
            "prepare_wait": span(pstages["verify_done"],
                                 pstages["prepare"]),
            "commit": span(pstages["prepare"], tail["commit_quorum"]),
            "execute": span(tail["commit_quorum"], tail["execute"]),
            "reply_sign": span(tail["execute"], tail["reply_sign"]),
            "reply_send": span(tail["reply_sign"], tail["reply_sent"]),
        }
        total = span(entry, tail["reply_sent"])
        if total <= 0:
            continue
        segments["unattributed"] = max(total - sum(segments.values()), 0.0)
        paths.append(RequestPath(
            cid=cid, seq=seq, total_ns=total, segments=segments,
            err_ns=2 * involved_err, primary=primary,
        ))
    return paths


def _sched_lat_map(docs: Iterable[dict]) -> Dict[Tuple[int, int], float]:
    """Scheduled-origin latencies from loadgen metadata docs
    (``kind: "loadgen"``, ``sched_lat_ns: {"cid:seq": ns}``)."""
    out: Dict[Tuple[int, int], float] = {}
    for d in docs:
        if d.get("kind") != "loadgen":
            continue
        for key, ns in (d.get("sched_lat_ns") or {}).items():
            try:
                cid_s, seq_s = key.split(":", 1)
                out[(int(cid_s), int(seq_s))] = float(ns)
            except (ValueError, TypeError):
                continue
    return out


def breach_report(docs: Iterable[dict], policy: SLOPolicy,
                  quorum: Optional[int] = None,
                  group: Optional[int] = None) -> dict:
    """Classify every fully-observed request in a dump set against the
    budget and attribute each BREACHED request's spend across critpath
    segments.  The attribution invariant: ``attribution_ms`` sums to
    ``breached_spend_ms`` exactly (per-request segments telescope to
    the per-request total by construction).

    Classification origin, most honest available first: scheduled
    (loadgen metadata doc present — the coordinated-omission rule),
    else client (client recorders dumped), else replica recv."""
    docs = list(filter_group(list(docs), group))
    res = cluster_paths(docs, quorum=quorum)
    paths = res.paths
    origin = "client"
    if not paths:
        paths = _replica_paths(docs, quorum=quorum)
        origin = "replica"
    sched = _sched_lat_map(docs)
    if sched and paths:
        origin = "scheduled"
        adjusted = []
        for p in paths:
            sched_ns = sched.get((p.cid, p.seq))
            if sched_ns is None or sched_ns <= p.total_ns:
                segments = dict(p.segments)
                segments.setdefault(SCHED_WAIT_SEGMENT, 0.0)
                total = p.total_ns
            else:
                segments = dict(p.segments)
                segments[SCHED_WAIT_SEGMENT] = sched_ns - p.total_ns
                total = sched_ns
            adjusted.append(RequestPath(
                cid=p.cid, seq=p.seq, total_ns=total, segments=segments,
                err_ns=p.err_ns, primary=p.primary,
            ))
        paths = adjusted
    breached = [p for p in paths if p.total_ns > policy.budget_ns]
    spend_ns = sum(p.total_ns for p in breached)
    seg_names: List[str] = []
    for p in breached:
        for s in p.segments:
            if s not in seg_names:
                seg_names.append(s)
    attribution = {
        s: round(
            sum(p.segments.get(s, 0.0) for p in breached) / 1e6, 3
        )
        for s in seg_names
    }
    return {
        "origin": origin,
        "target_ms": policy.target_ms,
        "objective": policy.objective,
        "requests": len(paths),
        "good": len(paths) - len(breached),
        "breached": len(breached),
        "good_fraction": round(
            (len(paths) - len(breached)) / len(paths), 4
        ) if paths else 1.0,
        "breached_spend_ms": round(spend_ns / 1e6, 3),
        "attribution_ms": attribution,
    }


# ---------------------------------------------------------------------------
# Breach forensics: the flight recorder that dumps itself.


class TokenBucket:
    """Classic token bucket on the monotonic clock; tests inject
    ``now``.  Starts FULL (the first breach of a run deserves its
    bundle; it is the second that must wait for a refill)."""

    __slots__ = ("capacity", "refill_s", "_tokens", "_t")

    def __init__(self, capacity: float = 1.0, refill_s: float = 300.0,
                 now: Optional[float] = None):
        self.capacity = max(capacity, 1.0)
        self.refill_s = max(refill_s, 1e-9)
        self._tokens = self.capacity
        self._t = time.monotonic() if now is None else now

    def take(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self._tokens = min(
            self.capacity, self._tokens + (now - self._t) / self.refill_s
        )
        self._t = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class BreachSpool:
    """Bounded, rate-limited on-disk spool of breach bundles.

    Two independent defenses against a sustained breach filling the
    disk: the token bucket (one bundle per ``refill_s``) and the spool
    bound (at most ``max_bundles`` ``slo_breach.*.json`` files in the
    directory — counting files, not this process's writes, so restarts
    share the bound).  ``suppressed`` counts the dumps either defense
    refused; it is a signal (sustained breach), not an error."""

    def __init__(self, directory: str, max_bundles: int = 4,
                 refill_s: float = 300.0):
        self.directory = directory
        self.max_bundles = max(int(max_bundles), 1)
        self.bucket = TokenBucket(1.0, refill_s)
        self.written = 0
        self.suppressed = 0

    @staticmethod
    def from_env() -> Optional["BreachSpool"]:
        directory = os.environ.get(DUMP_ENV, "")
        if not directory:
            return None
        return BreachSpool(
            directory,
            max_bundles=int(os.environ.get(DUMP_MAX_ENV, "") or 4),
            refill_s=float(os.environ.get(DUMP_REFILL_ENV, "") or 300.0),
        )

    def bundle_count(self) -> int:
        return len(glob.glob(
            os.path.join(self.directory, "slo_breach.*.json")
        ))

    def maybe_dump(self, bundle, now: Optional[float] = None
                   ) -> Optional[str]:
        """Write one bundle if both defenses allow; ``bundle`` may be a
        dict or a zero-arg callable (built only when the write is
        actually going to happen).  Returns the path or None."""
        if self.bundle_count() >= self.max_bundles:
            self.suppressed += 1
            return None
        if not self.bucket.take(now):
            self.suppressed += 1
            return None
        doc = bundle() if callable(bundle) else bundle
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(
            self.directory,
            f"slo_breach.{runinfo.RUN_ID}.{self.written}.json",
        )
        # noqa: AH102 - one-shot forensic dump; executors may be gone
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        self.written += 1
        return path


def build_bundle(
    policy: SLOPolicy,
    burn: dict,
    ledgers: Iterable[BudgetLedger],
    recorders: Iterable = (),
    timeseries=None,
    util: Optional[dict] = None,
    quorum: Optional[int] = None,
    extra_docs: Iterable[dict] = (),
) -> dict:
    """Compose one forensic snapshot: the flight-recorder docs (with the
    breach attribution computed over them), the telemetry ring, the
    utilization block, the per-group ledger counters, the burn rates at
    trigger time, and the build stamp — everything a postmortem needs
    in ONE file."""
    # Serialize the FULL configured ring, not to_dict()'s 4096-event
    # default: the operator sized the ring (MINBFT_TRACE_RING) to cover
    # the window they care about, and a truncated dump loses exactly the
    # head stages (verify/prepare) that breach attribution needs.
    docs = []
    for r in recorders:
        if r is None:
            continue
        ring = getattr(r, "ring", None)
        docs.append(
            r.to_dict(max_events=ring.capacity)
            if ring is not None
            else r.to_dict()
        )
    docs.extend(d for d in extra_docs if d)
    bundle = {
        "kind": "slo_breach",
        "run_id": runinfo.RUN_ID,
        "build": runinfo.build_info(),
        "policy": policy.to_dict(),
        "burn": burn,
        "ledgers": [
            {
                "group": lg.group,
                "good": lg.good,
                "breached": lg.breached,
                "breached_budget_ms": round(
                    lg.breached_budget_ns / 1e6, 3
                ),
                "budget_remaining": round(lg.budget_remaining(), 4),
            }
            for lg in ledgers
        ],
        "breach": breach_report(docs, policy, quorum=quorum)
        if docs else {},
        "trace": docs,
    }
    if timeseries is not None:
        bundle["timeseries"] = timeseries.to_dict()
    if util is not None:
        bundle["util"] = util
    return bundle


async def watch(
    ts,
    policy: SLOPolicy,
    spool: BreachSpool,
    bundle_fn: Callable[[dict], dict],
    group: Optional[int] = None,
    interval_s: float = 1.0,
) -> None:
    """The auto-dump trigger loop (``peer run`` owns the task): read the
    fast-window burn every interval, and when it crosses the threshold
    hand the spool a lazy bundle (built only if the token bucket and
    spool bound both allow).  Cancel the task to stop."""
    while True:
        await asyncio.sleep(interval_s)
        b = burn_rates(ts, policy, group=group)
        if b["fast_burn"] >= policy.burn_threshold:
            spool.maybe_dump(lambda: bundle_fn(b))
