"""Cluster-wide causal critical path of committed requests.

The flight recorder (obs/trace.py) attributes a request's time WITHIN
one process; the >100x host/device gap lives BETWEEN processes —
network hops, rx-queue waits, engine batch-formation waits, quorum
stalls.  This module merges the per-process trace dumps
(``load_dumps`` ingests ``{base}.r{id}.json`` / ``.c{id}.json`` /
``.engine{id}.json``) into ONE causal timeline per request, keyed on
the ``(client_id, seq)`` pair every REQUEST/PREPARE/COMMIT/REPLY
already carries — no wire change — and computes the per-request
critical path:

    client send → primary ingest → verify wait → PREPARE batch wait →
    backup commit quorum → execute → reply sign → f+1 reply → client
    accept

Cross-process timestamps go through :mod:`~minbft_tpu.obs.clockalign`
first; the pairwise uncertainty bound rides into every cross-node
segment (``RequestPath.err_ns``), so a cross-node segment is never
trusted tighter than the offset error.

Segment semantics (``SEGMENTS`` order; raw spans telescope from the
client's ``start`` to its ``quorum`` note, so shares sum to 1.0 with
the residual reported honestly as ``unattributed``):

- ``client_sign`` — start → signature resolved (client sign-queue wait
  included); ``client_gate`` — sign → broadcast (the seq-order send
  gate).
- ``ingress`` — client broadcast → the PRIMARY's first entry note
  (``ingest``/``recv``): network + transport rx queue + bundle-tick
  wait, minus the ``loop_lag`` carve below.
- ``loop_lag`` — the event-loop saturation share of ingress: the mean
  sampled scheduled-vs-actual loop delta (obs/looplag.py, carried in
  replica dumps), counted for the ONE guaranteed loop crossing at
  ingest and clamped to the observed ingress span — a deliberate
  lower-bound attribution (every later hop crosses the loop again, but
  those crossings are already inside other segments' spans).
- ``preverify`` — entry → verify_enqueue (decode + handler dispatch).
- ``queue_wait`` — the engine-queue wait share of the verify and
  reply-sign engine round trips, split by the measured
  enqueue→dispatch vs dispatch→complete ratio from the engine
  queue-wait histograms (``engine_queue_doc``); ``verify`` and
  ``reply_sign`` keep the complementary service share.  The ratio is
  aggregated per side (verify/sign) across schemes — a documented
  approximation, exact when one scheme dominates a side (the usual
  bench shape).
- ``prepare_wait`` — verify_done → PREPARE applied on the primary (the
  batch-formation wait: how long the request sat waiting for a PREPARE
  batch to ship).
- ``commit`` — primary PREPARE → the (f+1)-th replica's commit quorum:
  PREPARE broadcast, backup processing, COMMIT wave, quorum formation.
  Rank-based: per-replica stage times are order-statistics-coupled
  (stage_k(i) >= stage_{k-1}(i) per replica i, so the (f+1)-th
  smallest of a later stage is >= the (f+1)-th of an earlier one —
  rank differences are non-negative under one clock by construction).
- ``execute`` / ``reply_sign`` / ``reply_send`` — rank-(f+1)
  differences through the executor, the sign queue, and the reply
  marshal.
- ``reply_net`` — (f+1)-th reply_sent → the client's quorum note.
- ``unattributed`` — the telescoping residual: missing stages, clamped
  negative cross-node spans, anything the capture points cannot see.

``critpath_table`` mirrors ``stage_table``: one flat dict of
``{prefix}_critpath_{segment}_share`` keys (always the full segment
set, so the key set is stable), plus request/total/err metadata.  The
merged histograms' ``negatives`` counters (obs/hist.py) feed a
clock-sanity key: negative spans inside any single process mean the
pairing itself is suspect, not just the cross-clock math.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

from . import clockalign
from .hist import Log2Histogram

# Segment names, in causal order.  ``unattributed`` is always last.
SEGMENTS: Tuple[str, ...] = (
    "client_sign",
    "client_gate",
    "ingress",
    "loop_lag",
    "preverify",
    "queue_wait",
    "verify",
    "prepare_wait",
    "commit",
    "execute",
    "reply_sign",
    "reply_send",
    "reply_net",
    "unattributed",
)


@dataclasses.dataclass
class RequestPath:
    """One committed request's merged causal timeline."""

    cid: int
    seq: int
    total_ns: float
    segments: Dict[str, float]  # segment -> ns (>= 0, sums to total_ns)
    err_ns: float  # clock-offset uncertainty bound on cross-node segments
    primary: int  # replica id the head of the path ran through


@dataclasses.dataclass
class ClusterPaths:
    paths: List[RequestPath]
    skipped: int  # requests seen but not fully observable
    quorum: int  # f+1 used for the rank-based tail
    clock_err_ns: float  # max pairwise alignment uncertainty
    negative_spans: int  # clock-sanity: negatives across merged hists
    # Incarnation honesty (ISSUE 14): dump docs dropped because the same
    # (kind, id, group) appeared under two run_ids — a restarted process
    # reuses its replica id AND its (client_id, seq) keyspace, so
    # splicing both incarnations would manufacture chimera paths.
    refused_docs: int = 0


def _drop_conflicting_incarnations(docs: List[dict]) -> Tuple[List[dict], int]:
    """Drop every doc of any identity that appears under two different
    ``run_id``s (docs without the stamp — pre-ISSUE-14 dumps — are
    trusted as single-incarnation; mixing a stamped and an unstamped doc
    of one identity is indistinguishable from a restart, so it refuses
    too once any stamped doc exists for that identity)."""
    runs: Dict[Tuple, set] = {}
    for d in docs:
        if d.get("kind") in ("replica", "client") and d.get("id") is not None:
            key = (d.get("kind"), d.get("id"), d.get("group"))
            runs.setdefault(key, set()).add(d.get("run_id"))
    conflicted = {k for k, v in runs.items() if len(v) > 1}
    if not conflicted:
        return docs, 0
    kept = [
        d for d in docs
        if (d.get("kind"), d.get("id"), d.get("group")) not in conflicted
    ]
    return kept, len(docs) - len(kept)


def engine_queue_doc(engine, ident: int = 0) -> dict:
    """Dump-doc for one engine's queue-wait/service histograms
    (engine.VerifyStats/SignStats ``queue_wait``/``queue_service``) —
    written as ``{base}.engine{ident}.json`` next to the recorder dumps
    so ``load_dumps`` carries it into the merge."""

    def hists(stats_map: dict, attr: str) -> dict:
        out = {}
        for name, st in stats_map.items():
            h = getattr(st, attr, None)
            if h is not None and (h.count or h.negatives):
                out[name] = h.to_dict()
        return out

    return {
        "kind": "engine",
        "id": ident,
        "verify_queue_wait": hists(engine.stats, "queue_wait"),
        "verify_queue_service": hists(engine.stats, "queue_service"),
        "sign_queue_wait": hists(engine.sign_stats, "queue_wait"),
        "sign_queue_service": hists(engine.sign_stats, "queue_service"),
    }


def _merged_hist(dicts: Iterable[dict]) -> Log2Histogram:
    h = Log2Histogram()
    for d in dicts:
        h.merge(Log2Histogram.from_dict(d))
    return h


def _wait_ratio(docs: List[dict], side: str) -> Optional[float]:
    """enqueue→dispatch share of the engine round trip for one queue
    side ('verify' | 'sign'), aggregated across schemes and engines.
    None when no engine doc carries that side's histograms."""
    wait = _merged_hist(
        h for d in docs for h in (d.get(f"{side}_queue_wait") or {}).values()
    )
    service = _merged_hist(
        h for d in docs for h in (d.get(f"{side}_queue_service") or {}).values()
    )
    denom = wait.total_s + service.total_s
    if wait.count + service.count == 0 or denom <= 0:
        return None
    return wait.total_s / denom


def _doc_negatives(doc: dict) -> int:
    n = 0
    for hd in (doc.get("hists") or {}).values():
        n += int(hd.get("negatives", 0))
    ll = doc.get("loop_lag")
    if ll:
        n += int(ll.get("negatives", 0))
    for key in ("verify_queue_wait", "verify_queue_service",
                "sign_queue_wait", "sign_queue_service"):
        for hd in (doc.get(key) or {}).values():
            n += int(hd.get("negatives", 0))
    return n


def _rank(values: List[float], k: int) -> Optional[float]:
    """k-th smallest (1-based), None when fewer than k values."""
    if len(values) < k:
        return None
    return sorted(values)[k - 1]


def cluster_paths(docs: Iterable[dict], quorum: Optional[int] = None) -> ClusterPaths:
    """Merge dump docs into per-request critical paths.

    ``quorum`` is f+1 for the rank-based tail; defaults to the ``f``
    the replica dumps carry (``dump extra``), falling back to the BFT
    bound for the dumped replica count.
    """
    docs = list(docs)
    # Incarnation refusal BEFORE any stitching: two run_ids under one
    # replica/client identity are two processes whose (client_id, seq)
    # keys overlap — their events must never meet in one path.
    docs, refused = _drop_conflicting_incarnations(docs)
    groups = {d["group"] for d in docs if d.get("group") is not None}
    if len(groups) > 1:
        # Multi-group dump set (a GroupRuntime process dumps every core,
        # a MultiGroupClient every inner client): (client_id, seq) is
        # only unique WITHIN a group — the G inner clients share one
        # client id with wall-clock-seeded seq spaces that can overlap —
        # so stitch each group's docs separately (unstamped docs like
        # the shared engine's stay in every partition, exactly the
        # filter_group contract) and fold the results.
        from .trace import filter_group

        merged: Optional[ClusterPaths] = None
        for g in sorted(groups):
            res = cluster_paths(filter_group(docs, g), quorum=quorum)
            if merged is None:
                merged = res
            else:
                merged.paths.extend(res.paths)
                merged.skipped += res.skipped
                merged.clock_err_ns = max(
                    merged.clock_err_ns, res.clock_err_ns
                )
        assert merged is not None
        # Unstamped docs rode every partition: recount their
        # negative-span tallies exactly once over the full set.
        merged.negative_spans = sum(_doc_negatives(d) for d in docs)
        merged.refused_docs = refused
        return merged
    replica_docs = [d for d in docs if d.get("kind") == "replica"]
    client_docs = [d for d in docs if d.get("kind") == "client"]
    engine_docs = [d for d in docs if d.get("kind") == "engine"]
    negative_spans = sum(_doc_negatives(d) for d in docs)
    if quorum is None:
        fs = [d["f"] for d in replica_docs if isinstance(d.get("f"), int)]
        if fs:
            quorum = max(fs) + 1
        else:
            # Old dumps without the n/f extra: MinBFT's bound is n=2f+1
            # (NOT PBFT's 3f+1), so f = (n-1)//2 for a full dump set.
            quorum = (max(len(replica_docs) - 1, 0)) // 2 + 1
    result = ClusterPaths(
        paths=[], skipped=0, quorum=quorum, clock_err_ns=0.0,
        negative_spans=negative_spans, refused_docs=refused,
    )
    if not replica_docs or not client_docs:
        return result

    alignment = clockalign.align(docs)
    result.clock_err_ns = max(
        (a.err_ns for a in alignment.values()), default=0.0
    )

    # Mean event-loop lag per crossing (the loop_lag carve), merged
    # across the replica dumps that sampled it.
    lag_hist = _merged_hist(
        d["loop_lag"] for d in replica_docs if d.get("loop_lag")
    )
    mean_lag_ns = (lag_hist.total_s / lag_hist.count * 1e9) if lag_hist.count else 0.0

    verify_ratio = _wait_ratio(engine_docs, "verify")
    sign_ratio = _wait_ratio(engine_docs, "sign")

    # Aligned per-replica event maps.
    replica_events: Dict[int, Dict[Tuple[int, int], Dict[str, float]]] = {}
    replica_err: Dict[int, float] = {}
    for d in replica_docs:
        al = alignment.get(("replica", d["id"]))
        if al is None:
            continue
        replica_err[d["id"]] = al.err_ns
        replica_events[d["id"]] = {
            key: {s: t + al.offset_ns for s, t in stages.items()}
            for key, stages in clockalign.event_times(d).items()
        }

    for cdoc in client_docs:
        al = alignment.get(("client", cdoc["id"]))
        if al is None:
            continue
        for key, cstages in clockalign.event_times(cdoc).items():
            cid, seq = key
            if cid != cdoc["id"]:
                continue
            c = {s: t + al.offset_ns for s, t in cstages.items()}
            path = _one_path(
                cid, seq, c, replica_events, replica_err, al.err_ns,
                quorum, mean_lag_ns, verify_ratio, sign_ratio,
            )
            if path is None:
                result.skipped += 1
            else:
                result.paths.append(path)
    return result


_HEAD_STAGES = ("verify_enqueue", "verify_done", "prepare")
_TAIL_STAGES = ("commit_quorum", "execute", "reply_sign", "reply_sent")


def _one_path(
    cid: int,
    seq: int,
    c: Dict[str, float],
    replica_events: Dict[int, Dict[Tuple[int, int], Dict[str, float]]],
    replica_err: Dict[int, float],
    client_err: float,
    quorum: int,
    mean_lag_ns: float,
    verify_ratio: Optional[float],
    sign_ratio: Optional[float],
) -> Optional[RequestPath]:
    t0 = c.get("start")
    t_sign = c.get("sign")
    t_bcast = c.get("broadcast")
    t_accept = c.get("quorum")
    if None in (t0, t_sign, t_bcast, t_accept):
        return None

    # Primary = the replica whose PREPARE applied first (its own PREPARE
    # rides its own-message loop, so its note IS the broadcast instant
    # up to loop latency); it must carry the whole head chain.
    primary = None
    primary_stages = None
    best_prep = None
    err = client_err
    involved_err = 0.0
    for rid, events in replica_events.items():
        stages = events.get((cid, seq))
        if not stages:
            continue
        prep = stages.get("prepare")
        if prep is None:
            continue
        if best_prep is None or prep < best_prep:
            best_prep = prep
            primary = rid
            primary_stages = stages
    if primary_stages is None:
        return None
    entry = clockalign.entry_time(primary_stages)
    if entry is None or any(s not in primary_stages for s in _HEAD_STAGES):
        return None
    involved_err = max(involved_err, replica_err.get(primary, 0.0))

    # Rank-(f+1) tail times across every replica that observed the stage.
    tail: Dict[str, float] = {}
    for stage in _TAIL_STAGES:
        vals = []
        for rid, events in replica_events.items():
            t = events.get((cid, seq), {}).get(stage)
            if t is not None:
                vals.append(t)
                involved_err = max(involved_err, replica_err.get(rid, 0.0))
        ranked = _rank(vals, quorum)
        if ranked is None:
            return None
        tail[stage] = ranked
    err += 2 * involved_err  # both directions of every cross-node hop

    def span(a: float, b: float) -> float:
        return max(b - a, 0.0)

    ingress_raw = span(t_bcast, entry)
    loop_lag = min(mean_lag_ns, ingress_raw)
    verify_span = span(primary_stages["verify_enqueue"],
                       primary_stages["verify_done"])
    sign_span = span(tail["execute"], tail["reply_sign"])
    vr = verify_ratio or 0.0
    sr = sign_ratio or 0.0
    segments = {
        "client_sign": span(t0, t_sign),
        "client_gate": span(t_sign, t_bcast),
        "ingress": ingress_raw - loop_lag,
        "loop_lag": loop_lag,
        "preverify": span(entry, primary_stages["verify_enqueue"]),
        "queue_wait": verify_span * vr + sign_span * sr,
        "verify": verify_span * (1.0 - vr),
        "prepare_wait": span(primary_stages["verify_done"],
                             primary_stages["prepare"]),
        "commit": span(primary_stages["prepare"], tail["commit_quorum"]),
        "execute": span(tail["commit_quorum"], tail["execute"]),
        "reply_sign": sign_span * (1.0 - sr),
        "reply_send": span(tail["reply_sign"], tail["reply_sent"]),
        "reply_net": span(tail["reply_sent"], t_accept),
    }
    total = span(t0, t_accept)
    if total <= 0:
        return None
    segments["unattributed"] = max(
        total - sum(segments.values()), 0.0
    )
    return RequestPath(
        cid=cid, seq=seq, total_ns=total, segments=segments,
        err_ns=err, primary=primary,
    )


def critpath_table(
    docs: Iterable[dict],
    prefix: str,
    quorum: Optional[int] = None,
    group: Optional[int] = None,
) -> dict:
    """The bench's cluster critical-path keys (the ``stage_table``
    sibling): ``{prefix}_critpath_{segment}_share`` for EVERY segment in
    :data:`SEGMENTS` (stable key set; 0.0 when a segment never fired),
    shares of the summed client-observed request time, summing to 1.0;
    plus request count, total p50, the clock-uncertainty bound, and —
    only when nonzero — the negative-span clock-sanity counter.

    ``group`` restricts the merge to one consensus group's recorders
    (multi-group runtime dumps; :func:`minbft_tpu.obs.trace.filter_group`
    semantics — unstamped docs like the shared engine's stay in).

    Returns {} when the dumps yield no complete request, so a
    tracing-disabled bench emits byte-identical keys to a tracing-absent
    one (the stage_table contract)."""
    from .trace import filter_group

    res = cluster_paths(filter_group(docs, group), quorum=quorum)
    if not res.paths:
        return {}
    grand = sum(p.total_ns for p in res.paths)
    if grand <= 0:
        return {}
    out: dict = {}
    for seg in SEGMENTS:
        seg_total = sum(p.segments.get(seg, 0.0) for p in res.paths)
        out[f"{prefix}_critpath_{seg}_share"] = round(seg_total / grand, 4)
    totals = sorted(p.total_ns for p in res.paths)
    out[f"{prefix}_critpath_requests"] = len(res.paths)
    out[f"{prefix}_critpath_skipped"] = res.skipped
    out[f"{prefix}_critpath_total_p50_ms"] = round(
        totals[(len(totals) - 1) // 2] / 1e6, 3
    )
    out[f"{prefix}_critpath_clock_err_ms"] = round(res.clock_err_ns / 1e6, 3)
    if res.negative_spans:
        out[f"{prefix}_critpath_negative_spans"] = res.negative_spans
    if res.refused_docs:
        # Incarnation sanity (only-when-nonzero, like negative_spans): a
        # nonzero count means the dump set mixed restarts of one id.
        out[f"{prefix}_critpath_refused_docs"] = res.refused_docs
    return out
