"""Time-resolved telemetry rings: fixed-capacity per-interval series.

Everything the repo measured before this module is an end-of-run
aggregate — a committed req/s MEAN, a cumulative histogram, a one-shot
scrape.  The >100x underutilization headline (ROADMAP) is invisible in
aggregates: a run that saturates for 5 seconds and stalls for 25 shows
the same mean as a run that plods evenly.  These rings keep the SHAPE:
one slot per wall-clock interval (default 1s), a bounded window of them
(default 600 = 10 minutes), written concurrently by samplers and read
by scrapes, dumps, and the bench artifact's saturation timeline.

Design rules, inherited from :class:`~minbft_tpu.obs.hist.Log2Histogram`:

- **Exact merge.**  Every slot stores ``(sum, n)`` keyed by the ABSOLUTE
  interval index ``floor(epoch_seconds / interval)``, so merging two
  rings is slot-wise pair addition — associative and commutative, no
  re-binning, no argument order sensitivity.  ``rate`` series read as
  the sum (cluster totals add); ``gauge`` series read as ``sum/n``
  (the cross-process mean of sampled depths/lags) — both derived from
  the same merged pairs, so the merge itself never has to know which
  reading a consumer wants.
- **Bounded memory.**  Writing an interval prunes anything older than
  ``capacity`` intervals behind it; a ring can run for a week and hold
  ten minutes.
- **Counter-delta discipline.**  Rate series record per-interval DELTAS
  of cumulative counters (the sampler below keeps the baselines).  A
  counter that goes backwards (the bench's warm-up stats reset swaps in
  a fresh ``VerifyStats``) re-baselines and records nothing — a reset
  must read as "no data", never as a negative rate.

Cross-node alignment uses the wall clock (the indices are epoch-based).
That is deliberate: NTP-grade skew (well under the 1s interval) moves a
sample by at most one slot, and the alternative — per-process monotonic
origins — would make merge meaningless.  Incarnation honesty is handled
one level up: dumps carry ``run_id`` (obs/runinfo.py) and
:func:`merge_timeseries_docs` REFUSES to splice two incarnations of the
same replica id into one timeline.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from . import runinfo

DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAPACITY = 600

_KINDS = ("rate", "gauge")


class IncarnationMismatch(ValueError):
    """Two dumps claim the same replica id but different ``run_id``s —
    splicing them would chimera a restarted replica's fresh counters
    onto its predecessor's timeline, so the merge refuses."""


class TimeSeries:
    """A bundle of named per-interval series sharing one clock grid.

    Thread-safe: samplers on worker threads and the asyncio loop may
    ``record`` concurrently while a scrape thread reads — all state
    mutates under ``_lock`` (the MTStageRing discipline;
    tools/analyze/project.py pins it).
    """

    __slots__ = ("interval_s", "capacity", "_series", "_kinds", "_lock")

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 capacity: int = DEFAULT_CAPACITY):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        # name -> {abs_interval_index: [sum, n]}
        self._series: Dict[str, Dict[int, List[float]]] = {}
        self._kinds: Dict[str, str] = {}
        self._lock = threading.Lock()

    # -- writing ---------------------------------------------------------

    def index_for(self, t: Optional[float] = None) -> int:
        return int((time.time() if t is None else t) // self.interval_s)

    def record(self, name: str, value: float, kind: str = "rate",
               t: Optional[float] = None) -> None:
        """Add ``value`` into the slot covering wall-clock time ``t``
        (now by default).  ``kind`` is fixed at a series' first record;
        a later mismatch raises — silently reinterpreting a rate as a
        gauge would corrupt every merged reading downstream."""
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
        idx = self.index_for(t)
        with self._lock:
            have = self._kinds.get(name)
            if have is None:
                self._kinds[name] = kind
                self._series[name] = {}
            elif have != kind:
                raise ValueError(
                    f"series {name!r} is {have!r}, cannot record {kind!r}"
                )
            slots = self._series[name]
            slot = slots.get(idx)
            if slot is None:
                slots[idx] = [float(value), 1]
                # Prune: fixed capacity, measured from the newest index
                # EVER written to this series (late stragglers from a
                # skewed clock cannot resurrect evicted history).
                floor = max(slots) - self.capacity
                if len(slots) > self.capacity:
                    for old in [i for i in slots if i <= floor]:
                        del slots[old]
            else:
                slot[0] += value
                slot[1] += 1

    # -- reading ---------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def kind(self, name: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(name)

    def _read(self, name: str, idx: int) -> Optional[Tuple[float, int]]:
        slot = self._series.get(name, {}).get(idx)
        return None if slot is None else (slot[0], slot[1])

    def value(self, name: str, idx: int) -> float:
        """One slot's reading: rate -> summed delta in that interval,
        gauge -> mean of the samples in it.  Empty slot reads 0.0."""
        with self._lock:
            slot = self._series.get(name, {}).get(idx)
            if slot is None:
                return 0.0
            if self._kinds[name] == "gauge":
                return slot[0] / slot[1] if slot[1] else 0.0
            return slot[0]

    def window(self, seconds: float, now: Optional[float] = None) -> Dict[str, float]:
        """Recent-window reading per series, for the ``minbft_window_*``
        gauges: rate -> per-SECOND rate over the window, gauge -> mean
        of the window's samples.  The newest (still-filling) interval is
        excluded — a half-elapsed slot would read as a half rate."""
        end = self.index_for(now)  # exclusive
        n_slots = max(1, int(seconds // self.interval_s))
        out: Dict[str, float] = {}
        with self._lock:
            for name, slots in self._series.items():
                total = 0.0
                count = 0
                for idx in range(end - n_slots, end):
                    slot = slots.get(idx)
                    if slot is not None:
                        total += slot[0]
                        count += slot[1]
                if self._kinds[name] == "gauge":
                    out[name] = total / count if count else 0.0
                else:
                    out[name] = total / (n_slots * self.interval_s)
        return out

    def timeline(self, name: str, last: Optional[int] = None
                 ) -> Tuple[int, List[float]]:
        """Dense per-interval readings ``(start_index, values)`` for the
        bench artifact's saturation timeline.  Gaps read 0.0 (an idle
        second IS a zero rate; an unsampled gauge second has no better
        honest value and 0 is visibly a gap next to real depths)."""
        with self._lock:
            slots = self._series.get(name)
            if not slots:
                return (0, [])
            kind = self._kinds[name]
            lo, hi = min(slots), max(slots)
            if last is not None:
                lo = max(lo, hi - last + 1)
            vals: List[float] = []
            for idx in range(lo, hi + 1):
                slot = slots.get(idx)
                if slot is None:
                    vals.append(0.0)
                elif kind == "gauge":
                    vals.append(slot[0] / slot[1] if slot[1] else 0.0)
                else:
                    vals.append(slot[0])
            return (lo, vals)

    # -- merge / serialization (the Log2Histogram contract) --------------

    def merge(self, other: "TimeSeries") -> "TimeSeries":
        """Slot-wise pair addition into ``self``.  Exact and associative
        (the property test in tests/test_timeseries.py pins it).  Grids
        must match — re-binning across interval widths would not be."""
        if other.interval_s != self.interval_s:
            raise ValueError(
                f"interval mismatch: {self.interval_s} vs {other.interval_s}"
            )
        with other._lock:
            theirs = {
                name: (other._kinds[name],
                       {i: list(s) for i, s in slots.items()})
                for name, slots in other._series.items()
            }
        with self._lock:
            self.capacity = max(self.capacity, other.capacity)
            for name, (kind, slots) in theirs.items():
                have = self._kinds.get(name)
                if have is None:
                    self._kinds[name] = kind
                    self._series[name] = {}
                elif have != kind:
                    raise ValueError(
                        f"series {name!r} kind mismatch: {have} vs {kind}"
                    )
                mine = self._series[name]
                for idx, (s, n) in slots.items():
                    slot = mine.get(idx)
                    if slot is None:
                        mine[idx] = [s, n]
                    else:
                        slot[0] += s
                        slot[1] += n
                if len(mine) > self.capacity:
                    floor = max(mine) - self.capacity
                    for old in [i for i in mine if i <= floor]:
                        del mine[old]
        return self

    @staticmethod
    def merged(series: Iterable["TimeSeries"]) -> "TimeSeries":
        out: Optional[TimeSeries] = None
        for ts in series:
            if out is None:
                out = TimeSeries(ts.interval_s, ts.capacity)
            out.merge(ts)
        return out if out is not None else TimeSeries()

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "capacity": self.capacity,
                "series": {
                    name: {
                        "kind": self._kinds[name],
                        "points": {
                            str(i): [s, n] for i, (s, n) in sorted(
                                (i, (slot[0], slot[1]))
                                for i, slot in slots.items()
                            )
                        },
                    }
                    for name, slots in self._series.items()
                },
            }

    @staticmethod
    def from_dict(d: dict) -> "TimeSeries":
        ts = TimeSeries(
            float(d.get("interval_s", DEFAULT_INTERVAL_S)),
            int(d.get("capacity", DEFAULT_CAPACITY)),
        )
        for name, ser in (d.get("series") or {}).items():
            kind = ser.get("kind", "rate")
            ts._kinds[name] = kind
            ts._series[name] = {
                int(i): [float(p[0]), int(p[1])]
                for i, p in (ser.get("points") or {}).items()
            }
        return ts


class CounterSampler:
    """Samples cumulative counters into a :class:`TimeSeries` on a fixed
    tick, keeping the per-source baselines the counter-delta discipline
    needs.  All reads are GIL-atomic snapshots of ints/floats (the same
    contract the Prometheus scrape relies on), so a tick never blocks
    the event loop on protocol locks.

    Three source shapes:

    - ``add_rate(name, fn)`` — ``fn`` returns a cumulative count; each
      tick records the delta.  A backwards step (stats reset) only
      re-baselines.
    - ``add_gauge(name, fn)`` — ``fn`` returns the instantaneous value.
    - ``add_ratio(name, num_fn, den_fn)`` — per-interval
      ``Δnum / Δden`` recorded as a gauge (batch fill, frames/tick);
      nothing is recorded when the denominator did not move, so idle
      intervals stay gaps instead of fabricated zeros.
    """

    def __init__(self, ts: TimeSeries):
        self.ts = ts
        self._rates: List[Tuple[str, Callable[[], float]]] = []
        self._gauges: List[Tuple[str, Callable[[], float]]] = []
        self._ratios: List[
            Tuple[str, Callable[[], float], Callable[[], float]]
        ] = []
        self._last: Dict[str, float] = {}

    def add_rate(self, name: str, fn: Callable[[], float]) -> None:
        self._rates.append((name, fn))

    def add_gauge(self, name: str, fn: Callable[[], float]) -> None:
        self._gauges.append((name, fn))

    def add_ratio(self, name: str, num_fn: Callable[[], float],
                  den_fn: Callable[[], float]) -> None:
        self._ratios.append((name, num_fn, den_fn))

    def tick(self, t: Optional[float] = None) -> None:
        for name, fn in self._rates:
            cur = float(fn())
            last = self._last.get(name)
            self._last[name] = cur
            if last is not None and cur >= last:
                self.ts.record(name, cur - last, kind="rate", t=t)
        for name, num_fn, den_fn in self._ratios:
            num, den = float(num_fn()), float(den_fn())
            lnum = self._last.get(name + "#num")
            lden = self._last.get(name + "#den")
            self._last[name + "#num"] = num
            self._last[name + "#den"] = den
            if lnum is None or num < lnum or den < lden:
                continue  # first tick or reset: re-baseline only
            if den - lden > 0:
                self.ts.record(
                    name, (num - lnum) / (den - lden), kind="gauge", t=t
                )
        for name, fn in self._gauges:
            self.ts.record(name, float(fn()), kind="gauge", t=t)

    async def run(self) -> None:
        """Tick forever at the ring's interval; cancel the task to stop.
        The first tick only establishes baselines (no deltas recorded),
        so starting the sampler mid-run never fabricates a burst."""
        try:
            while True:
                await asyncio.sleep(self.ts.interval_s)
                self.tick()
        except asyncio.CancelledError:
            self.tick()  # flush the final partial interval's deltas
            raise


def register_replica_series(sampler: CounterSampler, metrics,
                            group: Optional[int] = None) -> None:
    """The standard per-replica series (per-group suffixed when the
    grouped runtime passes its core's group id): committed req/s, loop
    lag, and ingest fill — everything a ``peer top`` row needs that the
    engine does not know."""
    sfx = f"_g{group}" if group is not None else ""
    counters = metrics.counters
    sampler.add_rate(
        f"committed{sfx}",
        lambda: counters.get("requests_executed", 0),
    )
    sampler.add_gauge(
        f"loop_lag_p50_ms{sfx}",
        lambda: metrics.loop_lag.percentile(50) * 1e3,
    )
    sampler.add_ratio(
        f"ingest_frames_per_tick{sfx}",
        lambda: counters.get("ingest_frames", 0),
        lambda: counters.get("ingest_ticks", 0),
    )


def register_engine_series(sampler: CounterSampler, engine) -> None:
    """The shared-engine series: verify/sign item rates, per-interval
    batch fill, and total queue backlog.  Registered ONCE per engine —
    the grouped runtime's cores share one engine, and double-counting
    its items would inflate every merged reading."""

    def _verify_items() -> float:
        return sum(st.items for st in engine.stats.values())

    def _verify_batches() -> float:
        return sum(st.batches for st in engine.stats.values())

    def _sign_items() -> float:
        return sum(st.items for st in engine.sign_stats.values())

    def _depth() -> float:
        return float(
            sum(engine.queue_depths().values())
            + sum(engine.sign_queue_depths().values())
        )

    sampler.add_rate("verify_items", _verify_items)
    sampler.add_rate("sign_items", _sign_items)
    sampler.add_ratio("verify_fill", _verify_items, _verify_batches)
    sampler.add_gauge("queue_depth", _depth)

    def _wait_p50_ms() -> float:
        hists = [st.queue_wait for st in engine.stats.values()]
        if not hists:
            return 0.0
        from .hist import Log2Histogram

        return Log2Histogram.merged(hists).percentile(50) * 1e3

    sampler.add_gauge("queue_wait_p50_ms", _wait_p50_ms)


# -- dump / merge (the {base}.ts.json surface) ---------------------------


def dump_timeseries(ts: TimeSeries, base: str,
                    extra: Optional[dict] = None) -> str:
    """Write the ring next to the flight-recorder dumps as
    ``{base}.ts.json``.  The doc carries ``kind: "timeseries"`` (the
    trace loaders filter on kind, so sharing the glob is safe) plus the
    run_id/build attribution block every dump now carries."""
    import json

    doc = {
        "kind": "timeseries",
        "run_id": runinfo.RUN_ID,
        "build": runinfo.build_info(),
        "ts": ts.to_dict(),
    }
    if extra:
        doc.update(extra)
    path = f"{base}.ts.json"
    # noqa: AH102 - one-shot shutdown dump; no executor dependency at teardown
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def merge_timeseries_docs(docs: Iterable[dict]) -> TimeSeries:
    """Merge ``kind == "timeseries"`` dump docs into one cluster ring.

    Incarnation honesty (ISSUE 14 satellite): two docs claiming the
    same replica ``id`` with different ``run_id``s are two PROCESSES —
    a restart.  Splicing them would stack the restarted replica's
    counters onto its predecessor's slots as if one process produced
    both, so the merge raises :class:`IncarnationMismatch` instead;
    the caller decides which incarnation to keep.
    """
    ts_docs = [d for d in docs if d.get("kind") == "timeseries"]
    seen: Dict[object, str] = {}
    for d in ts_docs:
        ident = d.get("id")
        run = d.get("run_id")
        if ident is None or run is None:
            continue
        prev = seen.setdefault(ident, run)
        if prev != run:
            raise IncarnationMismatch(
                f"timeseries dumps for id {ident!r} span two incarnations "
                f"({prev} vs {run}): refusing to splice a restarted "
                "process onto its predecessor's timeline"
            )
    return TimeSeries.merged(
        TimeSeries.from_dict(d.get("ts") or {}) for d in ts_docs
    )
