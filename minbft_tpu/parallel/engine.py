"""Asyncio batching engine for TPU crypto verification.

The reference verifies every signature/UI serially and synchronously in the
message-handling goroutine (reference sample/authentication/crypto.go:79-89
called from core/message-handling.go:409-452 and core/usig-ui.go:62-73).
Here, each protocol task awaits ``BatchVerifier.verify_*`` and the engine:

1. appends the item to the scheme's pending queue,
2. flushes by a **ship-when-idle** policy: if no kernel dispatch is in
   flight, the queue flushes on the next event-loop turn (a lone low-load
   verification never stalls waiting for a batch to fill — the latency
   mitigation from SURVEY.md §7 "hard parts"); while a dispatch *is* in
   flight, items accumulate and flush the moment it completes, so batch
   sizes self-scale to arrival-rate × device-latency (high load fills
   batches with no tuning knob),
3. pads the batch to a fixed bucket size (one compiled kernel per bucket,
   never a recompile from a data-dependent shape),
4. dispatches the jitted kernel on a worker thread (keeping the event loop
   free) and resolves every awaiting future with its lane's verdict.

Quorum waits (reference core/commit.go:108-143's mutex-serialized collector)
thereby become "await one batched verify result" — the BASELINE.json north
star restructuring.

Signing gets the mirror-image treatment (:class:`_SignQueue`): client
REQUEST and replica REPLY signatures are awaitable batch lanes over the
fixed-base comb kernels (ops/p256.py / ops/ed25519.py sign halves), with
the cheap big-int nonce/inverse work vectorized on the host — moving
signature generation off the request critical path (DSig, arxiv
2406.07215) the same way verification already is.  The sign queues are
memo-free (every sign is its own protocol event) and fall back to serial
host signing whenever no healthy device exists — CPU backend, write-off,
or a hung dispatch — with the fallback recorded in :class:`SignStats`.
USIG UI signing deliberately never routes here (counter-after-sign is
serial per key, ref usig.c:66-69).
"""

from __future__ import annotations

import asyncio
import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.hist import Log2Histogram


def _bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class _Resolved:
    """Pre-resolved awaitable — a memo hit costs no Future machinery."""

    __slots__ = ("v",)

    def __init__(self, v: bool):
        self.v = v

    def __await__(self):
        if False:  # pragma: no cover — makes this a generator function
            yield
        return self.v


@dataclasses.dataclass
class VerifyStats:
    """Engine counters (the observability the reference lacks, SURVEY.md §5)."""

    items: int = 0
    batches: int = 0
    max_batch_seen: int = 0
    padded_lanes: int = 0
    device_time_s: float = 0.0
    # Host share of the dispatch: time the worker thread spent preparing
    # and packing the batch (limb conversion, batch inversion, staging
    # writes) BEFORE the kernel call — device_time_s covers the whole
    # dispatch await, so host_prep_time_s / device_time_s is the prep
    # share of the pipeline (bench.py reports it as *_prep_share).
    host_prep_time_s: float = 0.0
    memo_hits: int = 0
    dispatch_timeouts: int = 0  # hung device dispatches rescued on host
    # Flight-recorder gauges (event-loop-side updates only): why each
    # batch shipped ("full" / "idle" / "timer" / "completion" — the
    # ship-when-idle policy made observable), and pre-padding batch
    # occupancy bucketed by log2 size (key = (len(batch)-1).bit_length(),
    # so bucket k holds batches of 2^(k-1) < size <= 2^k items — prom.py
    # labels it with the 2^k upper edge).  Both sum to ``batches``.
    flush_reasons: Dict[str, int] = dataclasses.field(default_factory=dict)
    occupancy: Dict[int, int] = dataclasses.field(default_factory=dict)
    # Queue-wait attribution (ISSUE 8): per-item enqueue→dispatch wait
    # and dispatch→complete service as mergeable log2 histograms, both
    # recorded in _run's loop-side accounting block (so for successful
    # batches count == items; a failed dispatch records neither).
    # Scraped as minbft_{verify,sign}_queue_{wait,service}_seconds and
    # dumped for the critical-path merge (obs/critpath.py).
    queue_wait: Log2Histogram = dataclasses.field(default_factory=Log2Histogram)
    queue_service: Log2Histogram = dataclasses.field(
        default_factory=Log2Histogram
    )

    @property
    def mean_batch(self) -> float:
        return self.items / self.batches if self.batches else 0.0


@dataclasses.dataclass
class SignStats:
    """Sign-queue counters — the sign-side sibling of :class:`VerifyStats`.

    ``host_prep_time_s`` covers BOTH host halves of a dispatch (nonce
    derivation + limb packing before the kernel, batch inversion + scalar
    finish after it); ``device_time_s`` is the whole dispatch await, so
    the difference is the kernel + transfer share.
    ``host_fallback_items`` counts items signed by the serial host
    fallback instead of the device — because the backend is CPU (sign
    device auto-disabled), the device was written off, or a dispatch hung
    past the timeout — so a bench artifact can never pass host signing
    off as device throughput."""

    items: int = 0
    batches: int = 0
    max_batch_seen: int = 0
    padded_lanes: int = 0
    device_time_s: float = 0.0
    host_prep_time_s: float = 0.0
    dispatch_timeouts: int = 0
    host_fallback_items: int = 0
    # See VerifyStats: flush-reason and log2 batch-occupancy gauges,
    # loop-side updates only — and the queue-wait/service span
    # histograms (same recording point and invariants).
    flush_reasons: Dict[str, int] = dataclasses.field(default_factory=dict)
    occupancy: Dict[int, int] = dataclasses.field(default_factory=dict)
    queue_wait: Log2Histogram = dataclasses.field(default_factory=Log2Histogram)
    queue_service: Log2Histogram = dataclasses.field(
        default_factory=Log2Histogram
    )

    @property
    def mean_batch(self) -> float:
        return self.items / self.batches if self.batches else 0.0


class _StagingPool:
    """Recycled host staging buffers for the packed dispatch uploads.

    Dispatchers run on worker threads — up to ``max_inflight`` of them
    concurrently per scheme — so buffers are checked out under a lock and
    returned only after the device results are materialized: a buffer is
    never shared by two in-flight dispatches, and at steady state a
    dispatch allocates nothing — prep writes limbs straight into a
    recycled array and padding is a tail slice-zero instead of
    ``list(items) + [PAD] * k`` re-prepping pad lanes every dispatch.
    """

    def __init__(self, cap: int = 8):
        # ``cap`` bounds free buffers kept per (shape, dtype) — the engine
        # passes its max_inflight (the most dispatches that can hold a
        # buffer of one shape at once), so steady state never drops a
        # recyclable buffer.
        self._cap = max(2, cap)
        self._lock = threading.Lock()
        self._free: Dict[tuple, list] = {}

    def acquire(self, shape: tuple, dtype) -> np.ndarray:
        key = (shape, np.dtype(dtype).str)
        with self._lock:
            stack = self._free.get(key)
            buf = stack.pop() if stack else None
        return np.empty(shape, dtype) if buf is None else buf

    def release(self, buf: np.ndarray) -> None:
        key = (buf.shape, buf.dtype.str)
        with self._lock:
            stack = self._free.setdefault(key, [])
            if len(stack) < self._cap:
                stack.append(buf)


class _DispatchQueue:
    """Shared machinery of the verify and sign queues: ship-when-idle
    flush scheduling, ``max_inflight`` worker dispatch, and the
    hung-dispatch liveness net (timeout → host fallback → write-off →
    out-of-band re-probe).  Subclasses own the pending/resolution policy:
    :class:`_SchemeQueue` dedups (verification is a pure function),
    :class:`_SignQueue` is memo-free by design.
    """

    _WRITE_OFF_AFTER = 3  # CONSECUTIVE hung dispatches before host-only
    _REPROBE_AFTER = 600.0  # s before a written-off device is re-tried
    # Cold kernel compiles (unrolled ECDSA/Ed25519 shapes take minutes on
    # a cold cache) land inside the FIRST dispatch: give it headroom so a
    # slow-but-healthy compile is not misread as a hung tunnel.
    _FIRST_TIMEOUT_FACTOR = 4

    def __init__(self, engine: "BatchVerifier", name: str, dispatch):
        self.engine = engine
        self.name = name
        self.dispatch = dispatch  # List[item] -> per-lane results
        # (item, future, enqueue_monotonic_ns): the timestamp feeds the
        # per-item queue-wait histogram at dispatch time.
        self.pending: List[Tuple[object, asyncio.Future, int]] = []
        self._flush_handle: Optional[asyncio.Handle] = None
        self.inflight = 0
        # High-water mark of len(pending) since the last peak snapshot
        # (ISSUE 14): the point-in-time depth gauge samples whatever
        # backlog happens to exist AT scrape time and misses every burst
        # between scrapes — the peak is what capacity planning needs.
        # Updated loop-side in _schedule_flush (every growth path runs
        # through it); read-and-reset from the scrape thread is a pair
        # of GIL-atomic int ops (see queue_depth_peaks).
        self.peak_depth = 0
        self._consecutive_timeouts = 0
        self._device_written_off = False
        self._device_ever_succeeded = False
        self._written_off_at = 0.0
        self._probing = False
        # Strong refs to in-flight _run/_probe tasks: the loop keeps
        # only a weak reference to a running task, so without this set a
        # dispatch task is GC-able mid-flight (the TL601 contract).
        self._bg_tasks: set = set()

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    # -- subclass hooks -----------------------------------------------------

    def _fallback(self):
        """Serial host dispatcher for this queue's items (None: no net)."""
        raise NotImplementedError

    def _device_enabled(self) -> bool:
        """False routes every batch straight to the fallback without
        arming the timeout machinery.  May block (first call can
        initialize the jax backend) — only invoked off-loop."""
        return True

    def _device_enabled_fast(self):
        """Loop-safe view of the device-enabled state: the resolved
        bool, or None when resolution would block (the sign queues'
        backend probe initializes jax on first touch — that must happen
        on a worker thread, never on the event loop)."""
        return True

    def _resolve(self, batch, results, fell_back: bool) -> None:
        """Resolve a completed batch's futures (subclass policy)."""
        raise NotImplementedError

    def _resolve_error(self, batch, e: BaseException) -> None:
        """Resolve a failed batch's futures with the failure."""
        raise NotImplementedError

    async def _run(self, batch, reason: str) -> None:
        """One dispatch: liveness-netted execution, shared accounting,
        then the subclass's resolution policy.  The finally re-flush is
        what implements flush-on-completion (accumulated items ship the
        moment a dispatch slot frees up)."""
        items = [it for it, _f, _t in batch]
        t0_ns = time.monotonic_ns()
        try:
            results, fell_back = await self._dispatch_with_fallback(items)
        except Exception as e:
            self._resolve_error(batch, e)
            return
        finally:
            # Loop-atomic: each _run task decrements exactly once, and
            # inflight is only ever read/written between awaits on the
            # event loop — no read-modify-write spans a suspension.
            self.inflight -= 1  # noqa: LD001
            if self.pending:
                self._flush_now("completion")
        dt_ns = time.monotonic_ns() - t0_ns
        dt = dt_ns * 1e-9
        st = self.stats
        st.items += len(batch)
        st.batches += 1
        st.max_batch_seen = max(st.max_batch_seen, len(batch))
        st.device_time_s += dt
        # Flush-reason and occupancy gauges, counted HERE with batches —
        # not at flush time — so both always sum to ``batches`` (a batch
        # whose dispatch raises is counted in neither, keeping the
        # exported invariant true on error paths too).
        st.flush_reasons[reason] = st.flush_reasons.get(reason, 0) + 1
        # Pre-padding occupancy, log2-bucketed (loop-side — _run's
        # accounting block runs on the event loop like the rest of st).
        # (n-1).bit_length() puts bucket k at 2^(k-1) < size <= 2^k — the
        # documented upper-edge convention, so a full power-of-two batch
        # (the common case under load) lands in ITS bucket, not one up.
        occ = (len(batch) - 1).bit_length()
        st.occupancy[occ] = st.occupancy.get(occ, 0) + 1
        # Queue-wait attribution: per-item enqueue→dispatch wait, and the
        # shared dispatch→complete service span fanned to every lane in
        # one O(1) bulk observe.  Recorded HERE, with the other success
        # accounting, so wait.count == service.count == items for every
        # successful batch (the exported invariant).
        wait_h = st.queue_wait
        for _it, _f, t_enq in batch:
            wait_h.observe_ns(t0_ns - t_enq)
        st.queue_service.observe_ns(dt_ns, len(batch))
        self._resolve(batch, results, fell_back)

    # -- flush scheduling ---------------------------------------------------

    def _schedule_flush(self, fut: asyncio.Future) -> asyncio.Future:
        loop = asyncio.get_running_loop()
        # Peak BEFORE any flush decision: this line sees the deepest the
        # backlog ever gets (every submit/submit_many lands here with its
        # items already appended, before _flush_now pops them).
        if len(self.pending) > self.peak_depth:
            self.peak_depth = len(self.pending)  # noqa: LD001
        if len(self.pending) >= self.engine.max_batch:
            self._flush_now("full")
        elif self.inflight == 0 and self._flush_handle is None:
            # Device idle: flush on the next loop turn (after every
            # already-runnable coroutine has had the chance to co-submit),
            # optionally stretched by max_delay to coalesce more.
            if self.engine.max_delay > 0:
                self._flush_handle = loop.call_later(
                    self.engine.max_delay, self._flush_now, "timer"
                )
            else:
                self._flush_handle = loop.call_soon(self._flush_now, "idle")
        # else: a dispatch is in flight — accumulate; its completion flushes.
        return fut

    def _flush_now(self, reason: str = "direct") -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        max_batch = self.engine.max_batch
        while self.pending and self.inflight < self.engine.max_inflight:
            batch = self.pending[:max_batch]
            del self.pending[:max_batch]
            self.inflight += 1
            # The reason rides with the batch and is counted in _run's
            # success accounting alongside ``batches``.
            self._spawn(self._run(batch, reason))

    # -- dispatch with the liveness net -------------------------------------

    async def _dispatch_with_fallback(self, items):
        """Run the dispatcher with a liveness net: on remote-attached
        chips the tunnel occasionally stalls indefinitely mid-dispatch,
        and a hung kernel call would wedge the whole queue — every
        protocol task awaiting a result, forever.  The per-item host path
        computes the same function, so after ``dispatch_timeout`` the same
        items are re-run on the HOST (serial — slow but certain) and the
        hung thread is abandoned; repeated timeouts write the device off
        for this queue entirely (every later batch goes straight to host)
        rather than paying the timeout again and again.

        Returns ``(results, used_fallback)`` — the flag rides WITH the
        results so callers account items and fallbacks atomically at
        resolution time (a flag on ``self`` would race concurrent
        max_inflight dispatches across the awaits)."""
        fallback = self._fallback()
        timeout = self.engine.dispatch_timeout
        enabled = self._device_enabled_fast()
        if enabled is None:
            # Unresolved (first sign dispatch): the backend probe
            # initializes jax — run it on a worker thread so the event
            # loop (protocol timers, every other coroutine) never
            # stalls behind a backend init.
            enabled = await asyncio.to_thread(self._device_enabled)
        if fallback is not None and not enabled:
            # No healthy device for this queue (e.g. the sign queues on a
            # CPU backend): the host path IS the path — no timeout arming,
            # no write-off bookkeeping, fallback recorded in stats.  This
            # gate deliberately outranks the timeout<=0 shortcut below:
            # disabling the liveness net must not re-route sign batches
            # onto a backend the auto-gate ruled out.
            return await asyncio.to_thread(fallback, items), True
        if fallback is None or timeout <= 0:
            return await asyncio.to_thread(self.dispatch, items), False
        if self._device_written_off:
            # The write-off is a demotion, not a death sentence: after
            # _REPROBE_AFTER a duplicate of this batch re-tries the device
            # OUT-OF-BAND (one at a time — _probing gates) and restores
            # the queue on success.  The live batch always goes straight
            # to the fallback: a probe of a still-dead device must never
            # hold protocol work hostage for its timeout.
            due = time.monotonic() - self._written_off_at >= self._REPROBE_AFTER
            if due and not self._probing:
                self._probing = True
                self._spawn(self._probe(list(items)))
            return await asyncio.to_thread(fallback, items), True
        if not self._device_ever_succeeded:
            # Cold compile may be inside this dispatch — see
            # _FIRST_TIMEOUT_FACTOR.
            timeout *= self._FIRST_TIMEOUT_FACTOR
        task = asyncio.ensure_future(asyncio.to_thread(self.dispatch, items))
        try:
            results = await asyncio.wait_for(asyncio.shield(task), timeout)
            self._consecutive_timeouts = 0  # the device is healthy again
            self._device_ever_succeeded = True
            return results, False
        except asyncio.TimeoutError:
            # Abandon the hung thread; swallow whatever it eventually
            # raises (an abandoned-task exception would otherwise spam
            # "Task exception was never retrieved").
            task.add_done_callback(
                lambda t: t.exception() if not t.cancelled() else None
            )
            self.stats.dispatch_timeouts += 1
            self._consecutive_timeouts += 1
            if self._consecutive_timeouts >= self._WRITE_OFF_AFTER:
                self._device_written_off = True
                self._written_off_at = time.monotonic()
            import logging

            logging.getLogger("minbft.engine").error(
                "%s device dispatch hung >%ss (%d consecutive%s): "
                "running %d items on host",
                self.name,
                timeout,
                self._consecutive_timeouts,
                "; device written off" if self._device_written_off else "",
                len(items),
            )
            return await asyncio.to_thread(fallback, items), True

    async def _probe(self, items) -> None:
        """Out-of-band re-probe of a written-off device with a duplicate
        of a live batch (the duplicates' results are discarded — the live
        batch resolved via the fallback).  Success restores the device
        queue; failure re-arms the re-probe clock."""
        import logging

        task = asyncio.ensure_future(asyncio.to_thread(self.dispatch, items))
        try:
            await asyncio.wait_for(
                asyncio.shield(task), self.engine.dispatch_timeout
            )
            self._device_written_off = False
            self._consecutive_timeouts = 0
            self._device_ever_succeeded = True
            logging.getLogger("minbft.engine").warning(
                "%s device recovered on re-probe: restoring device queue",
                self.name,
            )
        except asyncio.TimeoutError:
            task.add_done_callback(
                lambda t: t.exception() if not t.cancelled() else None
            )
            self._written_off_at = time.monotonic()
        except Exception:
            self._written_off_at = time.monotonic()
        finally:
            self._probing = False


class _SchemeQueue(_DispatchQueue):
    """Pending verifications for one scheme, with ship-when-idle flush.

    Verification is a pure function of the item, and one engine typically
    serves a whole cluster (BASELINE.json: one chip verifies for all n
    replicas), so identical items are deduplicated: a memo LRU returns
    known verdicts instantly, and an in-flight map lets concurrent
    duplicates await the same lane instead of occupying n lanes.  (The n
    replicas of a cluster all verify the same client signature and the
    same primary UI — dedup turns those n device verifies into one.)
    """

    _MEMO_CAP = 16384
    # Failed verdicts live in their own, much smaller LRU: a flood of
    # distinct garbage signatures must not evict known-GOOD verdicts and
    # re-drive device traffic for them (round-4 verdict weak #7).  Small
    # because negative hits only matter for byzantine *retransmissions* of
    # the same bad item — there is no protocol reason to remember many.
    _NEG_MEMO_CAP = 512

    def __init__(self, engine: "BatchVerifier", name: str, dispatch):
        super().__init__(engine, name, dispatch)
        self.stats = VerifyStats()
        self._memo: "OrderedDict[object, bool]" = OrderedDict()
        self._neg_memo: "OrderedDict[object, bool]" = OrderedDict()
        self._inflight_futs: Dict[object, asyncio.Future] = {}

    def _fallback(self):
        return self.engine._host_fallback_for(self.name)

    def submit(self, item) -> "asyncio.Future | _Resolved":
        out = self._enqueue(item)
        if self.pending:
            self._schedule_flush(None)
        return out

    def submit_many(self, items) -> list:
        """Batch entry point (the ingest runtime's one-call feed): enqueue
        every item, then schedule ONE flush — the whole bundle lands in
        ``pending`` before any dispatch decision, so a decoded ingest
        bundle becomes at most ceil(len/max_batch) device batches instead
        of racing item-by-item against the idle flush.  Returns one
        awaitable per item (memo hits resolve instantly, duplicates share
        lanes — exactly :meth:`submit`'s semantics, item-wise)."""
        outs = [self._enqueue(it) for it in items]
        if self.pending:
            self._schedule_flush(None)
        return outs

    def _enqueue(self, item) -> "asyncio.Future | _Resolved":
        if not self.engine.dedup:
            # Measurement mode (round-4 verdict weak #1): every submission
            # occupies its own device lane — no memo, no in-flight
            # coalescing — so device traffic equals the protocol's logical
            # verification demand.  Duplicate items in one batch resolve
            # together on the first lane's pop (same pure-function verdict).
            loop = asyncio.get_running_loop()
            fut = loop.create_future()
            self._inflight_futs.setdefault(item, []).append(fut)
            self.pending.append((item, fut, time.monotonic_ns()))
            return fut
        verdict = self._memo.get(item)
        if verdict is None:
            verdict = self._neg_memo.get(item)
            memo = self._neg_memo
        else:
            memo = self._memo
        if verdict is not None:
            memo.move_to_end(item)
            self.stats.memo_hits += 1
            return _Resolved(verdict)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        waiters = self._inflight_futs.get(item)
        if waiters is not None:
            # Every duplicate awaiter gets its OWN future (resolved
            # together): sharing one future would let any awaiter's task
            # cancellation cancel it for all of them.
            self.stats.memo_hits += 1
            waiters.append(fut)
            return fut
        self._inflight_futs[item] = [fut]
        self.pending.append((item, fut, time.monotonic_ns()))
        return fut

    def _resolve_error(self, batch, e: BaseException) -> None:
        for it, _f, _t in batch:
            for fut in self._inflight_futs.pop(it, ()):
                if not fut.done():
                    fut.set_exception(e)

    def _resolve(self, batch, results, fell_back: bool) -> None:
        dedup = self.engine.dedup
        for (it, _f, _t), ok in zip(batch, results):
            ok = bool(ok)
            if dedup:
                # Pure function: verdicts (both ways) are stable — but they
                # age out of segregated LRUs so garbage cannot evict good.
                memo = self._memo if ok else self._neg_memo
                memo[it] = ok
            for fut in self._inflight_futs.pop(it, ()):
                if not fut.done():
                    fut.set_result(ok)
        # Loop-confined trims: each popitem is atomic on the event loop
        # and the while re-checks after every one, so interleaving with a
        # concurrent resolve only trims more — no cross-await invariant.
        while len(self._memo) > self._MEMO_CAP:
            self._memo.popitem(last=False)
        while len(self._neg_memo) > self._NEG_MEMO_CAP:
            self._neg_memo.popitem(last=False)


class _SignQueue(_DispatchQueue):
    """Pending signatures for one scheme — the sign-side mirror of
    :class:`_SchemeQueue` (same ship-when-idle flush, bucket padding,
    recycled staging, ``max_inflight`` workers, hung-dispatch fallback)
    with the dedup shortcuts deliberately ABSENT: no memo, no in-flight
    coalescing.  Every submission occupies its own lane — a sign is a
    distinct protocol event under the caller's own key (two replicas
    signing byte-identical REPLY content must each produce and account
    for their own signature), so nothing here may short-circuit on item
    equality.  Contrast the USIG, which must not batch at all: its
    counter is incremented only after each certificate exists
    (ref usig.c:66-69), an inherently serial per-key discipline — USIG
    signing never reaches this queue.
    """

    def __init__(self, engine: "BatchVerifier", name: str, dispatch):
        super().__init__(engine, name, dispatch)
        self.stats = SignStats()

    def _fallback(self):
        return self.engine._sign_fallback_for(self.name)

    def _device_enabled(self) -> bool:
        return self.engine._sign_device_enabled()

    def _device_enabled_fast(self):
        # None until the first resolution (reading the backend can
        # block) — see _DispatchQueue._device_enabled_fast.
        return self.engine._sign_on_device

    def submit(self, item) -> asyncio.Future:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.pending.append((item, fut, time.monotonic_ns()))
        return self._schedule_flush(fut)

    def _resolve_error(self, batch, e: BaseException) -> None:
        for _it, fut, _t in batch:
            if not fut.done():
                fut.set_exception(e)

    def _resolve(self, batch, results, fell_back: bool) -> None:
        if fell_back:
            # Accounted HERE, with items, so the two counters can never
            # skew apart (e.g. across a bench warmup stats reset).
            self.stats.host_fallback_items += len(batch)
        for (_it, fut, _t), sig in zip(batch, results):
            if not fut.done():
                fut.set_result(sig)


class BatchVerifier:
    """The TPU-backed batch verification engine.

    Schemes: ``ecdsa_p256`` (items: ((qx, qy), digest32, (r, s))),
    ``hmac_sha256`` (items: (key32, msg32, mac32) bytes), and
    ``ed25519`` (items: (pub32, msg, sig64) bytes).

    ``max_batch`` bounds the device batch (and the largest compiled bucket);
    ``max_delay`` optionally stretches the idle-device flush to coalesce
    more items (0 = flush on the next event-loop turn); ``max_inflight``
    bounds concurrent kernel dispatches per scheme (2 keeps the device fed
    while the next batch accumulates).
    """

    def __init__(
        self,
        max_batch: int = 512,
        max_delay: float = 0.0,
        buckets: Optional[Sequence[int]] = None,
        max_inflight: int = 2,
        mesh=None,
        dispatch_timeout: float = 90.0,
        dedup: bool = True,
        sign_on_device: Optional[bool] = None,
        device=None,
    ):
        # Sign-queue device placement.  None = auto: the device sign
        # kernels (fixed-base comb k*G / r*B) only beat serial host
        # OpenSSL on a real accelerator — on the CPU backend a sign batch
        # would pad to a full comb-kernel compile for no win, so auto
        # resolves to False there and every sign batch transparently runs
        # the host fallback with the fallback recorded in SignStats
        # (host_fallback_items).  Resolved lazily on first use (reading
        # the backend initializes it); tests force True to exercise the
        # device path on CPU.
        self._sign_on_device = sign_on_device
        # dedup=False is a MEASUREMENT mode: every logical verification
        # occupies a device lane (no memo, no in-flight coalescing), so
        # reported device verifies/s equals protocol demand — see
        # _SchemeQueue.submit.  Production keeps dedup on.
        self.dedup = dedup
        # Liveness net for remote-attached chips: a device dispatch that
        # exceeds this many seconds (generous — cold bucket compiles take
        # ~40s) is abandoned and its items re-verified on host; see
        # _SchemeQueue._dispatch_with_fallback.  0 disables.
        self.dispatch_timeout = dispatch_timeout
        # Multi-chip: pass a jax.sharding.Mesh (parallel.mesh.make_mesh)
        # and every device dispatch routes through the sharded kernels —
        # the batch axis is partitioned over the mesh and XLA lays the
        # per-chip programs out over ICI (BASELINE config[4]'s scaling
        # axis).  A 1-device mesh degenerates to the single-chip kernels.
        self.mesh = mesh if (mesh is not None and mesh.size > 1) else None
        # Home-chip pinning (the multi-device engine pool): a jax device
        # this engine's kernel dispatches run on.  None keeps jax's
        # default placement — byte-identical to the pre-pool engine, and
        # the only mode the C=1 pool uses.  Mutually exclusive with
        # ``mesh`` by construction: a mesh-routed engine stripes across
        # chips, a pinned engine owns one.
        if device is not None and self.mesh is not None:
            raise ValueError("pass either device= (home chip) or mesh=, not both")
        self.device = device
        self._sharded_kernels: Dict[str, object] = {}
        self._sharded_lock = threading.Lock()
        # Stats fields are owned per-field: the event loop owns the counts
        # _run updates; padded_lanes and host_prep_time_s are updated by
        # the DISPATCHER, which runs on a worker thread
        # (asyncio.to_thread) — and max_inflight of them can race the
        # read-modify-write.  All dispatcher-side stats updates go through
        # this lock via _note_prep (tools/analyze lock-discipline
        # enforces it).
        self._stats_lock = threading.Lock()
        self.max_batch = max_batch
        self.max_delay = max_delay
        self.max_inflight = max_inflight
        # Default: a small geometric ladder of padded shapes (8, 32, 128,
        # ..., max_batch).  Each distinct bucket size is a separate kernel
        # compilation, but padding a batch of 3 to max_batch=512 wastes
        # ~170x device compute — the ladder bounds pad waste at 4x while
        # keeping the shape count logarithmic.  Pass explicit buckets (e.g.
        # ``(max_batch,)``) when compilation is the scarcer resource (the
        # unrolled ECDSA kernel).
        if buckets:
            self.buckets = tuple(buckets)
        else:
            ladder = []
            b = 8
            while b < max_batch:
                ladder.append(b)
                b *= 4
            ladder.append(max_batch)
            self.buckets = tuple(ladder)
        if self.buckets[-1] < max_batch:
            # An explicit bucket list smaller than max_batch would hand the
            # dispatchers an unplanned data-dependent shape (ADVICE r1).
            raise ValueError(
                f"largest bucket {self.buckets[-1]} < max_batch {max_batch}"
            )
        if self.mesh is not None:
            # Sharded kernels need every argument's batch axis divisible
            # by the mesh size (mesh.py documents the constraint) — round
            # each bucket up to the next multiple, which also keeps the
            # staging-buffer shapes (keyed by bucket) on the contract.
            from . import mesh as mesh_mod

            self.buckets = tuple(
                sorted({mesh_mod.round_up_to_mesh(self.mesh, b) for b in self.buckets})
            )
        self._queues: Dict[str, _SchemeQueue] = {}
        self._sign_queues: Dict[str, _SignQueue] = {}
        self._staging = _StagingPool(cap=max_inflight)
        # Flight-recorder hookup (obs/): dispatcher-side span events —
        # (queue, padded lanes, host-prep ns) per dispatch — pushed by
        # the WORKER threads into a multi-producer ring.  None until an
        # operator enables it; the disabled cost is one attribute check
        # per dispatch (not per item).  Queue-name ids are interned under
        # _stats_lock (the same cross-thread discipline as the stats).
        self._obs_ring = None
        self._obs_queue_ids: Dict[str, int] = {}

    # -- flight-recorder surface -------------------------------------------

    def enable_obs_ring(self, capacity: int = 4096) -> None:
        """Start recording per-dispatch span events (see _note_prep)."""
        from ..obs.trace import MTStageRing

        if self._obs_ring is None:
            self._obs_ring = MTStageRing(capacity)

    def _obs_queue_id(self, name: str) -> int:
        qid = self._obs_queue_ids.get(name)  # GIL-atomic fast path
        if qid is None:
            with self._stats_lock:
                qid = self._obs_queue_ids.get(name)
                if qid is None:
                    qid = len(self._obs_queue_ids)
                    self._obs_queue_ids[name] = qid
        return qid

    def drain_obs_events(self) -> list:
        """Decoded dispatcher span events, oldest→newest:
        (queue_name, padded_lanes, host_prep_ns, t_monotonic_ns)."""
        ring = self._obs_ring
        if ring is None:
            return []
        # dict() is a C-level copy (GIL-atomic): worker threads may be
        # interning new names while we decode.
        names = {v: k for k, v in dict(self._obs_queue_ids).items()}
        return [
            (names.get(qid, f"queue{qid}"), pad, prep_ns, t_ns)
            for qid, pad, prep_ns, t_ns in ring.snapshot()
        ]

    def queue_depths(self) -> Dict[str, int]:
        """Items pending per verify queue right now (scrape gauge).
        dict() snapshots the live queue map first — the metrics thread
        iterates while the loop lazily inserts new queues, and a bare
        .items() walk could see the dict resize mid-iteration; len() of
        a loop-owned list is GIL-atomic, never torn."""
        return {name: len(q.pending) for name, q in dict(self._queues).items()}

    def sign_queue_depths(self) -> Dict[str, int]:
        return {
            name: len(q.pending) for name, q in dict(self._sign_queues).items()
        }

    def queue_depth_peaks(self, reset: bool = True) -> Dict[str, int]:
        """High-water mark of each verify queue's depth since the last
        peak snapshot (ISSUE 14 satellite): the committed bench artifact
        and the scrape both want peak backlog, not the instantaneous
        gauge that misses every burst between samples.  ``reset`` rearms
        the mark at the CURRENT depth.  Called from scrape threads: the
        read and the rearm store are each GIL-atomic; a burst landing
        between them is picked up by the next snapshot (never torn,
        possibly attributed one window late — the same benign race the
        loop-confined metrics reads accept)."""
        out: Dict[str, int] = {}
        for name, q in dict(self._queues).items():
            out[name] = max(q.peak_depth, len(q.pending))
            if reset:
                q.peak_depth = len(q.pending)  # noqa: LD001
        return out

    def sign_queue_depth_peaks(self, reset: bool = True) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for name, q in dict(self._sign_queues).items():
            out[name] = max(q.peak_depth, len(q.pending))
            if reset:
                q.peak_depth = len(q.pending)  # noqa: LD001
        return out

    def _device_scope(self):
        """Placement scope for one dispatch: ``jax.default_device`` bound
        to the engine's home chip, or a no-op when unpinned.  Entered on
        the WORKER thread around the kernel call — jax's config scopes
        are thread-local, so concurrent engines pinned to different
        chips never fight over a global default."""
        if self.device is None:
            import contextlib

            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.device)

    def _sharded(self, name: str, builder):
        # Dispatchers run on worker threads (max_inflight > 1): lock the
        # memo so two concurrent first dispatches don't both trace and
        # compile the same sharded kernel.
        with self._sharded_lock:
            k = self._sharded_kernels.get(name)
            if k is None:
                k = builder(self.mesh)
                self._sharded_kernels[name] = k
            return k

    # -- queues -------------------------------------------------------------

    def _queue(self, name: str, dispatch) -> _SchemeQueue:
        q = self._queues.get(name)
        if q is None:
            q = _SchemeQueue(self, name, dispatch)
            # Loop-side publish of a fresh queue: a GIL-atomic dict store;
            # worker threads only ever read entries that existed before
            # their dispatch was scheduled.
            self._queues[name] = q  # noqa: LD001
        return q

    def _sign_queue(self, name: str, dispatch) -> _SignQueue:
        q = self._sign_queues.get(name)
        if q is None:
            q = _SignQueue(self, name, dispatch)
            # Loop-side publish (see _queue): a GIL-atomic dict store.
            self._sign_queues[name] = q  # noqa: LD001
        return q

    def _host_fallback_for(self, name: str):
        """Serial host re-verification for a DEVICE queue's items (None
        for the host queues themselves — they cannot hang on a tunnel)."""
        return {
            "ecdsa_p256": self._dispatch_ecdsa_host,
            "hmac_sha256": self._dispatch_hmac_host,
            "ed25519": self._dispatch_ed25519_host,
        }.get(name)

    def _sign_fallback_for(self, name: str):
        """Serial host signing for a sign queue's items — the write-off /
        timeout / CPU-backend net.  OpenSSL-backed (hostcrypto picks the
        fast path), so a written-off device degrades to the measured
        ~900 signs/s host floor, never to pure-Python big-int signing."""
        from ..utils import hostcrypto as hc

        return {
            "ecdsa_p256": lambda items: [
                hc.ecdsa_sign(d, digest) for d, digest in items
            ],
            "ed25519": lambda items: [
                hc.ed25519_sign(seed, msg) for seed, msg in items
            ],
        }.get(name)

    def _sign_device_enabled(self) -> bool:
        v = self._sign_on_device
        if v is None:
            import jax

            v = jax.default_backend() != "cpu"
            self._sign_on_device = v
        return v

    @property
    def stats(self) -> Dict[str, VerifyStats]:
        # dict() snapshot: scrape threads iterate while the loop inserts
        # new queues (see queue_depths).
        return {name: q.stats for name, q in dict(self._queues).items()}

    @property
    def sign_stats(self) -> Dict[str, SignStats]:
        return {name: q.stats for name, q in dict(self._sign_queues).items()}

    # -- public API ---------------------------------------------------------

    async def verify_ecdsa_p256(
        self, pubkey: Tuple[int, int], digest: bytes, sig: Tuple[int, int]
    ) -> bool:
        q = self._queue("ecdsa_p256", self._dispatch_ecdsa)
        return await q.submit((pubkey, digest, sig))

    async def verify_ecdsa_p256_host(
        self, pubkey: Tuple[int, int], digest: bytes, sig: Tuple[int, int]
    ) -> bool:
        """Host-dispatched queue: same dedup memo as the device queue (one
        engine serves the cluster, so the n replicas' identical signature
        checks collapse to one) without coupling each verification to a
        device round trip — the right placement for per-message signature
        checks on hosts where the chip is remote-attached."""
        q = self._queue("ecdsa_p256_host", self._dispatch_ecdsa_host)
        return await q.submit((pubkey, digest, sig))

    async def verify_hmac_sha256(self, key: bytes, msg32: bytes, mac: bytes) -> bool:
        q = self._queue("hmac_sha256", self._dispatch_hmac)
        return await q.submit((key, msg32, mac))

    async def verify_hmac_sha256_host(
        self, key: bytes, msg32: bytes, mac: bytes
    ) -> bool:
        q = self._queue("hmac_sha256_host", self._dispatch_hmac_host)
        return await q.submit((key, msg32, mac))

    async def verify_ed25519(self, pub: bytes, msg: bytes, sig: bytes) -> bool:
        q = self._queue("ed25519", self._dispatch_ed25519)
        return await q.submit((pub, msg, sig))

    async def verify_ed25519_host(self, pub: bytes, msg: bytes, sig: bytes) -> bool:
        q = self._queue("ed25519_host", self._dispatch_ed25519_host)
        return await q.submit((pub, msg, sig))

    async def _verify_many(self, name: str, dispatch, items) -> list:
        """Whole-bundle verification feed (the batch-ingest runtime's one
        engine call per decoded bundle): every item lands in the queue
        before ONE flush decision, so an N-item bundle dispatches as
        ~N/max_batch device batches instead of N racing idle flushes.
        Returns per-item verdicts in input order."""
        q = self._queue(name, dispatch)
        outs = q.submit_many(items)
        # Gather with return_exceptions so EVERY lane's outcome is
        # consumed even when the batch errors — awaiting sequentially
        # would abandon lanes 2..N after the first raise and spam
        # "Future exception was never retrieved" at GC.
        results = await asyncio.gather(*outs, return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return list(results)

    async def verify_ecdsa_p256_many(self, items) -> list:
        """Batch sibling of :meth:`verify_ecdsa_p256`:
        ``items = [((qx, qy), digest32, (r, s)), ...]`` -> [bool, ...]."""
        return await self._verify_many("ecdsa_p256", self._dispatch_ecdsa, items)

    async def verify_ecdsa_p256_host_many(self, items) -> list:
        return await self._verify_many(
            "ecdsa_p256_host", self._dispatch_ecdsa_host, items
        )

    async def verify_ed25519_many(self, items) -> list:
        """Batch sibling of :meth:`verify_ed25519`:
        ``items = [(pub32, msg, sig64), ...]`` -> [bool, ...]."""
        return await self._verify_many("ed25519", self._dispatch_ed25519, items)

    async def verify_ed25519_host_many(self, items) -> list:
        return await self._verify_many(
            "ed25519_host", self._dispatch_ed25519_host, items
        )

    async def verify_nist_host(
        self, curve: str, pub: bytes, msg: bytes, sig: bytes
    ) -> bool:
        """Host-queue verification for the wider NIST curves (P-384/P-521
        have no TPU kernel): worker-thread OpenSSL behind the same dedup
        memo + thread-hop batching as the other host queues."""
        name = f"ecdsa_{curve}_host"
        q = self._queues.get(name)
        if q is None:
            from ..utils import hostcrypto as hc

            def dispatch(items, _curve=curve):
                return np.array(
                    [hc.nist_verify(_curve, p, m, s) for p, m, s in items],
                    dtype=bool,
                )

            q = self._queue(name, dispatch)
        return await q.submit((pub, msg, sig))

    # -- signing ------------------------------------------------------------
    #
    # The awaitable batch sign surface (DSig's off-critical-path signing
    # restructured for TPU): protocol tasks await a lane, the queue ships
    # fixed-bucket batches of k*G / r*B through the fixed-base comb
    # kernels, and the cheap big-int scalar work (RFC 6979 / RFC 8032
    # nonces, one Montgomery batch inversion per batch) stays on the
    # host — see ops/p256.py sign_prepare/sign_finish.  USIG UI signing
    # must NEVER route here: its counter is incremented only after the
    # certificate exists (ref usig.c:66-69), a serial per-key discipline.

    async def sign_ecdsa_p256(self, d: int, digest: bytes) -> Tuple[int, int]:
        """Batch-sign ``digest`` under private scalar ``d`` -> (r, s).
        RFC 6979 deterministic — byte-identical to
        ``hostcrypto.ecdsa_sign_py`` on the device path; the host
        fallback signs with OpenSSL (random nonce, equally valid)."""
        q = self._sign_queue("ecdsa_p256", self._dispatch_sign_ecdsa)
        return await q.submit((d, digest))

    async def sign_ed25519(self, seed: bytes, msg: bytes) -> bytes:
        """Batch-sign ``msg`` under ``seed`` -> 64-byte RFC 8032
        signature (deterministic on every path)."""
        q = self._sign_queue("ed25519", self._dispatch_sign_ed25519)
        return await q.submit((seed, msg))

    # -- dispatchers (worker thread; jax work happens here) -----------------
    #
    # Shape: acquire a recycled staging buffer, prep/pack the batch into
    # it (timed separately as host_prep_time_s — the prep/device split is
    # a first-class measurement), dispatch the kernel, materialize the
    # results, release the buffer.  The release MUST stay behind the
    # result materialization: jax may still be reading the host buffer
    # until the dispatch completes, and a released buffer can be
    # re-acquired and overwritten by a concurrent dispatcher.

    def _note_prep(self, name: str, pad: int, prep_s: float) -> None:
        """Cross-thread stats update for a dispatcher (worker thread):
        padded-lane and host-prep accounting under the stats lock."""
        with self._stats_lock:
            st = self._queues[name].stats
            st.padded_lanes += pad
            st.host_prep_time_s += prep_s
        ring = self._obs_ring
        if ring is not None:
            # Dispatcher span event from the worker thread: the ring's
            # own lock serializes concurrent max_inflight producers.
            ring.push(
                self._obs_queue_id(name),
                pad,
                int(prep_s * 1e9),
                time.monotonic_ns(),
            )

    def _note_sign_prep(self, name: str, pad: int, prep_s: float) -> None:
        """Sign-queue sibling of :meth:`_note_prep` (worker thread):
        same lock, the SignStats of ``_sign_queues[name]``."""
        with self._stats_lock:
            st = self._sign_queues[name].stats
            st.padded_lanes += pad
            st.host_prep_time_s += prep_s
        ring = self._obs_ring
        if ring is not None:
            ring.push(
                self._obs_queue_id("sign_" + name),
                pad,
                int(prep_s * 1e9),
                time.monotonic_ns(),
            )

    def _dispatch_ecdsa(self, items) -> np.ndarray:
        import jax.numpy as jnp

        from ..ops import p256

        n = len(items)
        b = _bucket_for(n, self.buckets)
        # Packed single-upload form: on tunnel-attached chips each array
        # is its own RPC and the 8-argument form paid 8 of them per
        # dispatch — the dominant share of the e2e dispatch round trip.
        t0 = time.perf_counter()
        staging = self._staging.acquire((b, p256.PACKED_COLS), np.uint16)
        try:
            packed = p256.prepare_packed(items, b, out=staging)
            self._note_prep("ecdsa_p256", b - n, time.perf_counter() - t0)
            if self.mesh is not None:
                from . import mesh as mesh_mod

                kernel = self._sharded("ecdsa", mesh_mod.sharded_ecdsa_kernel)
                return np.asarray(kernel(packed))[:n]
            with self._device_scope():
                out = p256.ecdsa_verify_kernel_packed(jnp.asarray(packed))
                return np.asarray(out)[:n]
        finally:
            self._staging.release(staging)

    def _dispatch_hmac(self, items) -> np.ndarray:
        import jax.numpy as jnp

        from ..ops.hmac_sha256 import hmac_verify_kernel_packed

        n = len(items)
        b = _bucket_for(n, self.buckets)
        t0 = time.perf_counter()
        staging = self._staging.acquire((b, 24), np.uint32)
        try:
            # One bulk big-endian word view of the concatenated batch
            # instead of 3n per-item frombuffer calls.
            staging[:n] = np.frombuffer(
                b"".join([key + msg + mac for key, msg, mac in items]),
                dtype=">u4",
            ).reshape(n, 24)
            staging[n:] = 0
            self._note_prep("hmac_sha256", b - n, time.perf_counter() - t0)
            if self.mesh is not None:
                from . import mesh as mesh_mod

                kernel = self._sharded("hmac", mesh_mod.sharded_hmac_kernel)
                return np.asarray(kernel(staging))[:n]
            with self._device_scope():
                out = hmac_verify_kernel_packed(jnp.asarray(staging))
                return np.asarray(out)[:n]
        finally:
            self._staging.release(staging)

    def _dispatch_ed25519(self, items) -> np.ndarray:
        import jax.numpy as jnp

        from ..ops import ed25519 as ed

        n = len(items)
        b = _bucket_for(n, self.buckets)
        t0 = time.perf_counter()
        staging = self._staging.acquire((b, ed.PACKED_COLS), np.uint16)
        try:
            packed = ed.prepare_packed(items, b, out=staging)
            self._note_prep("ed25519", b - n, time.perf_counter() - t0)
            if self.mesh is not None:
                from . import mesh as mesh_mod

                kernel = self._sharded("ed25519", mesh_mod.sharded_ed25519_kernel)
                return np.asarray(kernel(packed))[:n]
            with self._device_scope():
                out = ed.ed25519_verify_kernel_packed(jnp.asarray(packed))
                return np.asarray(out)[:n]
        finally:
            self._staging.release(staging)

    # Sign dispatchers: prep (host) → comb kernel (device) → finish
    # (host), with the nonce-limb staging recycled through the pool and
    # BOTH host halves timed into SignStats.host_prep_time_s.  The
    # staging release stays behind the result materialization, exactly
    # like the verify dispatchers.

    def _dispatch_sign_ecdsa(self, items) -> list:
        from ..ops import p256

        n = len(items)
        b = _bucket_for(n, self.buckets)
        t0 = time.perf_counter()
        staging = self._staging.acquire((b, p256.SIGN_COLS), np.uint16)
        try:
            k_arr, meta = p256.sign_prepare(items, b, out=staging)
            prep = time.perf_counter() - t0
            if self.mesh is not None:
                from . import mesh as mesh_mod

                kernel = self._sharded(
                    "ecdsa_sign", mesh_mod.sharded_ecdsa_sign_kernel
                )
            else:
                kernel = p256.ecdsa_kg_kernel
            with self._device_scope():
                xz = np.asarray(kernel(k_arr))
            t1 = time.perf_counter()
            sigs = p256.sign_finish(items, meta, xz)
            prep += time.perf_counter() - t1
            self._note_sign_prep("ecdsa_p256", b - n, prep)
            return sigs
        finally:
            self._staging.release(staging)

    def _dispatch_sign_ed25519(self, items) -> list:
        from ..ops import ed25519 as ed

        n = len(items)
        b = _bucket_for(n, self.buckets)
        t0 = time.perf_counter()
        staging = self._staging.acquire((b, ed.SIGN_COLS), np.uint16)
        try:
            r_arr, meta = ed.sign_prepare(items, b, out=staging)
            prep = time.perf_counter() - t0
            if self.mesh is not None:
                from . import mesh as mesh_mod

                kernel = self._sharded(
                    "ed25519_sign", mesh_mod.sharded_ed25519_sign_kernel
                )
            else:
                kernel = ed.ed25519_rb_kernel
            with self._device_scope():
                xyz = np.asarray(kernel(r_arr))
            t1 = time.perf_counter()
            sigs = ed.sign_finish(meta, xyz)
            prep += time.perf_counter() - t1
            self._note_sign_prep("ed25519", b - n, prep)
            return sigs
        finally:
            self._staging.release(staging)

    # Host dispatchers: serial OpenSSL in the worker thread — no padding,
    # no device round trip; the queue layer still provides batching of the
    # thread hops plus the dedup memo.

    def _dispatch_ecdsa_host(self, items) -> np.ndarray:
        from ..utils import hostcrypto as hc

        return np.array(
            [hc.ecdsa_verify(q, digest, sig) for q, digest, sig in items],
            dtype=bool,
        )

    def _dispatch_hmac_host(self, items) -> np.ndarray:
        import hashlib
        import hmac as hmac_mod

        return np.array(
            [
                hmac_mod.compare_digest(
                    hmac_mod.new(key, msg, hashlib.sha256).digest(), mac
                )
                for key, msg, mac in items
            ],
            dtype=bool,
        )

    def _dispatch_ed25519_host(self, items) -> np.ndarray:
        from ..utils import hostcrypto as hc

        return np.array(
            [hc.ed25519_verify(pub, msg, sig) for pub, msg, sig in items],
            dtype=bool,
        )
