"""Device-mesh sharding for the batch verification kernels.

The reference scales by adding replicas connected over gRPC (reference
sample/conn/grpc/); its crypto cost grows linearly and stays on each
replica's CPU.  Here the batch-verification workload is data-parallel by
construction, so scaling across TPU chips is a sharding annotation, not a
communication protocol: place the batch axis over a 1-D ``Mesh`` and XLA
partitions the kernel, with any cross-chip reduction (e.g. the "whole
quorum valid" conjunction) riding ICI collectives.

BASELINE config[4] (n=31, batch=1024, v4-8) maps to ``sharded_verifier``
with an 8-device mesh: 128 lanes per chip, one fused program per chip, one
all-reduce for aggregate statistics.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D device mesh over the batch axis.

    Defaults to all visible devices; pass an explicit device list (e.g. a
    CPU-backend virtual 8-device set in tests / ``dryrun_multichip``)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis across the mesh."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def round_up_to_mesh(mesh: Mesh, n: int) -> int:
    """Smallest multiple of the mesh size >= n.

    The batch-axis divisibility contract for every sharded kernel here:
    bucket ladders AND the engine's staging buffers must pad to THIS (the
    engine rounds its buckets through it at construction), or jit raises a
    sharding error at dispatch time."""
    sz = mesh.size
    return -(-n // sz) * sz


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_verifier(scalar_verify: Callable, mesh: Mesh, n_args: int):
    """vmap a scalar-shaped kernel and jit it with the batch axis sharded
    over ``mesh``.

    ``scalar_verify``: per-item kernel (limb/word arrays in; any output
    whose leading axis is the batch — bools for the verifiers, limb
    arrays for the sign kernel; trailing dims are replicated).
    ``n_args``: number of positional array arguments (all batch-leading).

    The result expects every argument's leading dimension to be a multiple
    of the mesh size (the engine's bucket sizes guarantee this).

    Per-lowering-mode jit (like the single-chip kernel entry points): the
    mode is read at trace time, so one jit instance would silently reuse
    whichever mode compiled first at a given shape.
    """
    sh = batch_sharding(mesh)
    batched = jax.vmap(scalar_verify)

    def build():
        return jax.jit(
            batched,
            in_shardings=(sh,) * n_args,
            out_shardings=sh,
        )

    import threading

    cache = {}
    lock = threading.Lock()  # callers dispatch from worker threads

    def wrapper(*args):
        from ..ops import lowering

        m = lowering.mode()
        with lock:
            fn = cache.get(m)
            if fn is None:
                fn = build()
                cache[m] = fn
        return fn(*args)

    return wrapper


def sharded_ecdsa_kernel(mesh: Mesh):
    """Batched ECDSA-P256 verify sharded across ``mesh`` — packed
    single-upload form ([B, PACKED_COLS] u16, see
    :func:`minbft_tpu.ops.p256.pack_arrays`): the batch axis partitions
    over the mesh; trailing columns replicate per lane."""
    from ..ops import p256

    return sharded_verifier(p256._verify_one_packed, mesh, 1)


def sharded_hmac_kernel(mesh: Mesh):
    """Batched HMAC-SHA256 verify sharded across ``mesh`` (packed
    [B, 24] u32 rows)."""
    from ..ops import hmac_sha256 as hs

    def one(row):
        return hs.hmac32_verify(row[0:8], row[8:16], row[16:24])

    return sharded_verifier(one, mesh, 1)


def sharded_ed25519_kernel(mesh: Mesh):
    """Batched Ed25519 verify sharded across ``mesh`` — packed
    single-upload form (see :func:`minbft_tpu.ops.ed25519.pack_arrays`)."""
    from ..ops import ed25519 as ed

    return sharded_verifier(ed._verify_one_packed, mesh, 1)


def sharded_ecdsa_sign_kernel(mesh: Mesh):
    """Batched fixed-base k*G (the device half of ECDSA signing,
    :func:`minbft_tpu.ops.p256.sign_batch`) sharded across ``mesh``:
    takes [B, 16] nonce limbs, returns [B, 2, 16] X/Z limbs (uint16).
    Uses the fixed-base comb kernel; its precomputed table is a
    compile-time constant replicated on every device."""
    import jax.numpy as jnp

    from ..ops import p256

    table = jnp.asarray(p256._comb_table_np())

    def kg_one(k):
        return p256._kg_comb_one(k.astype(jnp.uint32), table)

    return sharded_verifier(kg_one, mesh, 1)


def sharded_ed25519_sign_kernel(mesh: Mesh):
    """Batched fixed-base r*B (the device half of Ed25519 signing,
    :func:`minbft_tpu.ops.ed25519.sign_batch`) sharded across ``mesh``:
    [B, 16] nonce limbs in, [B, 3, 16] X/Y/Z limbs (uint16) out; the
    comb table replicates as a compile-time constant per device."""
    import jax.numpy as jnp

    from ..ops import ed25519 as ed

    table = jnp.asarray(ed._comb_table_np())

    def rb_one(r):
        return ed._rb_comb_one(r.astype(jnp.uint32), table)

    return sharded_verifier(rb_one, mesh, 1)
