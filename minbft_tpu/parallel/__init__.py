"""Parallel execution layer: the asyncio↔TPU batching engine and device-mesh
sharding helpers.

This is where the reference's serial per-message CPU crypto (reference
core/message-handling.go:363-377 validate-then-process, core/commit.go:108-143
mutex-serialized quorum collection) becomes submit-batch-then-resolve: many
concurrent protocol tasks await individual verification results while the
engine coalesces them into fixed-shape batches dispatched to one XLA kernel
(one chip) or a sharded mesh (many chips).
"""

from .engine import BatchVerifier, SignStats, VerifyStats
from .pool import EnginePool

__all__ = ["BatchVerifier", "EnginePool", "SignStats", "VerifyStats"]
