"""Multi-device engine pool: one batching engine per home chip.

PR 8 proved the coalescing claim on ONE device: G consensus groups
sharing a single :class:`~minbft_tpu.parallel.engine.BatchVerifier`
raise verify batch fill with G (2.0 → 32.0 across G=1..16) because every
group's authenticator lands checks in the same scheme queues.  The chip
ceiling, though, is per *device* — ~164k ECDSA verifies/s on one chip
while the other seven idle (ROADMAP item 1, the MULTICHIP dryruns).

:class:`EnginePool` replicates the PR-8 win **per chip** instead of
diluting it globally:

- one :class:`BatchVerifier` per home chip — its own verify/sign
  queues, staging pool, and dedup memo, pinned to its device
  (``BatchVerifier(device=...)``);
- a **placement policy** mapping each consensus group to exactly one
  home chip (static round-robin ``group % chips``), so all groups homed
  on a chip keep coalescing into that chip's queues exactly as PR 8
  measured — cross-chip traffic never splits a batch;
- a **rebalance hook** fed by the PR-9 ledger's per-chip
  ``busy × fill`` score: :meth:`rebalance` migrates groups off the
  hottest chip, but NEVER a group with in-flight dispatches (a migrated
  group's outstanding futures must all resolve on the engine that owns
  their memo/staging state);
- a **striping path** for oversized explicit batches: a ``verify_*_many``
  call larger than ``stripe_threshold`` routes through a mesh-routed
  engine (the existing ``mesh.sharded_*`` kernels partition the batch
  axis over all chips), because a batch that already fills several
  chips' buckets gains nothing from home-chip affinity.

Degenerate honesty: ``chips=1`` (or one visible device) builds exactly
ONE unpinned ``BatchVerifier`` and every facade call forwards to it —
the C=1 pool is byte-identical to the pre-pool engine (results, stats
accounting, flush decisions), which the differential fuzz in
tests/test_pool.py pins.

Concurrency: the placement map, per-group in-flight counters, and the
facade cache are event-loop confined (every mutation is a sync method or
a loop-atomic update around an await — LD-spec'd in
tools/analyze/project.py).  Scrape threads only read (GIL-atomic), the
same contract as the engine stats.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .engine import BatchVerifier


class _GroupEngine:
    """One group's BatchVerifier-compatible facade over the pool.

    Forwards the engine's public verify/sign surface to the group's
    CURRENT home-chip engine (placement is read per call, so a rebalance
    takes effect on the next submission), counting in-flight calls per
    group — the witness :meth:`EnginePool.rebalance` consults before
    migrating.  Attribute reads (``stats``, ``queue_depths``, ...) fall
    through to the home engine, so existing engine-shaped consumers keep
    working unchanged.
    """

    __slots__ = ("_pool", "group")

    def __init__(self, pool: "EnginePool", group: int):
        self._pool = pool
        self.group = int(group)

    @property
    def home(self) -> BatchVerifier:
        return self._pool._engines[self._pool.home_chip(self.group)]

    async def _call(self, name: str, *args):
        pool = self._pool
        g = self.group
        eng = pool._engines[pool.home_chip(g)]
        # Loop-atomic bump (sync before the await, decrement after):
        # rebalance reads this between awaits on the same loop, so a
        # group is only ever migrated with zero outstanding futures.
        pool._inflight[g] = pool._inflight.get(g, 0) + 1
        try:
            return await getattr(eng, name)(*args)
        finally:
            pool._inflight[g] -= 1

    async def _call_many(self, name: str, items):
        pool = self._pool
        g = self.group
        eng = pool._route_many(g, len(items))
        pool._inflight[g] = pool._inflight.get(g, 0) + 1
        try:
            return await getattr(eng, name)(items)
        finally:
            pool._inflight[g] -= 1

    # -- verify surface (mirrors BatchVerifier's public API) ---------------

    def verify_ecdsa_p256(self, pubkey, digest, sig):
        return self._call("verify_ecdsa_p256", pubkey, digest, sig)

    def verify_ecdsa_p256_host(self, pubkey, digest, sig):
        return self._call("verify_ecdsa_p256_host", pubkey, digest, sig)

    def verify_hmac_sha256(self, key, msg32, mac):
        return self._call("verify_hmac_sha256", key, msg32, mac)

    def verify_hmac_sha256_host(self, key, msg32, mac):
        return self._call("verify_hmac_sha256_host", key, msg32, mac)

    def verify_ed25519(self, pub, msg, sig):
        return self._call("verify_ed25519", pub, msg, sig)

    def verify_ed25519_host(self, pub, msg, sig):
        return self._call("verify_ed25519_host", pub, msg, sig)

    def verify_nist_host(self, curve, pub, msg, sig):
        return self._call("verify_nist_host", curve, pub, msg, sig)

    # Device _many entry points may stripe (oversized batches span the
    # mesh); the host _many variants never do — host queues have no
    # device to stripe over, and splitting their dedup memo would only
    # re-verify items the home chip already knows.

    def verify_ecdsa_p256_many(self, items):
        return self._call_many("verify_ecdsa_p256_many", items)

    def verify_ecdsa_p256_host_many(self, items):
        return self._call("verify_ecdsa_p256_host_many", items)

    def verify_ed25519_many(self, items):
        return self._call_many("verify_ed25519_many", items)

    def verify_ed25519_host_many(self, items):
        return self._call("verify_ed25519_host_many", items)

    # -- sign surface -------------------------------------------------------

    def sign_ecdsa_p256(self, d, digest):
        return self._call("sign_ecdsa_p256", d, digest)

    def sign_ed25519(self, seed, msg):
        return self._call("sign_ed25519", seed, msg)

    def __getattr__(self, name):
        # stats / queue_depths / dedup / buckets / ... — read-side
        # passthrough to the current home engine.
        return getattr(self._pool._engines[self._pool.home_chip(self.group)],
                       name)


class EnginePool:
    """One :class:`BatchVerifier` per home chip, with group placement.

    ``chips`` requests the pool width; it clamps to the number of
    visible jax devices (``requested_chips`` keeps the ask).  With one
    chip the pool never touches jax at construction and owns exactly one
    unpinned engine — the degenerate path this CPU container runs.

    ``stripe_threshold`` (default: the engines' ``max_batch``) sets the
    explicit-batch size above which ``verify_*_many`` routes through the
    mesh-striped engine instead of the home chip; ``None``/a 1-chip pool
    disables striping.  All remaining keyword arguments construct each
    per-chip :class:`BatchVerifier` identically.
    """

    def __init__(
        self,
        chips: int = 1,
        *,
        devices: Optional[list] = None,
        stripe_threshold: Optional[int] = None,
        **engine_kwargs,
    ):
        if chips < 1:
            raise ValueError(f"chips must be >= 1, got {chips}")
        if "mesh" in engine_kwargs or "device" in engine_kwargs:
            raise ValueError(
                "the pool owns device/mesh placement; pass chips=/devices="
            )
        self.requested_chips = int(chips)
        if chips > 1 and devices is None:
            import jax

            devices = list(jax.devices())
        if devices is not None and chips > len(devices):
            # Honest degeneracy (the CPU container): fewer devices than
            # asked → a narrower pool, never an oversubscribed one.
            chips = max(len(devices), 1)
        self.chips = int(chips)
        self._devices = list(devices[:chips]) if devices is not None else None
        self._engine_kwargs = dict(engine_kwargs)
        if chips == 1:
            engines = [BatchVerifier(**engine_kwargs)]
        else:
            engines = [
                BatchVerifier(device=self._devices[c], **engine_kwargs)
                for c in range(chips)
            ]
        self._engines: Tuple[BatchVerifier, ...] = tuple(engines)
        # Striped engine: mesh over the pool's chips for oversized
        # explicit batches.  Only built for a real multi-chip pool (a
        # 1-device mesh degenerates inside BatchVerifier anyway).
        self._striped: Optional[BatchVerifier] = None
        self.stripe_threshold: Optional[int] = None
        if self.chips > 1:
            from . import mesh as mesh_mod

            self._striped = BatchVerifier(
                mesh=mesh_mod.make_mesh(self._devices), **engine_kwargs
            )
            self.stripe_threshold = (
                int(stripe_threshold)
                if stripe_threshold is not None
                else int(self._engines[0].max_batch)
            )
        # group -> home chip; facade cache; per-group in-flight counters.
        # All loop-confined (see module docstring).
        self._placement: Dict[int, int] = {}
        self._facades: Dict[int, _GroupEngine] = {}
        self._inflight: Dict[int, int] = {}
        # Rolling per-chip utilization windows (chip_utilization):
        # DeviceLedger baselines captured at the previous call.
        self._util_ledgers: Optional[list] = None
        # Ceilings re-applied to every rolling window (set_ceiling).
        self._ceilings: Dict[str, Tuple[float, str]] = {}

    # -- placement -----------------------------------------------------------

    @property
    def engines(self) -> Tuple[BatchVerifier, ...]:
        return self._engines

    @property
    def striped_engine(self) -> Optional[BatchVerifier]:
        return self._striped

    def home_chip(self, group: int) -> int:
        """The group's home chip, assigning static round-robin
        (``group % chips``) on first touch.  Every group maps to exactly
        one chip — the placement invariant tests pin."""
        chip = self._placement.get(group)
        if chip is None:
            chip = group % self.chips
            self._placement[group] = chip
        return chip

    def engine_for(self, group: int) -> _GroupEngine:
        """The group's engine facade (cached — one identity per group)."""
        fac = self._facades.get(group)
        if fac is None:
            self.home_chip(group)  # place eagerly
            fac = _GroupEngine(self, group)
            self._facades[group] = fac
        return fac

    def placement(self) -> Dict[int, int]:
        return dict(self._placement)

    def groups_on(self, chip: int) -> List[int]:
        return sorted(g for g, c in self._placement.items() if c == chip)

    def group_inflight(self, group: int) -> int:
        return self._inflight.get(group, 0)

    def _route_many(self, group: int, n_items: int) -> BatchVerifier:
        if (
            self._striped is not None
            and self.stripe_threshold is not None
            and n_items > self.stripe_threshold
        ):
            return self._striped
        return self._engines[self.home_chip(group)]

    def rebalance(
        self,
        scores: Optional[List[float]] = None,
        min_gap: float = 0.25,
    ) -> Dict[int, Tuple[int, int]]:
        """Migrate groups off the hottest chip when the per-chip
        ``busy × fill`` scores diverge.

        ``scores[c]`` is chip ``c``'s load score (higher = busier) — the
        PR-9 ledger product; defaults to :meth:`chip_scores`.  When the
        hottest chip exceeds the coolest by more than ``min_gap``
        (absolute score gap), ONE group homed on the hottest chip moves
        to the coolest.  A group with in-flight dispatches is never
        migrated: its outstanding futures resolve on the engine whose
        memo/staging own them, so migration under load would split a
        group's verification state across chips mid-await.  Returns
        ``{group: (old_chip, new_chip)}`` (empty when balanced).
        """
        if self.chips < 2:
            return {}
        if scores is None:
            scores = self.chip_scores()
        if len(scores) != self.chips:
            raise ValueError(
                f"{len(scores)} scores for a {self.chips}-chip pool"
            )
        hot = max(range(self.chips), key=lambda c: scores[c])
        cool = min(range(self.chips), key=lambda c: scores[c])
        if hot == cool or scores[hot] - scores[cool] <= min_gap:
            return {}
        movable = [
            g for g in self.groups_on(hot) if self._inflight.get(g, 0) == 0
        ]
        if not movable:
            return {}
        # Deterministic choice: the highest-numbered idle group moves
        # (later groups are the round-robin overflow that made the chip
        # hot in the first place).
        g = movable[-1]
        self._placement[g] = cool
        return {g: (hot, cool)}

    # -- utilization (the busy × fill feed) ----------------------------------

    def set_ceiling(self, queue: str, lanes_per_sec: float, source: str) -> None:
        """Calibrated per-chip full-batch lane rate for ``queue`` with
        provenance, applied to every rolling utilization window (and
        re-applied after each window reset)."""
        if lanes_per_sec <= 0:
            raise ValueError("ceiling must be positive")
        self._ceilings[queue] = (float(lanes_per_sec), source)

    def _fresh_ledgers(self, now=None) -> list:
        from ..obs.ledger import DeviceLedger

        leds = [DeviceLedger(e, now=now) for e in self._engines]
        for led in leds:
            for q, (rate, source) in self._ceilings.items():
                led.set_ceiling(q, rate, source)
        return leds

    def chip_utilization(self, now=None) -> List[dict]:
        """Per-chip rows over the window since the previous call: busy
        fraction, fill efficiency (lane-weighted across that chip's
        active queues; 1.0 under a self ceiling), the ``busy × fill``
        placement score, current total queue depth, and the groups homed
        there.  The first call establishes baselines and reads all-idle
        rows — by design (there was no window yet)."""
        prev = self._util_ledgers
        self._util_ledgers = self._fresh_ledgers(now=now)
        rows: List[dict] = []
        for c, eng in enumerate(self._engines):
            busy = 0.0
            fill = 1.0
            if prev is not None:
                wins = prev[c].snapshot(now=now)
                if wins:
                    wall = max(w.wall_s for w in wins.values())
                    busy = min(
                        sum(w.busy_s for w in wins.values()) / max(wall, 1e-9),
                        1.0,
                    )
                    lanes = sum(w.dispatched_lanes for w in wins.values())
                    if lanes > 0:
                        fill = sum(
                            prev[c].decompose(w).fill_efficiency
                            * w.dispatched_lanes
                            for w in wins.values()
                        ) / lanes
            depth = sum(eng.queue_depths().values()) + sum(
                eng.sign_queue_depths().values()
            )
            rows.append(
                {
                    "chip": c,
                    "device": (
                        str(self._devices[c])
                        if self._devices is not None
                        else "default"
                    ),
                    "busy": round(busy, 4),
                    "fill": round(fill, 4),
                    "score": round(busy * fill, 4),
                    "depth": depth,
                    "groups": self.groups_on(c),
                }
            )
        return rows

    def chip_up(self, chip: int) -> bool:
        """False when EVERY instantiated queue on the chip's engine has
        written its device off (the hung-dispatch liveness net demoted
        them all to host fallback) — the ``peer top`` DOWN row.  A chip
        with no queues yet is up (nothing has disproved it)."""
        eng = self._engines[chip]
        qs = list(dict(eng._queues).values()) + list(
            dict(eng._sign_queues).values()
        )
        if not qs:
            return True
        return any(not q._device_written_off for q in qs)

    def chip_scores(self, now=None) -> List[float]:
        """The per-chip ``busy × fill`` placement scores (PR-9 product)
        over the window since the last :meth:`chip_utilization` call."""
        return [row["score"] for row in self.chip_utilization(now=now)]

    # -- merged read-side surfaces (prom / timeseries compatibility) ---------
    #
    # Shaped exactly like one BatchVerifier's maps so existing consumers
    # (register_engine_series, _collect_engine) take a pool unchanged.
    # A 1-chip pool uses the bare queue names (indistinguishable from
    # the single engine); a multi-chip pool prefixes "c{chip}:" for
    # per-chip attribution, with the striped engine's traffic under
    # "stripe:".

    def _merged(self, getter) -> Dict[str, object]:
        if self.chips == 1 and self._striped is None:
            return getter(self._engines[0])
        out: Dict[str, object] = {}
        for c, eng in enumerate(self._engines):
            for name, v in getter(eng).items():
                out[f"c{c}:{name}"] = v
        if self._striped is not None:
            for name, v in getter(self._striped).items():
                out[f"stripe:{name}"] = v
        return out

    @property
    def stats(self) -> Dict[str, object]:
        return self._merged(lambda e: e.stats)

    @property
    def sign_stats(self) -> Dict[str, object]:
        return self._merged(lambda e: e.sign_stats)

    def queue_depths(self) -> Dict[str, int]:
        return self._merged(lambda e: e.queue_depths())

    def sign_queue_depths(self) -> Dict[str, int]:
        return self._merged(lambda e: e.sign_queue_depths())

    def queue_depth_peaks(self, reset: bool = True) -> Dict[str, int]:
        return self._merged(lambda e: e.queue_depth_peaks(reset=reset))

    def sign_queue_depth_peaks(self, reset: bool = True) -> Dict[str, int]:
        return self._merged(lambda e: e.sign_queue_depth_peaks(reset=reset))
