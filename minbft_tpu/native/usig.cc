/* Native USIG implementation.  See usig.h for the contract and the
 * reference-parity notes (reference usig/sgx/enclave/usig.c semantics:
 * sign {digest, epoch, counter}, increment-after-sign, counters from 1,
 * seal/unseal round-trip).
 */

#include "usig.h"

#include <cstring>
#include <mutex>
#include <new>
#include <vector>

#include "ossl.h"

namespace {

/* Seal layout v2: magic(4) || der-private-key.  The epoch is NOT sealed:
 * every init draws a fresh random epoch (reference usig.c:168-186 draws
 * sgx_read_rand before unsealing), so a restored instance whose counter
 * restarts at 1 can never re-certify (epoch, cv) pairs already issued by
 * a previous instance of the same key. */
constexpr unsigned char kSealMagic[4] = {'U', 'S', 'G', '2'};
/* v1 blobs carried a sealed epoch (magic || epoch_be8 || key); accepted
 * for key recovery, with the stored epoch ignored. */
constexpr unsigned char kSealMagicV1[4] = {'U', 'S', 'G', '1'};
/* v3: encrypted-at-rest (the sgx_seal_data confidentiality analogue,
 * reference usig.c:107-116).  Layout:
 *   magic(4) || salt(16) || iters_be4 || nonce(12) || ct || tag(16)
 * with key = PBKDF2-HMAC-SHA256(secret, salt, iters, 32) and
 * AES-256-GCM over the DER private key. */
constexpr unsigned char kSealMagicV3[4] = {'U', 'S', 'G', '3'};
constexpr size_t kSaltLen = 16;
constexpr size_t kNonceLen = 12;
constexpr size_t kTagLen = 16;
constexpr uint32_t kKdfIters = 60000;
constexpr size_t kV3Overhead = 4 + kSaltLen + 4 + kNonceLen + kTagLen;

bool kdf_key(const uint8_t *secret, size_t secret_len,
             const unsigned char *salt, uint32_t iters,
             unsigned char out[32]) {
  return PKCS5_PBKDF2_HMAC(reinterpret_cast<const char *>(secret),
                           static_cast<int>(secret_len), salt,
                           static_cast<int>(kSaltLen),
                           static_cast<int>(iters), EVP_sha256(), 32,
                           out) == 1;
}

/* AES-256-GCM one-shot encrypt: ct || tag appended at out. */
bool gcm_encrypt(const unsigned char key[32], const unsigned char *nonce,
                 const unsigned char *plain, int plain_len,
                 unsigned char *ct_out, unsigned char *tag_out) {
  EVP_CIPHER_CTX *ctx = EVP_CIPHER_CTX_new();
  if (ctx == nullptr) return false;
  int len = 0, ok = 0;
  ok = EVP_EncryptInit_ex(ctx, EVP_aes_256_gcm(), nullptr, nullptr, nullptr) == 1 &&
       EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_GCM_SET_IVLEN,
                           static_cast<int>(kNonceLen), nullptr) == 1 &&
       EVP_EncryptInit_ex(ctx, nullptr, nullptr, key, nonce) == 1 &&
       EVP_EncryptUpdate(ctx, ct_out, &len, plain, plain_len) == 1 &&
       len == plain_len &&
       EVP_EncryptFinal_ex(ctx, ct_out + len, &len) == 1 &&
       EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_GCM_GET_TAG,
                           static_cast<int>(kTagLen), tag_out) == 1;
  EVP_CIPHER_CTX_free(ctx);
  return ok;
}

bool gcm_decrypt(const unsigned char key[32], const unsigned char *nonce,
                 const unsigned char *ct, int ct_len,
                 const unsigned char *tag, unsigned char *plain_out) {
  EVP_CIPHER_CTX *ctx = EVP_CIPHER_CTX_new();
  if (ctx == nullptr) return false;
  int len = 0, ok = 0;
  unsigned char tagbuf[kTagLen];
  std::memcpy(tagbuf, tag, kTagLen);
  ok = EVP_DecryptInit_ex(ctx, EVP_aes_256_gcm(), nullptr, nullptr, nullptr) == 1 &&
       EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_GCM_SET_IVLEN,
                           static_cast<int>(kNonceLen), nullptr) == 1 &&
       EVP_DecryptInit_ex(ctx, nullptr, nullptr, key, nonce) == 1 &&
       EVP_DecryptUpdate(ctx, plain_out, &len, ct, ct_len) == 1 &&
       len == ct_len &&
       EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_GCM_SET_TAG,
                           static_cast<int>(kTagLen), tagbuf) == 1 &&
       EVP_DecryptFinal_ex(ctx, plain_out + len, &len) == 1;
  EVP_CIPHER_CTX_free(ctx);
  return ok;
}

/* DER ECDSA-Sig-Value -> raw r||s (32+32 big-endian).  The encoding is
 * SEQUENCE { INTEGER r, INTEGER s } with minimal-length integers. */
bool der_to_raw64(const unsigned char *der, size_t len, unsigned char out[64]) {
  size_t off = 0;
  auto read_hdr = [&](unsigned char want_tag, size_t *out_len) -> bool {
    if (off + 2 > len || der[off] != want_tag) return false;
    ++off;
    size_t l = der[off++];
    if (l & 0x80) {
      size_t nbytes = l & 0x7f;
      if (nbytes == 0 || nbytes > 2 || off + nbytes > len) return false;
      l = 0;
      for (size_t i = 0; i < nbytes; ++i) l = (l << 8) | der[off++];
    }
    if (off + l > len) return false;
    *out_len = l;
    return true;
  };
  size_t seq_len;
  if (!read_hdr(0x30, &seq_len)) return false;
  std::memset(out, 0, 64);
  for (int part = 0; part < 2; ++part) {
    size_t int_len;
    if (!read_hdr(0x02, &int_len)) return false;
    const unsigned char *p = der + off;
    off += int_len;
    /* strip leading zero pad */
    while (int_len > 0 && p[0] == 0x00) {
      ++p;
      --int_len;
    }
    if (int_len > 32) return false;
    std::memcpy(out + part * 32 + (32 - int_len), p, int_len);
  }
  return off == len;
}

/* raw r||s -> DER (for verification through OpenSSL). */
std::vector<unsigned char> raw64_to_der(const unsigned char sig[64]) {
  auto encode_int = [](const unsigned char *p) {
    std::vector<unsigned char> v;
    size_t n = 32;
    while (n > 1 && p[32 - n] == 0x00) --n;
    const unsigned char *q = p + (32 - n);
    v.push_back(0x02);
    if (q[0] & 0x80) {
      v.push_back(static_cast<unsigned char>(n + 1));
      v.push_back(0x00);
    } else {
      v.push_back(static_cast<unsigned char>(n));
    }
    v.insert(v.end(), q, q + n);
    return v;
  };
  std::vector<unsigned char> r = encode_int(sig);
  std::vector<unsigned char> s = encode_int(sig + 32);
  std::vector<unsigned char> der;
  der.push_back(0x30);
  der.push_back(static_cast<unsigned char>(r.size() + s.size()));
  der.insert(der.end(), r.begin(), r.end());
  der.insert(der.end(), s.begin(), s.end());
  return der;
}

bool sha256(const void *data, size_t len, unsigned char out[32]) {
  unsigned int sz = 0;
  return EVP_Digest(data, len, out, &sz, EVP_sha256(), nullptr) == 1 &&
         sz == 32;
}

/* SHA256(digest32 || epoch_be8 || counter_be8) — must match
 * minbft_tpu/usig/software.py _signed_payload. */
bool signed_payload(const unsigned char digest[32], uint64_t epoch,
                    uint64_t counter, unsigned char out[32]) {
  unsigned char buf[48];
  std::memcpy(buf, digest, 32);
  for (int i = 0; i < 8; ++i)
    buf[32 + i] = static_cast<unsigned char>(epoch >> (56 - 8 * i));
  for (int i = 0; i < 8; ++i)
    buf[40 + i] = static_cast<unsigned char>(counter >> (56 - 8 * i));
  return sha256(buf, sizeof buf, out);
}

}  // namespace

struct usig {
  EVP_PKEY *key = nullptr;
  uint64_t epoch = 0;    /* random per instance (usig.c:181) */
  uint64_t counter = 1;  /* counters start at 1 */
  std::mutex mu;         /* reference ecallLock analogue */
};

extern "C" {

const char *usig_native_version(void) { return "minbft-tpu-usig/1 openssl3"; }

int usig_init(usig_t **out, const uint8_t *sealed, size_t sealed_len) {
  return usig_init2(out, sealed, sealed_len, nullptr, 0);
}

int usig_init2(usig_t **out, const uint8_t *sealed, size_t sealed_len,
               const uint8_t *secret, size_t secret_len) {
  if (out == nullptr) return USIG_ERR_ARG;
  usig_t *u = new (std::nothrow) usig_t;
  if (u == nullptr) return USIG_ERR_ALLOC;
  /* Fresh random epoch on EVERY init — including restores.  The counter
   * restarts at 1, so reusing an old epoch would let a restarted instance
   * certify different messages under already-issued (epoch, cv) values:
   * exactly the equivocation USIG exists to prevent (reference
   * usig.c:177-186).  Verifiers learn the new epoch trust-on-first-use
   * (reference crypto.go:204-218; SampleAuthenticator epoch capture). */
  unsigned char eb[8];
  if (RAND_bytes(eb, 8) != 1) {
    delete u;
    return USIG_ERR_CRYPTO;
  }
  u->epoch = 0;
  for (int i = 0; i < 8; ++i) u->epoch = (u->epoch << 8) | eb[i];
  if (sealed == nullptr) {
    u->key = EVP_PKEY_Q_keygen(nullptr, nullptr, "EC", "P-256");
    if (u->key == nullptr) {
      delete u;
      return USIG_ERR_CRYPTO;
    }
  } else if (sealed_len > kV3Overhead &&
             std::memcmp(sealed, kSealMagicV3, 4) == 0) {
    /* v3: AES-256-GCM under the operator secret. */
    if (secret == nullptr || secret_len == 0) {
      delete u;
      return USIG_ERR_SECRET;
    }
    const unsigned char *salt = sealed + 4;
    uint32_t iters = 0;
    for (int i = 0; i < 4; ++i)
      iters = (iters << 8) | sealed[4 + kSaltLen + i];
    if (iters == 0 || iters > 10u * 1000u * 1000u) {
      delete u;
      return USIG_ERR_SEALED;
    }
    const unsigned char *nonce = sealed + 4 + kSaltLen + 4;
    const unsigned char *ct = nonce + kNonceLen;
    size_t ct_len = sealed_len - kV3Overhead;
    const unsigned char *tag = ct + ct_len;
    unsigned char key[32];
    std::vector<unsigned char> plain(ct_len);
    if (!kdf_key(secret, secret_len, salt, iters, key)) {
      delete u;
      return USIG_ERR_CRYPTO;
    }
    if (!gcm_decrypt(key, nonce, ct, static_cast<int>(ct_len), tag,
                     plain.data())) {
      /* GCM wrote (garbage or partially correct) plaintext before the
       * tag check failed — scrub it like the success path does. */
      std::memset(plain.data(), 0, plain.size());
      std::memset(key, 0, sizeof key);
      delete u;
      return USIG_ERR_SECRET;
    }
    std::memset(key, 0, sizeof key);
    const unsigned char *p = plain.data();
    u->key = d2i_AutoPrivateKey(nullptr, &p, static_cast<long>(ct_len));
    std::memset(plain.data(), 0, plain.size());
    if (u->key == nullptr) {
      delete u;
      return USIG_ERR_SEALED;
    }
  } else {
    size_t key_off;
    if (sealed_len >= 5 && std::memcmp(sealed, kSealMagic, 4) == 0) {
      key_off = 4;
    } else if (sealed_len >= 13 &&
               std::memcmp(sealed, kSealMagicV1, 4) == 0) {
      key_off = 12; /* skip the v1 sealed epoch; it is never reused */
    } else {
      delete u;
      return USIG_ERR_SEALED;
    }
    const unsigned char *p = sealed + key_off;
    u->key = d2i_AutoPrivateKey(nullptr, &p,
                                static_cast<long>(sealed_len - key_off));
    if (u->key == nullptr) {
      delete u;
      return USIG_ERR_SEALED;
    }
  }
  *out = u;
  return USIG_OK;
}

int usig_destroy(usig_t *u) {
  if (u == nullptr) return USIG_ERR_ARG;
  EVP_PKEY_free(u->key);
  delete u;
  return USIG_OK;
}

int usig_get_epoch(usig_t *u, uint64_t *epoch) {
  if (u == nullptr || epoch == nullptr) return USIG_ERR_ARG;
  *epoch = u->epoch;
  return USIG_OK;
}

int usig_get_pubkey(usig_t *u, uint8_t out[64]) {
  if (u == nullptr || out == nullptr) return USIG_ERR_ARG;
  unsigned char pt[65];
  size_t sz = 0;
  if (EVP_PKEY_get_octet_string_param(u->key, "pub", pt, sizeof pt, &sz) != 1 ||
      sz != 65 || pt[0] != 0x04)
    return USIG_ERR_CRYPTO;
  std::memcpy(out, pt + 1, 64);
  return USIG_OK;
}

int usig_create_ui(usig_t *u, const uint8_t digest[32], uint64_t *counter,
                   uint8_t sig_out[64]) {
  if (u == nullptr || digest == nullptr || counter == nullptr ||
      sig_out == nullptr)
    return USIG_ERR_ARG;
  std::lock_guard<std::mutex> lock(u->mu);
  unsigned char payload[32];
  if (!signed_payload(digest, u->epoch, u->counter, payload))
    return USIG_ERR_CRYPTO;
  EVP_PKEY_CTX *ctx = EVP_PKEY_CTX_new(u->key, nullptr);
  if (ctx == nullptr) return USIG_ERR_CRYPTO;
  unsigned char der[80];
  size_t der_len = sizeof der;
  int ok = EVP_PKEY_sign_init(ctx) == 1 &&
           EVP_PKEY_sign(ctx, der, &der_len, payload, 32) == 1;
  EVP_PKEY_CTX_free(ctx);
  if (!ok || !der_to_raw64(der, der_len, sig_out)) return USIG_ERR_CRYPTO;
  *counter = u->counter;
  /* Increment only after the signature exists: this counter value can
   * never be issued again (reference usig.c:66-69). */
  u->counter += 1;
  return USIG_OK;
}

int usig_sealed_size(usig_t *u, size_t *out) {
  if (u == nullptr || out == nullptr) return USIG_ERR_ARG;
  int der_len = i2d_PrivateKey(u->key, nullptr);
  if (der_len <= 0) return USIG_ERR_CRYPTO;
  *out = 4 + static_cast<size_t>(der_len);
  return USIG_OK;
}

int usig_seal(usig_t *u, uint8_t *out, size_t cap, size_t *out_len) {
  if (u == nullptr || out == nullptr || out_len == nullptr)
    return USIG_ERR_ARG;
  size_t need = 0;
  int rc = usig_sealed_size(u, &need);
  if (rc != USIG_OK) return rc;
  if (cap < need) return USIG_ERR_BUFSZ;
  std::memcpy(out, kSealMagic, 4);
  unsigned char *p = out + 4;
  int der_len = i2d_PrivateKey(u->key, &p);
  if (der_len <= 0) return USIG_ERR_CRYPTO;
  *out_len = 4 + static_cast<size_t>(der_len);
  return USIG_OK;
}

int usig_sealed_size2(usig_t *u, size_t secret_len, size_t *out) {
  if (u == nullptr || out == nullptr) return USIG_ERR_ARG;
  int der_len = i2d_PrivateKey(u->key, nullptr);
  if (der_len <= 0) return USIG_ERR_CRYPTO;
  *out = (secret_len == 0 ? 4 : kV3Overhead) + static_cast<size_t>(der_len);
  return USIG_OK;
}

int usig_seal2(usig_t *u, const uint8_t *secret, size_t secret_len,
               uint8_t *out, size_t cap, size_t *out_len) {
  if (u == nullptr || out == nullptr || out_len == nullptr)
    return USIG_ERR_ARG;
  if (secret == nullptr || secret_len == 0)
    return usig_seal(u, out, cap, out_len);
  size_t need = 0;
  int rc = usig_sealed_size2(u, secret_len, &need);
  if (rc != USIG_OK) return rc;
  if (cap < need) return USIG_ERR_BUFSZ;
  int der_len = i2d_PrivateKey(u->key, nullptr);
  if (der_len <= 0) return USIG_ERR_CRYPTO;
  std::vector<unsigned char> der(static_cast<size_t>(der_len));
  unsigned char *dp = der.data();
  if (i2d_PrivateKey(u->key, &dp) != der_len) return USIG_ERR_CRYPTO;

  std::memcpy(out, kSealMagicV3, 4);
  unsigned char *salt = out + 4;
  unsigned char *itp = out + 4 + kSaltLen;
  unsigned char *nonce = itp + 4;
  unsigned char *ct = nonce + kNonceLen;
  unsigned char *tag = ct + der_len;
  if (RAND_bytes(salt, static_cast<int>(kSaltLen)) != 1 ||
      RAND_bytes(nonce, static_cast<int>(kNonceLen)) != 1) {
    std::memset(der.data(), 0, der.size());
    return USIG_ERR_CRYPTO;
  }
  for (int i = 0; i < 4; ++i)
    itp[i] = static_cast<unsigned char>(kKdfIters >> (24 - 8 * i));
  unsigned char key[32];
  int ok = kdf_key(secret, secret_len, salt, kKdfIters, key) &&
           gcm_encrypt(key, nonce, der.data(), der_len, ct, tag);
  std::memset(key, 0, sizeof key);
  std::memset(der.data(), 0, der.size());
  if (!ok) return USIG_ERR_CRYPTO;
  *out_len = kV3Overhead + static_cast<size_t>(der_len);
  return USIG_OK;
}

int usig_verify_ui(const uint8_t pub[64], uint64_t epoch_be,
                   const uint8_t digest[32], uint64_t counter,
                   const uint8_t sig[64]) {
  if (pub == nullptr || digest == nullptr || sig == nullptr)
    return USIG_ERR_ARG;
  unsigned char payload[32];
  if (!signed_payload(digest, epoch_be, counter, payload))
    return USIG_ERR_CRYPTO;

  unsigned char pt[65];
  pt[0] = 0x04;
  std::memcpy(pt + 1, pub, 64);
  char group[8] = "P-256";
  OSSL_PARAM params[3];
  params[0].key = "group";
  params[0].data_type = OSSL_PARAM_UTF8_STRING;
  params[0].data = group;
  params[0].data_size = 5;
  params[0].return_size = static_cast<size_t>(-1);
  params[1].key = "pub";
  params[1].data_type = OSSL_PARAM_OCTET_STRING;
  params[1].data = pt;
  params[1].data_size = sizeof pt;
  params[1].return_size = static_cast<size_t>(-1);
  params[2].key = nullptr;
  params[2].data_type = 0;
  params[2].data = nullptr;
  params[2].data_size = 0;
  params[2].return_size = 0;

  EVP_PKEY_CTX *fctx = EVP_PKEY_CTX_new_from_name(nullptr, "EC", nullptr);
  if (fctx == nullptr) return USIG_ERR_CRYPTO;
  EVP_PKEY *pkey = nullptr;
  int ok = EVP_PKEY_fromdata_init(fctx) == 1 &&
           EVP_PKEY_fromdata(fctx, &pkey, EVP_PKEY_PUBLIC_KEY, params) == 1;
  EVP_PKEY_CTX_free(fctx);
  if (!ok || pkey == nullptr) return USIG_ERR_CRYPTO;

  std::vector<unsigned char> der = raw64_to_der(sig);
  EVP_PKEY_CTX *vctx = EVP_PKEY_CTX_new(pkey, nullptr);
  int valid = 0;
  if (vctx != nullptr) {
    valid = EVP_PKEY_verify_init(vctx) == 1 &&
            EVP_PKEY_verify(vctx, der.data(), der.size(), payload, 32) == 1;
    EVP_PKEY_CTX_free(vctx);
  }
  EVP_PKEY_free(pkey);
  return valid ? USIG_OK : USIG_ERR_CRYPTO;
}

}  /* extern "C" */
