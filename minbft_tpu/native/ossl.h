/* Hand-declared prototypes for the subset of OpenSSL 3 (libcrypto.so.3)
 * this module uses.  The image ships the shared library but not the
 * development headers, so the needed functions are declared here verbatim
 * from the stable public API (all exported, none deprecated-removed).
 * The Makefile links against the versioned .so directly.
 */

#ifndef MINBFT_TPU_NATIVE_OSSL_H
#define MINBFT_TPU_NATIVE_OSSL_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_pkey_ctx_st EVP_PKEY_CTX;
typedef struct ossl_lib_ctx_st OSSL_LIB_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;

/* Key generation (OpenSSL 3 one-shot helper). */
EVP_PKEY *EVP_PKEY_Q_keygen(OSSL_LIB_CTX *libctx, const char *propq,
                            const char *type, ...);

/* Sign / verify a precomputed digest (DER-encoded ECDSA signature). */
EVP_PKEY_CTX *EVP_PKEY_CTX_new(EVP_PKEY *pkey, ENGINE *e);
void EVP_PKEY_CTX_free(EVP_PKEY_CTX *ctx);
int EVP_PKEY_sign_init(EVP_PKEY_CTX *ctx);
int EVP_PKEY_sign(EVP_PKEY_CTX *ctx, unsigned char *sig, size_t *siglen,
                  const unsigned char *tbs, size_t tbslen);
int EVP_PKEY_verify_init(EVP_PKEY_CTX *ctx);
int EVP_PKEY_verify(EVP_PKEY_CTX *ctx, const unsigned char *sig,
                    size_t siglen, const unsigned char *tbs, size_t tbslen);

/* Raw public-key bytes (uncompressed SEC1 point). */
int EVP_PKEY_get_octet_string_param(const EVP_PKEY *pkey,
                                    const char *key_name, unsigned char *buf,
                                    size_t max_buf_sz, size_t *out_sz);

/* Build a key from encoded parts (used for unsealing / verification). */
EVP_PKEY *EVP_PKEY_new_raw_public_key_ex(OSSL_LIB_CTX *libctx,
                                         const char *keytype,
                                         const char *propq,
                                         const unsigned char *key,
                                         size_t keylen);

/* Classic DER (de)serialization — still exported in OpenSSL 3. */
int i2d_PrivateKey(const EVP_PKEY *a, unsigned char **pp);
EVP_PKEY *d2i_AutoPrivateKey(EVP_PKEY **a, const unsigned char **pp,
                             long length);

void EVP_PKEY_free(EVP_PKEY *pkey);

/* SHA-256 one-shot. */
int EVP_Digest(const void *data, size_t count, unsigned char *md,
               unsigned int *size, const EVP_MD *type, ENGINE *impl);
const EVP_MD *EVP_sha256(void);

/* CSPRNG. */
int RAND_bytes(unsigned char *buf, int num);

/* AES-256-GCM + PBKDF2 (encrypted sealing, v3 blobs).  Ctrl constants
 * are the stable AEAD values from <openssl/evp.h>. */
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
const EVP_CIPHER *EVP_aes_256_gcm(void);
EVP_CIPHER_CTX *EVP_CIPHER_CTX_new(void);
void EVP_CIPHER_CTX_free(EVP_CIPHER_CTX *ctx);
int EVP_CIPHER_CTX_ctrl(EVP_CIPHER_CTX *ctx, int type, int arg, void *ptr);
int EVP_EncryptInit_ex(EVP_CIPHER_CTX *ctx, const EVP_CIPHER *cipher,
                       ENGINE *impl, const unsigned char *key,
                       const unsigned char *iv);
int EVP_EncryptUpdate(EVP_CIPHER_CTX *ctx, unsigned char *out, int *outl,
                      const unsigned char *in, int inl);
int EVP_EncryptFinal_ex(EVP_CIPHER_CTX *ctx, unsigned char *out, int *outl);
int EVP_DecryptInit_ex(EVP_CIPHER_CTX *ctx, const EVP_CIPHER *cipher,
                       ENGINE *impl, const unsigned char *key,
                       const unsigned char *iv);
int EVP_DecryptUpdate(EVP_CIPHER_CTX *ctx, unsigned char *out, int *outl,
                      const unsigned char *in, int inl);
int EVP_DecryptFinal_ex(EVP_CIPHER_CTX *ctx, unsigned char *out, int *outl);
#define EVP_CTRL_GCM_SET_IVLEN 0x9
#define EVP_CTRL_GCM_GET_TAG 0x10
#define EVP_CTRL_GCM_SET_TAG 0x11
int PKCS5_PBKDF2_HMAC(const char *pass, int passlen,
                      const unsigned char *salt, int saltlen, int iter,
                      const EVP_MD *digest, int keylen, unsigned char *out);

/* EC pubkey-from-point (verification path): build via OSSL_PARAM is
 * heavyweight without headers; instead use EVP_PKEY_fromdata with an
 * OSSL_PARAM array we lay out manually. */
typedef struct ossl_param_st {
  const char *key;
  unsigned int data_type;
  void *data;
  size_t data_size;
  size_t return_size;
} OSSL_PARAM;

#define OSSL_PARAM_UTF8_STRING 4
#define OSSL_PARAM_OCTET_STRING 5

EVP_PKEY_CTX *EVP_PKEY_CTX_new_from_name(OSSL_LIB_CTX *libctx,
                                         const char *name,
                                         const char *propquery);
int EVP_PKEY_fromdata_init(EVP_PKEY_CTX *ctx);
int EVP_PKEY_fromdata(EVP_PKEY_CTX *ctx, EVP_PKEY **ppkey, int selection,
                      OSSL_PARAM params[]);

/* selection constant: public key portions */
#define EVP_PKEY_PUBLIC_KEY 0x86

#ifdef __cplusplus
}
#endif

#endif /* MINBFT_TPU_NATIVE_OSSL_H */
