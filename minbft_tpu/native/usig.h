/* Native USIG module — public C surface.
 *
 * Mirrors the reference's untrusted shim API (reference
 * usig/sgx/shim/usig.h, shim.c:25-117) over a software trusted component
 * with the exact enclave semantics of reference usig/sgx/enclave/usig.c:
 *
 *  - per-instance ECDSA-P256 keypair + random 64-bit epoch (usig.c:25-27,
 *    181);
 *  - usig_create_ui signs SHA256(digest || epoch_be8 || counter_be8) and
 *    increments the counter only AFTER signing, so a counter value can
 *    never be issued twice (usig.c:36-76, comment at 66-69);
 *  - counters start at 1 (usig.c:181, test usig_test.c:34-60);
 *  - key seal/unseal round-trip (usig.c:107-166), with a FRESH random
 *    epoch drawn on every init — including restores (usig.c:168-186) — so
 *    a restarted instance whose counter restarts at 1 can never
 *    re-certify already-issued (epoch, cv) values.  Without SGX there is
 *    no hardware sealing root; the v3 sealed format instead encrypts the
 *    key with AES-256-GCM under an operator-supplied secret
 *    (PBKDF2-HMAC-SHA256 KDF) so a stolen blob discloses nothing —
 *    the confidentiality property of sgx_seal_data (usig.c:107-116)
 *    under a software root of trust.  Sealing without a secret keeps
 *    the v2 plaintext layout for compatibility.
 *
 * The byte formats match minbft_tpu/usig/software.py EcdsaUSIG exactly
 * (cert payload, epoch || x || y identity), so UIs created natively verify
 * on the TPU batch path unchanged.
 */

#ifndef MINBFT_TPU_NATIVE_USIG_H
#define MINBFT_TPU_NATIVE_USIG_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct usig usig_t;

enum {
  USIG_OK = 0,
  USIG_ERR_ALLOC = 1,
  USIG_ERR_CRYPTO = 2,
  USIG_ERR_SEALED = 3, /* malformed sealed blob */
  USIG_ERR_ARG = 4,
  USIG_ERR_BUFSZ = 5,
  USIG_ERR_SECRET = 6, /* encrypted blob: secret missing or wrong */
};

/* Create an instance.  sealed==NULL generates a fresh keypair; otherwise
 * the keypair is restored from a previously sealed blob (reference
 * shim.c:35-57 usig_init with/without sealed data).  Either way the
 * epoch is freshly random (usig.c:177-186). */
int usig_init(usig_t **out, const uint8_t *sealed, size_t sealed_len);
int usig_destroy(usig_t *u);

/* Certify a 32-byte message digest: writes the counter value used and the
 * raw 64-byte (r||s big-endian) ECDSA-P256 signature over
 * SHA256(digest || epoch_be8 || counter_be8).  Thread-safe (internal
 * mutex — the reference serializes enclave calls with ecallLock,
 * usig-enclave.go:105-114). */
int usig_create_ui(usig_t *u, const uint8_t digest[32], uint64_t *counter,
                   uint8_t sig_out[64]);

/* Current epoch (big-endian bytes are the caller's concern). */
int usig_get_epoch(usig_t *u, uint64_t *epoch);

/* Uncompressed public key: 64 bytes x||y big-endian. */
int usig_get_pubkey(usig_t *u, uint8_t out[64]);

/* Two-call seal dance (reference shim.c:84-117): query the size, then
 * seal into a caller buffer. */
int usig_sealed_size(usig_t *u, size_t *out);
int usig_seal(usig_t *u, uint8_t *out, size_t cap, size_t *out_len);

/* Encrypted sealing (v3, sgx_seal_data confidentiality analogue):
 * secret==NULL/len==0 degrades to the plaintext v2 paths above.
 * usig_init2 accepts v3 (requires the right secret), v2 and v1 blobs. */
int usig_init2(usig_t **out, const uint8_t *sealed, size_t sealed_len,
               const uint8_t *secret, size_t secret_len);
int usig_sealed_size2(usig_t *u, size_t secret_len, size_t *out);
int usig_seal2(usig_t *u, const uint8_t *secret, size_t secret_len,
               uint8_t *out, size_t cap, size_t *out_len);

/* Host-side UI verification (used by the C++ test and as a fast serial
 * fallback): pub is x||y (64B), sig is r||s (64B). Returns USIG_OK when
 * valid, USIG_ERR_CRYPTO when not. */
int usig_verify_ui(const uint8_t pub[64], uint64_t epoch_be,
                   const uint8_t digest[32], uint64_t counter,
                   const uint8_t sig[64]);

/* Library build id, for the capability probe. */
const char *usig_native_version(void);

#ifdef __cplusplus
}
#endif

#endif /* MINBFT_TPU_NATIVE_USIG_H */
