/* Native USIG test — ports the reference enclave test
 * (reference usig/sgx/test/usig_test.c:34-60): init/destroy, counter
 * monotonicity from 1, seal/unseal round-trip, plus signature validity and
 * forgery rejection.  Run by `make check`.
 */

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "usig.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                         \
    }                                                                   \
  } while (0)

int main() {
  usig_t *u = nullptr;
  CHECK(usig_init(&u, nullptr, 0) == USIG_OK);

  uint64_t epoch = 0;
  CHECK(usig_get_epoch(u, &epoch) == USIG_OK);

  uint8_t pub[64];
  CHECK(usig_get_pubkey(u, pub) == USIG_OK);

  /* counters start at 1 and increase by exactly 1 per certificate
   * (reference usig_test.c:34-60). */
  uint8_t digest[32];
  std::memset(digest, 0xAB, sizeof digest);
  uint8_t sig[64];
  for (uint64_t expect = 1; expect <= 5; ++expect) {
    uint64_t counter = 0;
    CHECK(usig_create_ui(u, digest, &counter, sig) == USIG_OK);
    CHECK(counter == expect);
    CHECK(usig_verify_ui(pub, epoch, digest, counter, sig) == USIG_OK);
    /* wrong counter / digest / epoch must not verify */
    CHECK(usig_verify_ui(pub, epoch, digest, counter + 1, sig) != USIG_OK);
    uint8_t bad[32];
    std::memcpy(bad, digest, 32);
    bad[0] ^= 1;
    CHECK(usig_verify_ui(pub, epoch, bad, counter, sig) != USIG_OK);
    CHECK(usig_verify_ui(pub, epoch ^ 1, digest, counter, sig) != USIG_OK);
    /* corrupted signature */
    sig[10] ^= 0x40;
    CHECK(usig_verify_ui(pub, epoch, digest, counter, sig) != USIG_OK);
    sig[10] ^= 0x40;
  }

  /* seal -> unseal: same key (same pubkey, valid sigs) but a FRESH epoch
   * (reference usig.c:168-186 draws a new random epoch on every init);
   * counter restarts at 1 (volatile state, reference usig.c:140-166). */
  size_t need = 0;
  CHECK(usig_sealed_size(u, &need) == USIG_OK && need > 4);
  std::vector<uint8_t> blob(need);
  size_t sealed_len = 0;
  CHECK(usig_seal(u, blob.data(), blob.size(), &sealed_len) == USIG_OK);
  CHECK(sealed_len == need);

  usig_t *u2 = nullptr;
  CHECK(usig_init(&u2, blob.data(), sealed_len) == USIG_OK);
  uint64_t epoch2 = 0;
  CHECK(usig_get_epoch(u2, &epoch2) == USIG_OK && epoch2 != epoch);
  uint8_t pub2[64];
  CHECK(usig_get_pubkey(u2, pub2) == USIG_OK);
  CHECK(std::memcmp(pub, pub2, 64) == 0);
  uint64_t counter = 0;
  CHECK(usig_create_ui(u2, digest, &counter, sig) == USIG_OK);
  CHECK(counter == 1);
  /* the restored instance's counter-1 certificate binds the NEW epoch:
   * it can never collide with the old instance's (epoch, cv=1) cert. */
  CHECK(usig_verify_ui(pub, epoch2, digest, counter, sig) == USIG_OK);
  CHECK(usig_verify_ui(pub, epoch, digest, counter, sig) != USIG_OK);

  /* malformed sealed blobs are rejected */
  usig_t *u3 = nullptr;
  CHECK(usig_init(&u3, blob.data(), 3) == USIG_ERR_SEALED);
  blob[0] ^= 1;
  CHECK(usig_init(&u3, blob.data(), sealed_len) == USIG_ERR_SEALED);
  blob[0] ^= 1;

  /* v1 blobs (magic || epoch_be8 || key) still restore the key, with the
   * stored epoch ignored. */
  {
    std::vector<uint8_t> v1;
    v1.push_back('U'); v1.push_back('S'); v1.push_back('G'); v1.push_back('1');
    for (int i = 0; i < 8; ++i)
      v1.push_back(static_cast<uint8_t>(epoch >> (56 - 8 * i)));
    v1.insert(v1.end(), blob.begin() + 4, blob.begin() + sealed_len);
    usig_t *u4 = nullptr;
    CHECK(usig_init(&u4, v1.data(), v1.size()) == USIG_OK);
    uint64_t epoch4 = 0;
    CHECK(usig_get_epoch(u4, &epoch4) == USIG_OK && epoch4 != epoch);
    uint8_t pub4[64];
    CHECK(usig_get_pubkey(u4, pub4) == USIG_OK);
    CHECK(std::memcmp(pub, pub4, 64) == 0);
    CHECK(usig_destroy(u4) == USIG_OK);
  }

  /* small-buffer seal is refused */
  uint8_t tiny[4];
  size_t out_len = 0;
  CHECK(usig_seal(u, tiny, sizeof tiny, &out_len) == USIG_ERR_BUFSZ);

  /* encrypted sealing (v3): round-trips under the right secret, is
   * refused without one or with the wrong one, and the blob holds no
   * plaintext DER (sgx_seal_data confidentiality analogue). */
  {
    const uint8_t secret[] = "operator-secret";
    size_t need3 = 0;
    CHECK(usig_sealed_size2(u, sizeof secret - 1, &need3) == USIG_OK);
    std::vector<uint8_t> enc(need3);
    size_t enc_len = 0;
    CHECK(usig_seal2(u, secret, sizeof secret - 1, enc.data(), enc.size(),
                     &enc_len) == USIG_OK);
    CHECK(enc_len == need3);
    /* the plaintext DER (from the v2 blob) must not appear in the
     * ciphertext */
    const uint8_t *der = blob.data() + 4;
    size_t der_len = sealed_len - 4;
    bool found = false;
    for (size_t i = 0; i + der_len <= enc_len && !found; ++i)
      found = std::memcmp(enc.data() + i, der, der_len) == 0;
    CHECK(!found);

    usig_t *u5 = nullptr;
    CHECK(usig_init2(&u5, enc.data(), enc_len, secret, sizeof secret - 1) ==
          USIG_OK);
    uint8_t pub5[64];
    CHECK(usig_get_pubkey(u5, pub5) == USIG_OK);
    CHECK(std::memcmp(pub, pub5, 64) == 0);
    CHECK(usig_destroy(u5) == USIG_OK);

    usig_t *u6 = nullptr;
    CHECK(usig_init2(&u6, enc.data(), enc_len, nullptr, 0) ==
          USIG_ERR_SECRET);
    const uint8_t wrong[] = "wrong-secret";
    CHECK(usig_init2(&u6, enc.data(), enc_len, wrong, sizeof wrong - 1) ==
          USIG_ERR_SECRET);
  }

  /* Concurrent certification hammer (the race tier, `make check-race`):
   * usig.h promises usig_create_ui is thread-safe behind an internal
   * lock (the reference enclave's ecallLock).  N threads certify
   * concurrently on one instance; the counter values they observe must
   * be a permutation of one contiguous range — a duplicate or a gap
   * would be exactly the monotonicity break the whole protocol leans
   * on.  Built under ThreadSanitizer this also proves the signing path
   * itself (shared EVP contexts would tear here) is data-race free. */
  {
    usig_t *uc = nullptr;
    CHECK(usig_init(&uc, nullptr, 0) == USIG_OK);
    const int kThreads = 8;
    const int kPerThread = 64;
    std::vector<std::vector<uint64_t>> seen(kThreads);
    std::vector<std::thread> workers;
    std::vector<int> fails(kThreads, 0);
    for (int t = 0; t < kThreads; ++t) {
      workers.emplace_back([&, t] {
        uint8_t d[32];
        std::memset(d, 0x30 + t, sizeof d);
        uint8_t s[64];
        for (int i = 0; i < kPerThread; ++i) {
          uint64_t cv = 0;
          if (usig_create_ui(uc, d, &cv, s) != USIG_OK) {
            ++fails[t];
            return;
          }
          seen[t].push_back(cv);
        }
      });
    }
    for (auto &w : workers) w.join();
    std::vector<uint64_t> all;
    for (int t = 0; t < kThreads; ++t) {
      CHECK(fails[t] == 0);
      all.insert(all.end(), seen[t].begin(), seen[t].end());
    }
    std::sort(all.begin(), all.end());
    CHECK(all.size() == static_cast<size_t>(kThreads * kPerThread));
    for (size_t i = 0; i < all.size(); ++i)
      CHECK(all[i] == i + 1);  /* contiguous from 1: no duplicate, no gap */
    CHECK(usig_destroy(uc) == USIG_OK);
  }

  CHECK(usig_destroy(u) == USIG_OK);
  CHECK(usig_destroy(u2) == USIG_OK);

  std::printf("usig_test: all checks passed (%s)\n", usig_native_version());
  return 0;
}
