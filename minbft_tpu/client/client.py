"""Client implementation.

Reference structure (client/):

- request pipeline: construct -> sign(ClientAuthen over AuthenBytes) ->
  broadcast to n sender tasks (reference client/request.go:186-204,
  requestbuffer.go:59-88);
- per-replica connection task pair: outgoing pumps the request stream,
  incoming authenticates REPLYs (ReplicaAuthen + client-ID check,
  reference client/message-handling.go:161-170) and feeds the collector;
- collector: f+1 matching replies by SHA256(result), dedup'd by replica ID
  (reference client/request.go:83-97, requestbuffer.go:219-236).

Pipelining re-design: the reference gates one request in flight per client
(requestbuffer.go:59-88 AddRequest blocks until the prior request is
removed) because its replicas process a client's requests one sequence at a
time anyway.  Here requests are tracked in a per-seq pending map, so a
client may pipeline many requests; the replicas' clientstate still captures
each client's sequences in order, but the network/verification latency of
request k no longer serializes request k+1 — this is what lets the batch
verification engine actually fill batches (the round-1 bench ran one
request at a time and starved it).  ``max_inflight`` bounds the pipeline;
an asyncio semaphore replaces the reference's single-slot buffer when set
to 1.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from typing import AsyncIterator, Dict, Optional

from .. import api
from ..obs import trace as obs_trace
from ..utils.backoff import ReconnectBackoff, RetransmitBackoff
from ..messages import (
    Busy,
    CodecError,
    Reply,
    Request,
    authen_bytes,
    drain_multi,
    marshal,
    split_multi,
    unmarshal,
)

# Consecutive reply-handling failures on one stream before it is torn down
# for a backoff redial (see _run_connection's poison-frame guard).
_MAX_CONSECUTIVE_REPLY_ERRORS = 10


class _PendingRequest:
    __slots__ = (
        "seq",
        "threshold",
        "read_only",
        "replies_by_replica",
        "count_by_digest",
        "result",
        "data",
        "busy_until",
    )

    def __init__(
        self,
        seq: int,
        threshold: int,
        loop: asyncio.AbstractEventLoop,
        read_only: bool = False,
    ):
        self.seq = seq
        # f+1 matching replies for ordered requests; ALL n for read-only
        # fast reads (the n=2f+1 read-quorum bound — see Client.request).
        self.threshold = threshold
        self.read_only = read_only
        self.replies_by_replica: Dict[int, bytes] = {}
        self.count_by_digest: Dict[bytes, int] = {}
        self.result: asyncio.Future = loop.create_future()
        # Pre-retrieve any exception outcome: an error quorum landing just
        # after the awaiter timed out (and the pending was popped) must
        # not log "Future exception was never retrieved" on GC.
        self.result.add_done_callback(
            lambda f: f.exception() if not f.cancelled() else None
        )
        # Marshaled REQUEST bytes, kept so a reconnecting replica stream can
        # re-send everything still unresolved (see _run_connection).
        self.data: Optional[bytes] = None
        # Monotonic deadline before which retransmission is suppressed —
        # set by a verified BUSY shed signal (replica admission control).
        # The request itself stays live: a reply still resolves it.
        self.busy_until: float = 0.0

    def add_reply(self, reply: Reply) -> None:
        if reply.read_only != self.read_only:
            return  # an ordered reply cannot complete a read, nor vice versa
        if reply.replica_id in self.replies_by_replica:
            return  # one vote per replica (reference requestbuffer.go:219-236)
        self.replies_by_replica[reply.replica_id] = reply.result
        # The error flag is part of the vote: a signed error reply must
        # never merge with a real empty result.
        digest = hashlib.sha256(
            (b"\x01" if reply.error else b"\x00") + reply.result
        ).digest()
        cnt = self.count_by_digest.get(digest, 0) + 1
        self.count_by_digest[digest] = cnt
        if cnt >= self.threshold and not self.result.done():
            if reply.error:
                self.result.set_exception(
                    api.ReadOnlyQueryError(
                        "replica quorum signed error replies: query "
                        "unsupported or raised on this operation"
                    )
                )
            else:
                self.result.set_result(reply.result)


class Client:
    def __init__(
        self,
        client_id: int,
        n: int,
        f: int,
        authenticator: api.Authenticator,
        connector: api.ReplicaConnector,
        seq_start: Optional[int] = None,
        max_inflight: Optional[int] = None,
        retransmit_interval: Optional[float] = None,
        trace: bool = False,
        group: Optional[int] = None,
    ):
        if n < 2 * f + 1:
            raise ValueError(f"n must be at least 2f+1 (n={n}, f={f})")
        self.client_id = client_id
        self.n = n
        self.f = f
        # Consensus-group id when this is one of a MultiGroupClient's
        # per-group inner clients (minbft_tpu/groups): labels the flight
        # recorder so grouped dumps stay separable; None = ungrouped.
        self.group = group
        self._auth = authenticator
        self._connector = connector
        # Sequence numbers seeded from wall clock so a restarted client
        # doesn't reuse sequences (reference client/request.go:209-217).
        self._seq = seq_start if seq_start is not None else time.time_ns()
        self._pending: Dict[int, _PendingRequest] = {}
        self._inflight: Optional[asyncio.Semaphore] = (
            asyncio.Semaphore(max_inflight) if max_inflight else None
        )
        self._retransmit_interval = retransmit_interval
        self._queues: Dict[int, asyncio.Queue] = {}
        self._tasks: list = []
        self._started = False
        # Broadcast-order gate: ordered REQUESTs must hit the wire in seq
        # order (see request()) even when their batch-signed signatures
        # resolve out of order.  Holds the previous ordered request's
        # "broadcast done" future.
        self._send_gate: Optional[asyncio.Future] = None
        # Flight recorder for the client-side spans (sign → broadcast →
        # first-reply → f+1-quorum); one predicated check per hook when
        # off (obs/trace.py).
        self._trace = (
            obs_trace.FlightRecorder.for_client(client_id, group=group)
            if (trace or obs_trace.tracing_enabled())
            else None
        )
        # Verified BUSY shed signals received (observable by load harnesses).
        self.busy_signals = 0
        self._log = logging.getLogger(f"minbft_tpu.client.{client_id}")

    # -- connections --------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        for rid in range(self.n):
            handler = self._connector.replica_message_stream_handler(rid)
            if handler is None:
                raise ValueError(f"no connection for replica {rid}")
            q: asyncio.Queue = asyncio.Queue()
            self._queues[rid] = q
            task = loop.create_task(self._run_connection(rid, handler, q))
            # A connection task dying with an exception (a bug — the loop
            # is designed to swallow transport errors and redial) must
            # not lose the trace: dump on the fatal error, not only on a
            # clean stop() (the crashed-soak blind spot).
            task.add_done_callback(self._on_task_done)
            self._tasks.append(task)
        self._started = True

    def _on_task_done(self, task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is None:
            return
        self._log.error(
            "client %d task %s died: %r", self.client_id, task.get_name(), exc
        )
        if self._trace is not None:
            try:
                obs_trace.dump_recorder(self._trace)
            except OSError:
                pass

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        self._started = False
        # Fail in-flight requests instead of leaving their callers parked
        # on futures nothing will ever resolve.
        for pending in list(self._pending.values()):
            if not pending.result.done():
                pending.result.set_exception(
                    ConnectionError("client stopped with the request in flight")
                )
        if self._trace is not None:
            # No-op unless MINBFT_TRACE_DUMP is set (live-scrape-only
            # recorders have nothing to flush).
            obs_trace.dump_recorder(self._trace)

    async def _outgoing(self, q: asyncio.Queue) -> AsyncIterator[bytes]:
        # Coalesce a pipelined burst of requests into one transport
        # frame — per-frame gRPC/asyncio cost dominates on small hosts
        # (see core.message_handling's pump coalescing).
        while True:
            data, _ = drain_multi(await q.get(), q)
            yield data

    async def _run_connection(
        self, replica_id: int, handler: api.MessageStreamHandler, q: asyncio.Queue
    ) -> None:
        """One replica's stream, redialed with backoff when it drops.

        Mirrors core.message_handling.run_peer_connection: both connectors
        dial a fresh connection per handle_message_stream call, so a network
        blip or replica restart must not permanently cost the client a
        reply vote — with only f+1 matching replies required, losing >f
        streams forever would wedge every future request even though every
        replica is healthy again.  Each redial swaps in a FRESH queue (the
        dead attempt's outgoing pump may still hold q.get() and would steal
        frames) and re-sends every still-pending request: frames drained
        into the dying connection are otherwise lost, and replica-side
        clientstate dedups the re-send (same reply re-served from cache)."""
        backoff = ReconnectBackoff()
        while True:
            attempt_start = time.monotonic()
            poisoned = False
            # Per-STREAM counter (the constant's contract): carrying it
            # across redials would tear every later stream down on its
            # first failure.
            consecutive_errors = 0
            try:
                async for data in handler.handle_message_stream(self._outgoing(q)):
                    try:
                        frames = split_multi(data)
                    except CodecError:
                        continue
                    for fr in frames:
                        # A poison frame (reply handling raising — only
                        # local bugs or transient verifier/backend errors
                        # reach here; auth and codec failures are swallowed
                        # inside _handle_reply) costs the FRAME, not the
                        # connection.  A run of them tears the stream down
                        # for a BACKOFF redial — never permanently: a
                        # transient verifier outage must not sever >f
                        # streams forever (the wedge this loop exists to
                        # prevent), while a deterministic bug self-throttles
                        # at the ladder cap.
                        try:
                            await self._handle_reply(replica_id, fr)
                            consecutive_errors = 0
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            consecutive_errors += 1
                            self._log.exception(
                                "client %d replica %d: reply handling failed "
                                "(%d consecutive)",
                                self.client_id,
                                replica_id,
                                consecutive_errors,
                            )
                            if consecutive_errors >= _MAX_CONSECUTIVE_REPLY_ERRORS:
                                poisoned = True
                                break
                    if poisoned:
                        break
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # A faulty replica connection must not break the client: f+1
                # matching replies from the others still complete requests.
                # But an operator debugging missing reply votes needs the
                # cause (auth failure vs refused vs codec bug) on record.
                self._log.warning(
                    "client %d replica %d stream failed: %s",
                    self.client_id,
                    replica_id,
                    e,
                )
            delay = backoff.next_delay(time.monotonic() - attempt_start)
            q = asyncio.Queue()
            self._queues[replica_id] = q
            resent = 0
            for pending in self._pending.values():
                if (
                    pending.data is not None
                    and not pending.result.done()
                    # this replica already voted: its clientstate would only
                    # re-serve a reply add_reply discards as a duplicate
                    and replica_id not in pending.replies_by_replica
                ):
                    q.put_nowait(pending.data)
                    resent += 1
            self._log.debug(
                "client %d replica %d stream ended: redialing in %.1fs "
                "(%d pending re-sent)",
                self.client_id,
                replica_id,
                delay,
                resent,
            )
            await asyncio.sleep(delay)

    async def _handle_reply(self, replica_id: int, data: bytes) -> None:
        try:
            msg = unmarshal(data)
        except Exception:
            return
        if isinstance(msg, Busy):
            await self._handle_busy(replica_id, msg)
            return
        if not isinstance(msg, Reply):
            return
        # Authenticate and attribute (reference client/message-handling.go:161-170).
        if msg.replica_id != replica_id or msg.client_id != self.client_id:
            return
        pending = self._pending.get(msg.seq)
        if pending is None or pending.result.done():
            return
        try:
            await self._auth.verify_message_authen_tag(
                api.AuthenticationRole.REPLICA,
                msg.replica_id,
                authen_bytes(msg),
                msg.signature,
            )
        except api.AuthenticationError:
            return
        # Re-fetch: the request may have resolved/retired during the await.
        pending = self._pending.get(msg.seq)
        if pending is not None:
            tr = self._trace
            if tr is None:
                pending.add_reply(msg)
                return
            first = not pending.replies_by_replica
            was_done = pending.result.done()
            pending.add_reply(msg)
            if first and pending.replies_by_replica:
                tr.note(obs_trace.C_FIRST_REPLY, self.client_id, msg.seq)
            if not was_done and pending.result.done():
                tr.note(obs_trace.C_QUORUM, self.client_id, msg.seq)

    async def _handle_busy(self, replica_id: int, msg: Busy) -> None:
        """A replica shed our REQUEST at its admission boundary: verify the
        signal (a forged BUSY must not be able to starve this client) and
        suppress retransmission of that request for ``retry_after_ms``.
        The pending request stays live — replies from less-loaded replicas
        (or this one, post-recovery) still resolve it; only the re-send
        pressure backs off."""
        if msg.replica_id != replica_id or msg.client_id != self.client_id:
            return
        pending = self._pending.get(msg.seq)
        if pending is None or pending.result.done():
            return
        try:
            await self._auth.verify_message_authen_tag(
                api.AuthenticationRole.REPLICA,
                msg.replica_id,
                authen_bytes(msg),
                msg.signature,
            )
        except api.AuthenticationError:
            return
        # Re-fetch: the request may have resolved during the await.
        pending = self._pending.get(msg.seq)
        if pending is None:
            return
        hold = min(max(msg.retry_after_ms, 0), 60_000) / 1000.0
        pending.busy_until = max(pending.busy_until, time.monotonic() + hold)
        self.busy_signals += 1

    # -- requests -----------------------------------------------------------

    async def request(
        self,
        operation: bytes,
        timeout: Optional[float] = None,
        read_only: bool = False,
        read_timeout: float = 1.0,
        read_fallback: bool = True,
    ) -> bytes:
        """Submit an operation; resolves once f+1 replicas agree on the
        result (reference client/client.go:66-71 Request).  Many requests
        may be pipelined concurrently (bounded by ``max_inflight``).

        ``read_only=True`` takes the fast path (reference roadmap
        README.md:503-504): replicas answer from committed state without
        ordering, and the read is accepted only when ALL n replies match —
        with n=2f+1 a read quorum below n cannot be guaranteed to
        intersect a write quorum in a correct replica, so any smaller
        threshold could return stale data.  If the cluster disagrees (a
        write is in flight, a replica lags or is down), the fast read
        times out after ``read_timeout`` and, with ``read_fallback``,
        the operation is resubmitted as an ordered request — the same
        degradation PBFT's read-only optimization uses."""
        if not self._started:
            raise RuntimeError("client not started")
        mode = 0
        if read_only:
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
            ro_wait = (
                read_timeout if timeout is None else min(read_timeout, timeout)
            )
            # Fast reads respect max_inflight too: the pipelining bound is
            # an operator cap on replica load, and query work is load.
            if self._inflight is not None:
                await self._inflight.acquire()
            try:
                if not self._started:
                    # stopped while parked on the semaphore: the sweep in
                    # stop() already ran, so registering now would hang
                    raise ConnectionError("client stopped")
                return await self._request_read_only(operation, ro_wait)
            except (asyncio.TimeoutError, api.ReadOnlyQueryError):
                # ReadOnlyQueryError: the fast quorum ANSWERED — with
                # signed errors.  The ordered fallback usually fails the
                # same way (it raises the typed error to the caller),
                # but falling back is honest and costs one attempt.
                if not read_fallback:
                    raise
            finally:
                if self._inflight is not None:
                    self._inflight.release()
            if deadline is not None and deadline - time.monotonic() <= 0.005:
                # The fast attempt consumed the caller's whole budget:
                # signing + broadcasting a fallback that times out in
                # microseconds only wastes consensus work.
                raise asyncio.TimeoutError()
            # Fall through to the ordered pipeline as an ORDERED read
            # (read_mode=2): consensus linearizes it, execution answers
            # via consumer.query — no state mutation, f+1 reply quorum.
            mode = 2
            timeout = (
                None if deadline is None else deadline - time.monotonic()
            )
        if self._inflight is not None:
            await self._inflight.acquire()
        try:
            if not self._started:
                # stopped while parked on the semaphore (see stop())
                raise ConnectionError("client stopped")
            self._seq += 1
            seq = self._seq
            # Broadcast-order gate: replica-side retirement has
            # watermark-jump semantics (executing seq k supersedes every
            # lower seq of this client), so ordered REQUESTs must reach
            # the wire in seq order.  Batch signing suspends between seq
            # allocation and broadcast — without the gate, seq k+1's
            # signature resolving first would broadcast it ahead of seq
            # k and k could be superseded unexecuted.  Signing itself
            # still co-batches: every pipelined request submits to the
            # sign queue immediately; only the SEND waits for its
            # predecessor's send.
            prev_gate = self._send_gate
            gate: asyncio.Future = asyncio.get_running_loop().create_future()
            self._send_gate = gate
            tr = self._trace
            try:
                req = Request(
                    client_id=self.client_id,
                    seq=seq,
                    operation=operation,
                    read_mode=mode,
                )
                if tr is not None:
                    tr.note(obs_trace.C_START, self.client_id, seq)
                # Awaitable batch-aware signing: concurrent pipelined
                # requests co-batch their signatures on the engine's sign
                # queue (plain synchronous signing for engine-less
                # authenticators).
                req.signature = await self._auth.generate_message_authen_tag_async(
                    api.AuthenticationRole.CLIENT, authen_bytes(req)
                )
                if tr is not None:
                    tr.note(obs_trace.C_SIGN, self.client_id, seq)
                if prev_gate is not None and not prev_gate.done():
                    await prev_gate
                pending = _PendingRequest(
                    seq,
                    self.f + 1,
                    asyncio.get_running_loop(),
                    read_only=bool(mode),
                )
                self._pending[seq] = pending
                data = marshal(req)
                pending.data = data
                self._broadcast(data)
                if tr is not None:
                    tr.note(obs_trace.C_BROADCAST, self.client_id, seq)
            finally:
                # Always open the gate — a failed/cancelled sign must not
                # wedge every later request (its seq simply goes unused;
                # client seqs need not be dense).
                if not gate.done():
                    gate.set_result(None)
            try:
                if self._retransmit_interval is not None:
                    return await self._await_with_retransmit(pending, data, timeout)
                if timeout is not None:
                    return await asyncio.wait_for(pending.result, timeout)
                return await pending.result
            finally:
                self._pending.pop(seq, None)
        finally:
            if self._inflight is not None:
                self._inflight.release()

    async def _request_read_only(self, operation: bytes, wait: float) -> bytes:
        """One fast-read attempt: broadcast, require ALL n matching."""
        self._seq += 1
        seq = self._seq
        req = Request(
            client_id=self.client_id,
            seq=seq,
            operation=operation,
            read_mode=1,
        )
        tr = self._trace
        if tr is not None:
            tr.note(obs_trace.C_START, self.client_id, seq)
        req.signature = await self._auth.generate_message_authen_tag_async(
            api.AuthenticationRole.CLIENT, authen_bytes(req)
        )
        if tr is not None:
            tr.note(obs_trace.C_SIGN, self.client_id, seq)
        pending = _PendingRequest(
            seq, self.n, asyncio.get_running_loop(), read_only=True
        )
        self._pending[seq] = pending
        data = marshal(req)
        pending.data = data
        self._broadcast(data)
        if tr is not None:
            tr.note(obs_trace.C_BROADCAST, self.client_id, seq)
        try:
            return await asyncio.wait_for(pending.result, wait)
        finally:
            self._pending.pop(seq, None)

    def _broadcast(self, data: bytes) -> None:
        for q in self._queues.values():
            q.put_nowait(data)

    async def _await_with_retransmit(
        self, pending: _PendingRequest, data: bytes, timeout: Optional[float]
    ) -> bytes:
        """Re-send the request until resolved — the network may drop
        messages (the reference relies on its stream replay design,
        core/message-handling.go:316-350 HELLO log replay, for the peer side;
        clients get retransmission here).  Intervals climb a capped
        exponential ladder with jitter (utils.backoff.RetransmitBackoff):
        a fixed interval re-broadcast every unresolved pipelined request
        in the same tick, which under loss or partition turned the
        recovery path itself into a synchronized load spike."""
        deadline = None if timeout is None else time.monotonic() + timeout
        backoff = RetransmitBackoff(self._retransmit_interval)
        while True:
            interval = backoff.next_delay()
            if deadline is not None:
                interval = min(interval, max(deadline - time.monotonic(), 0.001))
            try:
                return await asyncio.wait_for(
                    asyncio.shield(pending.result), interval
                )
            except asyncio.TimeoutError:
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                if time.monotonic() < pending.busy_until:
                    # A verified BUSY hold is active: retransmitting into a
                    # saturated replica set only deepens the overload (and
                    # earns another shed).  Skip this tick; the ladder keeps
                    # climbing, and the overall deadline still applies.
                    continue
                self._broadcast(data)


def new_client(
    client_id: int,
    n: int,
    f: int,
    authenticator: api.Authenticator,
    connector: api.ReplicaConnector,
    **kw,
) -> Client:
    """Create a client (reference client.New, client/client.go:51-64)."""
    return Client(client_id, n, f, authenticator, connector, **kw)
