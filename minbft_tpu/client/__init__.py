"""Client protocol library.

Mirrors the reference ``client`` package (reference client/client.go:51-77):
construct and sign a REQUEST, broadcast it to all n replicas, accept the
result once **f+1 matching REPLYs** (keyed by the SHA-256 of the result)
arrive from distinct replicas.
"""

from .client import Client, new_client

__all__ = ["Client", "new_client"]
