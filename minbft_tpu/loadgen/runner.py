"""Local-cluster load runs (ISSUE 15): one entry point shared by
``peer load``, ``bench.py bench_load``, the CI load-smoke step, and the
tests.

Stands up an in-process n-replica cluster whose CLIENT traffic rides
REAL loopback TCP (``TcpReplicaServer`` in front of each replica;
replica-to-replica stays in-process — the measurement target is the
client-facing ingest/admission path, not peer gossip), builds the
identity fleet, drives an :class:`~.harness.OpenLoopGenerator`, and
returns the merged report: generator-side curve point plus cluster-side
commit/shed/queue-high-water accounting.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from .arrivals import LoadSpec
from .harness import OpenLoopGenerator

_USIG_SPEC = "HMAC_SHA256"  # cheapest USIG: the load path is the target


def _replica_auth(store, rid: int):
    if store.mac_keys:
        return store.mac_replica_authenticator(rid)
    return store.replica_authenticator(rid)


def _client_auth(store, cid: int):
    if store.mac_keys:
        return store.mac_client_authenticator(cid)
    return store.client_authenticator(cid)


async def _warmup(spec: LoadSpec, n: int, f: int, store, addrs) -> None:
    """One committed write per group through a throwaway closed-loop
    client, over the same TCP path the generator will use."""
    from ..client import new_client
    from ..sample.conn.tcp import connect_many_replicas_tcp

    warm_cid = spec.n_clients  # the extra identity past the fleet
    conn = connect_many_replicas_tcp(addrs, kind="client")
    warm_auth = _client_auth(store, warm_cid)
    if spec.n_groups > 1:
        from ..groups import MultiGroupClient

        client = MultiGroupClient(
            warm_cid, n, f, spec.n_groups, warm_auth, conn
        )
        await client.start()
        try:
            for g in range(spec.n_groups):
                await asyncio.wait_for(
                    client.request(b"loadgen-warmup", group=g), 120
                )
        finally:
            await client.stop()
    else:
        client = new_client(warm_cid, n, f, warm_auth, conn)
        await client.start()
        try:
            await asyncio.wait_for(client.request(b"loadgen-warmup"), 120)
        finally:
            await client.stop()
            await conn.close()


async def run_local_load(
    spec: LoadSpec,
    n: int = 4,
    f: int = 1,
    pool_slots: int = 4,
    retransmit_interval: Optional[float] = 0.5,
    drain_s: float = 5.0,
    verify_replies: bool = False,
    batchsize_prepare: int = 64,
    expect_goodput: float = 0.0,
    scheme: str = "mac",
    chips: Optional[int] = None,
    pool_util_prefix: Optional[str] = None,
    slo_target_ms: Optional[float] = None,
    slo_objective: Optional[float] = None,
) -> dict:
    """Run ``spec`` against a fresh local cluster; returns the report.

    ``pool_slots`` bounds the client-side connection pool: slots × n real
    TCP connections total, however many thousand identities ride them.
    ``expect_goodput`` (req/s) stamps ``goodput_ok`` into the report —
    the ``peer load`` / CI rc contract.  ``scheme`` defaults to pairwise
    MACs: the harness measures the ingest/admission/consensus path, and
    on an OpenSSL-less container pure-Python ECDSA (~10ms/verify) would
    turn every run into a host-crypto benchmark; pass ``ecdsa-p256`` to
    include public-key request auth in the measurement.

    ``chips`` (grouped runs only) threads a multi-device
    :class:`~minbft_tpu.parallel.EnginePool` through each replica's
    group runtime — one verify/sign engine per home chip, groups placed
    round-robin (ISSUE 17).  ``None`` (default) keeps the engine-less
    path byte-for-byte; any integer (1 included — the pool clamps to
    the visible device count) builds a pool per replica, routing MAC
    verifies through each group's home-chip engine (host HMAC lane —
    batched, no kernel compile, honest on every backend).
    ``pool_util_prefix`` additionally snapshots replica 0's pool through
    the PR-9 :class:`~minbft_tpu.obs.ledger.PoolLedger` over the
    measured run and returns the ``{prefix}_chip{c}_util_*`` /
    pool-aggregate ``{prefix}_util_*`` keys (plus
    ``{prefix}_verify_mean_batch``) under ``report["pool_util"]`` —
    the bench grid merges them into the artifact verbatim.

    ``slo_target_ms`` stamps ``slo_ok`` (good_fraction >= objective)
    into the report — the optional third leg of the ``peer load`` rc
    contract; ``slo_objective`` defaults to the env/config-resolved
    :class:`~minbft_tpu.obs.slo.SLOPolicy` objective (0.99).  When
    ``MINBFT_SLO_DUMP`` names a spool directory, a run that breached
    its objective hands ONE rate-limited forensic bundle (replica
    flight-recorder docs, scheduled-origin loadgen metadata, burn
    rates replayed from the run) to the breach spool.
    """
    from ..core import new_replica
    from ..groups import GroupAuthenticator, new_group_runtime
    from ..sample.authentication import generate_testnet_keys
    from ..sample.config import SimpleConfiger
    from ..sample.conn.inprocess import (
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from ..sample.conn.tcp import TcpReplicaServer, connect_many_replicas_tcp
    from ..sample.requestconsumer import SimpleLedger

    spec.validate()
    if hasattr(asyncio, "eager_task_factory"):
        asyncio.get_running_loop().set_task_factory(asyncio.eager_task_factory)
    if scheme not in ("mac", "ecdsa-p256"):
        raise ValueError(f"unknown auth scheme {scheme!r}")
    # +1 identity: the warmup client needs its own sequence space (the
    # generator pre-assigns seqs for ids 0..n_clients-1).
    store = generate_testnet_keys(
        n,
        n_clients=spec.n_clients + 1,
        usig_spec=_USIG_SPEC,
        with_macs=scheme == "mac",
    )
    cfg = SimpleConfiger(
        n=n,
        f=f,
        # Steady-state measurement: an overloaded-but-shedding replica
        # must not detonate a view-change cascade mid-run (the bench
        # convention; see bench.py _bench_cluster).
        timeout_request=900.0,
        timeout_prepare=450.0,
        batchsize_prepare=batchsize_prepare,
        groups=spec.n_groups,
    )
    stubs = make_testnet_stubs(n)
    grouped = spec.n_groups > 1
    ledgers: list = []
    replicas = []
    servers = []
    pools = []
    for i in range(n):
        if grouped:
            group_ledgers = [SimpleLedger() for _ in range(spec.n_groups)]
            ledgers.append(group_ledgers)
            engine_pool = None
            if chips is not None:
                from ..parallel import EnginePool

                engine_pool = EnginePool(chips=chips)
                pools.append(engine_pool)
            r = new_group_runtime(
                i,
                cfg,
                [_replica_auth(store, i) for _ in range(spec.n_groups)],
                InProcessPeerConnector(stubs),
                group_ledgers,
                engine_pool=engine_pool,
            )
        else:
            ledger = SimpleLedger()
            ledgers.append(ledger)
            r = new_replica(
                i,
                cfg,
                _replica_auth(store, i),
                InProcessPeerConnector(stubs),
                ledger,
            )
        stubs[i].assign_replica(r)
        replicas.append(r)
    gen = None
    connectors = []
    try:
        for r in replicas:
            await r.start()
        addrs = {}
        for i, r in enumerate(replicas):
            srv = TcpReplicaServer(r)
            servers.append(srv)
            addrs[i] = await srv.start("127.0.0.1:0")

        # Warmup OFF the clock (the bench convention): first-use costs —
        # USIG/crypto warm paths, the first PREPARE/COMMIT round, stream
        # setup — otherwise land as a multi-second stall INSIDE the
        # schedule and starve the firing loop (everything shares one
        # event loop here).
        await _warmup(spec, n, f, store, addrs)

        # Pool attribution window opens AFTER warmup (the ledger deltas
        # against its construction-time baseline, so warmup batches
        # never pollute the measured busy/fill).
        pool_ledger = None
        if pools and pool_util_prefix:
            from ..obs.ledger import PoolLedger

            pool_ledger = PoolLedger(pools[0])

        client_ids = list(range(spec.n_clients))
        schedule = None
        if grouped:
            # Client affinity: each identity signs in ITS group's domain
            # (GroupAuthenticator — matches the group core that will
            # verify it); the schedule knows each client's group.
            from .arrivals import build_schedule

            schedule = build_schedule(spec)
            group_of = {}
            for a in schedule.arrivals:
                group_of.setdefault(a.client_idx, a.group)
            authenticators = [
                GroupAuthenticator(
                    _client_auth(store, cid), group_of.get(cid, 0)
                )
                for cid in client_ids
            ]
        else:
            authenticators = [
                _client_auth(store, cid) for cid in client_ids
            ]
        connectors = [
            connect_many_replicas_tcp(addrs, kind="client")
            for _ in range(max(pool_slots, 1))
        ]
        gen = OpenLoopGenerator(
            spec,
            n,
            f,
            client_ids,
            authenticators,
            connectors,
            retransmit_interval=retransmit_interval,
            drain_s=drain_s,
            verify_replies=verify_replies,
            schedule=schedule,
            slo_target_ms=slo_target_ms,
        )
        report = await gen.run()
        # Breach forensics BEFORE teardown: the bundle reads the live
        # replicas' flight recorders and SLO ledgers.
        _slo_forensics(report, gen, replicas, grouped, f, slo_objective)
        if pool_ledger is not None:
            # Snapshot before teardown: wall time must cover exactly the
            # measured run, not the server drain below.  MAC request
            # auth rides the host HMAC lane of each home-chip engine.
            queue = (
                "hmac_sha256_host" if scheme == "mac" else "ecdsa_p256"
            )
            util = pool_ledger.util_keys(pool_util_prefix, queue)
            win = pool_ledger.window(queue)
            if win is not None:
                util[f"{pool_util_prefix}_verify_mean_batch"] = round(
                    win.mean_batch, 2
                )
            report["pool_util"] = util
            report["pool_placement"] = {
                str(g): c for g, c in sorted(pools[0].placement().items())
            }
    finally:
        for srv in servers:
            try:
                await srv.stop()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        for r in replicas:
            try:
                await r.stop()
            except Exception:  # noqa: BLE001
                pass

    # Cluster-side accounting: committed entries, admission visibility,
    # queue high-water marks (the bounded-growth witness).
    committed = 0
    shed = busy_sent = suppressed = 0
    rx_peak = 0
    rx_bound = 0
    for i in range(n):
        if grouped:
            metrics_list = [core.metrics for core in replicas[i].cores]
            committed += max(lg.length for lg in ledgers[i])
        else:
            metrics_list = [replicas[i].metrics]
            committed += ledgers[i].length
        for m in metrics_list:
            shed += m.counters.get("admission_shed", 0)
            busy_sent += m.counters.get("admission_busy_sent", 0)
            suppressed += m.counters.get("admission_busy_suppressed", 0)
            rx_peak = max(rx_peak, getattr(m, "admission_rx_peak", 0))
            rx_bound = max(rx_bound, getattr(m, "admission_rx_bound", 0))
    arrivals = max(report.get("arrivals", 0), 1)
    report["cluster"] = {
        "n": n,
        "f": f,
        # Actual pool width (post-clamp) — 1 when no pool was threaded.
        "chips": pools[0].chips if pools else 1,
        "committed_entries_all_replicas": committed,
        "admission_shed": shed,
        "admission_busy_sent": busy_sent,
        "admission_busy_suppressed": suppressed,
        "admission_rx_peak": rx_peak,
        "admission_rx_bound": rx_bound,
        # Shed rate against offered arrivals (sheds can exceed arrivals
        # under retransmission, so this is a rate, not a fraction of 1).
        "shed_per_arrival": round(shed / arrivals, 3),
    }
    if expect_goodput > 0:
        report["expect_goodput_per_sec"] = expect_goodput
        report["goodput_ok"] = report["goodput_per_sec"] >= expect_goodput
    if slo_target_ms is not None:
        from ..obs.slo import SLOPolicy

        if slo_objective is None:
            slo_objective = SLOPolicy.from_env().objective
        report["slo_objective"] = slo_objective
        report["slo_ok"] = report["slo_good_fraction"] >= slo_objective
    return report


def _slo_forensics(
    report: dict,
    gen: OpenLoopGenerator,
    replicas,
    grouped: bool,
    f: int,
    slo_objective: Optional[float] = None,
) -> None:
    """Hand the breach spool one bundle when the run breached and
    ``MINBFT_SLO_DUMP`` asked for forensics.  The burn rates come from
    replaying the run's scheduled-origin classifications into a ring
    (:meth:`OpenLoopGenerator.slo_ring`); the trace docs come from the
    live replicas' flight recorders (empty unless ``MINBFT_TRACE`` was
    also on); the scheduled-origin loadgen metadata doc rides along so
    :func:`~minbft_tpu.obs.slo.breach_report` classifies at the
    coordinated-omission-honest origin.  The policy is the RUN's: the
    generator's effective target (a ``slo_target_ms`` argument beats the
    env) and the caller's objective when given — the bundle must explain
    the breach that was actually declared, not the env default's."""
    import dataclasses

    from ..obs import slo as obs_slo

    spool = obs_slo.BreachSpool.from_env()
    if spool is None:
        return
    policy = obs_slo.SLOPolicy.from_env()
    policy = dataclasses.replace(
        policy,
        target_ms=gen._slo_target_ms,
        objective=(
            slo_objective if slo_objective is not None else policy.objective
        ),
    )
    if report["slo_good_fraction"] >= policy.objective:
        return
    ts = gen.slo_ring()
    burn = obs_slo.burn_rates(ts, policy)
    recorders = []
    ledgers = []
    for r in replicas:
        cores = r.cores if grouped else [r]
        for core in cores:
            h = core.handlers
            if getattr(h, "trace", None) is not None:
                recorders.append(h.trace)
            if getattr(h, "slo", None) is not None:
                ledgers.append(h.slo)
    bundle = obs_slo.build_bundle(
        policy,
        burn,
        ledgers,
        recorders=recorders,
        timeseries=ts,
        quorum=f + 1,
        extra_docs=[gen.sched_doc()],
    )
    path = spool.maybe_dump(bundle)
    report["slo_breach_bundle"] = path
    report["slo_breach_suppressed"] = spool.suppressed
