"""Open-loop load harness (ISSUE 15): seeded arrival schedules, a
bounded-connection traffic generator measuring latency from SCHEDULED
arrival time, and the replay-census faithfulness contract.

See ``perf/LOAD.md`` for the methodology and ``peer load`` /
``bench.py bench_load`` for the entry points.
"""

from .arrivals import (
    Arrival,
    LoadSpec,
    Schedule,
    build_schedule,
    replay_census,
)
from .harness import OpenLoopGenerator

__all__ = [
    "Arrival",
    "LoadSpec",
    "Schedule",
    "build_schedule",
    "replay_census",
    "OpenLoopGenerator",
]
