"""Seeded open-loop arrival schedules (ISSUE 15).

A schedule is a pure function of its :class:`LoadSpec` — same spec,
byte-identical schedule (:attr:`Schedule.digest`), across processes and
platforms.  Every random draw comes from ONE ``random.Random(seed)``
instance in a FIXED order per arrival (inter-arrival gap, then client,
then read flag, then payload class), mirroring the faultnet determinism
contract (``testing.faultnet`` SEEDED_KINDS draw order): adding a draw
or reordering draws is a breaking change to seed compatibility and must
bump the process name.

Two arrival processes:

- ``poisson``: memoryless gaps at the offered rate — the millions-of-
  independent-users regime.
- ``onoff``: bursty on/off periods whose ON rate is scaled so the
  time-averaged offered rate matches the spec — the synchronized-burst
  regime (thundering herds, retry storms).

The census (:meth:`Schedule.census`) is the replayable summary the
harness's LIVE fired-census is checked against
(:func:`replay_census` == what actually got fired), exactly the
``FaultNet.replay_counts`` contract: a divergence means the generator
dropped or invented traffic.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
import struct
from typing import Dict, Tuple

from ..groups.router import ShardRouter

_PROCESSES = ("poisson", "onoff")
_ARRIVAL_PACK = struct.Struct(">QIBIH")


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One open-loop run's full parameterization.  Frozen: the spec IS
    the schedule's identity (hash it, log it, replay it)."""

    seed: int
    rate: float  # offered arrivals/sec (time-averaged for onoff)
    duration_s: float
    n_clients: int = 1000
    process: str = "poisson"
    # Workload mix: fraction of fast-read arrivals (read_mode=1) and of
    # large payloads among the writes/reads.
    read_fraction: float = 0.0
    large_fraction: float = 0.0
    small_payload: int = 16
    large_payload: int = 1024
    # onoff process shape: ON window / OFF window seconds.  The ON rate
    # is rate * (on_s + off_s) / on_s so the offered average holds.
    on_s: float = 0.25
    off_s: float = 0.25
    # Consensus groups: arrivals are routed by the existing ShardRouter
    # over a per-client shard key (client affinity — one client's seqs
    # stay in one group's sequence space).
    n_groups: int = 1

    def validate(self) -> None:
        if self.process not in _PROCESSES:
            raise ValueError(f"unknown arrival process {self.process!r}")
        if self.rate <= 0 or self.duration_s <= 0 or self.n_clients <= 0:
            raise ValueError("rate, duration_s and n_clients must be > 0")
        if not (0.0 <= self.read_fraction <= 1.0):
            raise ValueError("read_fraction must be in [0, 1]")
        if not (0.0 <= self.large_fraction <= 1.0):
            raise ValueError("large_fraction must be in [0, 1]")
        if self.process == "onoff" and (self.on_s <= 0 or self.off_s < 0):
            raise ValueError("onoff needs on_s > 0 and off_s >= 0")


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: WHEN (ns offset from run start — ints so
    the digest has no float-representation hazard), WHO (client index),
    WHAT (read flag + payload bytes), and WHERE (consensus group)."""

    t_ns: int
    client_idx: int
    read: bool
    payload_len: int
    group: int


class Schedule:
    """An immutable arrival sequence plus its identity digest."""

    def __init__(self, spec: LoadSpec, arrivals: Tuple[Arrival, ...]):
        self.spec = spec
        self.arrivals = arrivals

    @property
    def digest(self) -> str:
        """SHA-256 over the packed arrival tuple — byte-identical
        schedules have equal digests (the determinism test's witness)."""
        h = hashlib.sha256()
        for a in self.arrivals:
            h.update(
                _ARRIVAL_PACK.pack(
                    a.t_ns, a.client_idx, 1 if a.read else 0,
                    a.payload_len, a.group,
                )
            )
        return h.hexdigest()

    def census(self) -> Dict[str, int]:
        """Replayable traffic summary (the faultnet ``replay_counts``
        mirror): what a faithful generator MUST have fired."""
        c = {
            "arrivals": len(self.arrivals),
            "reads": 0,
            "writes": 0,
            "large": 0,
            "small": 0,
        }
        for a in self.arrivals:
            c["reads" if a.read else "writes"] += 1
            big = a.payload_len >= self.spec.large_payload
            c["large" if big else "small"] += 1
            gk = f"group_{a.group}"
            c[gk] = c.get(gk, 0) + 1
        return c


def build_schedule(spec: LoadSpec) -> Schedule:
    """Materialize the spec's schedule.  Pure: no clock, no I/O."""
    spec.validate()
    rng = random.Random(spec.seed)
    router = ShardRouter(spec.n_groups)
    # Client shard keys are deterministic strings; the router's SHA-256
    # hash spreads them across groups regardless of index distribution.
    groups = [
        router.group_for(b"loadgen-client-%d" % i)
        for i in range(spec.n_clients)
    ]
    horizon_ns = int(spec.duration_s * 1e9)
    if spec.process == "onoff":
        on_rate = spec.rate * (spec.on_s + spec.off_s) / spec.on_s
        cycle_s = spec.on_s + spec.off_s
    arrivals = []
    on_time = 0.0  # poisson: wall clock; onoff: accumulated ON time
    while True:
        # Draw-order contract (see module docstring): gap, client, read,
        # payload class — one draw each, every arrival, even when a
        # fraction is 0 or 1.
        if spec.process == "poisson":
            on_time += rng.expovariate(spec.rate)
            wall_s = on_time
        else:
            on_time += rng.expovariate(on_rate)
            # Map accumulated ON time onto the wall clock by inserting
            # the OFF gap after every completed ON window.
            cycles = int(on_time // spec.on_s)
            wall_s = cycles * cycle_s + (on_time - cycles * spec.on_s)
        t_ns = int(wall_s * 1e9)
        if t_ns >= horizon_ns:
            break
        cidx = rng.randrange(spec.n_clients)
        read = rng.random() < spec.read_fraction
        big = rng.random() < spec.large_fraction
        arrivals.append(
            Arrival(
                t_ns=t_ns,
                client_idx=cidx,
                read=read,
                payload_len=(
                    spec.large_payload if big else spec.small_payload
                ),
                group=groups[cidx],
            )
        )
    return Schedule(spec, tuple(arrivals))


def replay_census(spec: LoadSpec) -> Dict[str, int]:
    """Recompute the census from the spec alone (the seed-replay side of
    the faultnet contract).  The harness's live fired-census must equal
    this, or the generator was not faithful to the schedule."""
    return build_schedule(spec).census()
