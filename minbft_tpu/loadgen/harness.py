"""Open-loop traffic generator (ISSUE 15).

:class:`OpenLoopGenerator` drives a seeded :class:`~.arrivals.Schedule`
against a replica cluster: thousands of lightweight client identities
(own keys, own sequence spaces) multiplexed over a BOUNDED pool of real
connections, fired at their scheduled times regardless of how slow the
cluster answers — the open-loop discipline.  Latency is measured from
the SCHEDULED arrival time, so coordinated omission cannot flatter the
curve: a straggling reply is charged the full wait its user would have
experienced, not the (late) moment the generator got around to sending.
The send-origin latency is tracked alongside as the explicit
counter-factual — the regression test pins that the two diverge under an
injected stall and that the REPORTED percentiles come from the
scheduled-origin series.

Design notes:

- Requests are pre-signed before the run starts (the schedule is known
  upfront), so per-request signing cost cannot blunt the offered rate —
  the firing loop only stamps, enqueues, and sleeps until the next
  arrival.
- One pool slot = one connection per replica (``n`` real connections);
  identities map to slots round-robin.  The replica side multiplexes any
  number of client ids over one stream, so 1,000+ identities ride a
  handful of sockets.
- Replicas' BUSY shed signals are honored exactly like the product
  client: a verified-or-counted hold suppresses that request's
  retransmission until ``retry_after_ms`` passes (the request stays
  live).  Reply signature verification is OFF by default — the generator
  must stay cheap enough to saturate the cluster from one process — and
  can be enabled for end-to-end auth runs.
- The live fired-census must equal ``arrivals.replay_census(spec)``
  (checked in :meth:`OpenLoopGenerator.report`): the generator proves it
  was faithful to the seed, the faultnet ``replay_counts`` contract.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from typing import AsyncIterator, Dict, List, Optional, Sequence, Tuple

from .. import api
from ..messages import (
    Busy,
    CodecError,
    Reply,
    Request,
    authen_bytes,
    drain_multi,
    marshal,
    pack_group,
    split_group,
    split_multi,
    unmarshal,
)
from ..utils.backoff import RetransmitBackoff
from .arrivals import LoadSpec, Schedule, build_schedule

_log = logging.getLogger("minbft_tpu.loadgen")

# How long past the last scheduled arrival the run waits for stragglers
# before counting them as timeouts.
_DEFAULT_DRAIN_S = 5.0
# BUSY retry-after holds are capped like the product client's.
_MAX_BUSY_HOLD_S = 60.0


class _Identity:
    __slots__ = ("client_id", "auth", "seq")

    def __init__(self, client_id: int, auth: api.Authenticator):
        self.client_id = client_id
        self.auth = auth
        self.seq = 0


class _Pending:
    __slots__ = (
        "key", "slot", "group", "read", "threshold", "sched_s", "send_mono",
        "resolve_mono", "frame", "votes", "count_by_digest", "busy_until",
        "backoff", "next_resend",
    )

    def __init__(
        self, key, slot, group, read, threshold, sched_s, frame, backoff
    ):
        self.key = key  # (client_id, seq)
        self.slot = slot
        self.group = group
        self.read = read
        self.threshold = threshold
        self.sched_s = sched_s  # offset from run start
        self.send_mono = 0.0
        self.resolve_mono = 0.0
        self.frame = frame
        self.votes: Dict[int, None] = {}
        self.count_by_digest: Dict[bytes, int] = {}
        self.busy_until = 0.0
        self.backoff = backoff
        self.next_resend = 0.0

    @property
    def resolved(self) -> bool:
        return self.resolve_mono > 0.0


class _Slot:
    """One pool slot: per-replica outgoing queues + inbound pump tasks
    over ONE stream per replica."""

    __slots__ = ("queues", "tasks")

    def __init__(self):
        self.queues: Dict[int, asyncio.Queue] = {}
        self.tasks: list = []


class OpenLoopGenerator:
    """Drive one schedule against a cluster and report the curve point.

    ``connectors`` is the bounded connection pool: one
    :class:`api.ReplicaConnector` per slot (each slot dials one stream
    per replica).  ``authenticators`` holds one client authenticator per
    identity, parallel to ``client_ids``.
    """

    def __init__(
        self,
        spec: LoadSpec,
        n: int,
        f: int,
        client_ids: Sequence[int],
        authenticators: Sequence[api.Authenticator],
        connectors: Sequence[api.ReplicaConnector],
        retransmit_interval: Optional[float] = 0.5,
        drain_s: float = _DEFAULT_DRAIN_S,
        verify_replies: bool = False,
        schedule: Optional[Schedule] = None,
        slo_target_ms: Optional[float] = None,
    ):
        if len(client_ids) < spec.n_clients:
            raise ValueError(
                f"{len(client_ids)} identities for n_clients="
                f"{spec.n_clients}"
            )
        if len(authenticators) != len(client_ids):
            raise ValueError("client_ids and authenticators must be parallel")
        if not connectors:
            raise ValueError("need at least one pool connector")
        self.spec = spec
        self.n = n
        self.f = f
        self.schedule = schedule or build_schedule(spec)
        self._idents = [
            _Identity(cid, auth)
            for cid, auth in zip(client_ids, authenticators)
        ]
        self._by_client_id = {
            ident.client_id: ident for ident in self._idents
        }
        self._connectors = list(connectors)
        self._retransmit_interval = retransmit_interval
        self._drain_s = drain_s
        self._verify = verify_replies
        self._slots: List[_Slot] = []
        self._pending: Dict[Tuple[int, int], _Pending] = {}
        self._resolved: List[_Pending] = []
        # Fixed keys start at zero to mirror Schedule.census() exactly
        # (a zero count must compare equal, not be a missing key).
        self._fired_census: Dict[str, int] = {
            "arrivals": 0, "reads": 0, "writes": 0, "large": 0, "small": 0,
        }
        self._busy_received = 0
        self._busy_rejected = 0
        self._start_mono = 0.0
        self._fired = 0
        self._late_fire_max_s = 0.0
        # Finality budget for the report's SLO keys: explicit target, or
        # the env/config-resolved policy default (so the bench emits the
        # keys at every curve point without new plumbing).
        if slo_target_ms is None:
            from ..obs.slo import SLOPolicy

            slo_target_ms = SLOPolicy.from_env().target_ms
        self._slo_target_ms = float(slo_target_ms)

    # -- wire plumbing ------------------------------------------------------

    async def _outgoing(self, q: asyncio.Queue) -> AsyncIterator[bytes]:
        while True:
            data, _ = drain_multi(await q.get(), q)
            yield data

    async def _pump_in(self, rid: int, handler, q: asyncio.Queue) -> None:
        try:
            async for data in handler.handle_message_stream(self._outgoing(q)):
                try:
                    frames = split_multi(data)
                except CodecError:
                    continue
                for fr in frames:
                    if self.spec.n_groups > 1:
                        try:
                            _gid, fr = split_group(fr)
                        except CodecError:
                            continue
                    await self._handle_frame(rid, fr)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # Open loop: a dead stream costs that slot's votes from this
            # replica; the run keeps firing (that IS the measurement).
            _log.warning("loadgen stream to replica %d failed: %r", rid, e)

    async def _handle_frame(self, rid: int, fr: bytes) -> None:
        try:
            msg = unmarshal(fr)
        except Exception:
            return
        if isinstance(msg, Busy):
            await self._handle_busy(rid, msg)
            return
        if not isinstance(msg, Reply):
            return
        if msg.replica_id != rid:
            return
        pending = self._pending.get((msg.client_id, msg.seq))
        if pending is None or pending.resolved:
            return
        if msg.replica_id in pending.votes:
            return
        if self._verify:
            ident = self._by_client_id.get(msg.client_id)
            if ident is None:
                return
            try:
                await ident.auth.verify_message_authen_tag(
                    api.AuthenticationRole.REPLICA,
                    msg.replica_id,
                    authen_bytes(msg),
                    msg.signature,
                )
            except api.AuthenticationError:
                return
        pending.votes[msg.replica_id] = None
        digest = hashlib.sha256(
            (b"\x01" if msg.error else b"\x00") + msg.result
        ).digest()
        cnt = pending.count_by_digest.get(digest, 0) + 1
        pending.count_by_digest[digest] = cnt
        if cnt >= pending.threshold:
            pending.resolve_mono = time.monotonic()
            self._resolved.append(pending)
            self._pending.pop(pending.key, None)

    async def _handle_busy(self, rid: int, msg: Busy) -> None:
        pending = self._pending.get((msg.client_id, msg.seq))
        if pending is None or pending.resolved:
            return
        if msg.replica_id != rid:
            return
        if self._verify:
            ident = self._by_client_id.get(msg.client_id)
            if ident is None:
                return
            try:
                await ident.auth.verify_message_authen_tag(
                    api.AuthenticationRole.REPLICA,
                    msg.replica_id,
                    authen_bytes(msg),
                    msg.signature,
                )
            except api.AuthenticationError:
                self._busy_rejected += 1
                return
        self._busy_received += 1
        hold = min(max(msg.retry_after_ms, 0) / 1000.0, _MAX_BUSY_HOLD_S)
        pending.busy_until = max(
            pending.busy_until, time.monotonic() + hold
        )

    # -- run ----------------------------------------------------------------

    async def _prepare(self) -> List[Tuple[object, _Pending]]:
        """Pre-sign every scheduled request; returns (arrival, pending)
        in schedule order.  Signing happens before the clock starts, so
        host sign cost cannot throttle the offered rate."""
        prepared = []
        n_slots = len(self._connectors)
        for i, arr in enumerate(self.schedule.arrivals):
            ident = self._idents[arr.client_idx]
            ident.seq += 1
            # Payload: arrival-stamped then padded to the scheduled size.
            op = (b"load-%d-%d" % (i, arr.payload_len)).ljust(
                arr.payload_len, b"."
            )
            req = Request(
                client_id=ident.client_id,
                seq=ident.seq,
                operation=op,
                read_mode=1 if arr.read else 0,
            )
            req.signature = (
                await ident.auth.generate_message_authen_tag_async(
                    api.AuthenticationRole.CLIENT, authen_bytes(req)
                )
            )
            frame = marshal(req)
            if self.spec.n_groups > 1:
                frame = pack_group(arr.group, frame)
            pending = _Pending(
                key=(ident.client_id, req.seq),
                slot=arr.client_idx % n_slots,
                group=arr.group,
                read=arr.read,
                # fast reads need ALL n matching; writes f+1
                threshold=self.n if arr.read else self.f + 1,
                sched_s=arr.t_ns / 1e9,
                frame=frame,
                backoff=(
                    RetransmitBackoff(self._retransmit_interval)
                    if self._retransmit_interval
                    else None
                ),
            )
            prepared.append((arr, pending))
        return prepared

    async def _open_slots(self) -> None:
        loop = asyncio.get_running_loop()
        for conn in self._connectors:
            slot = _Slot()
            for rid in range(self.n):
                handler = conn.replica_message_stream_handler(rid)
                if handler is None:
                    raise ValueError(f"pool connector missing replica {rid}")
                q: asyncio.Queue = asyncio.Queue()
                slot.queues[rid] = q
                slot.tasks.append(
                    loop.create_task(self._pump_in(rid, handler, q))
                )
            self._slots.append(slot)

    def _broadcast(self, pending: _Pending) -> None:
        for q in self._slots[pending.slot].queues.values():
            q.put_nowait(pending.frame)

    def _fire(self, arr, pending: _Pending) -> None:
        now = time.monotonic()
        pending.send_mono = now
        late = now - (self._start_mono + pending.sched_s)
        if late > self._late_fire_max_s:
            self._late_fire_max_s = late
        if pending.backoff is not None:
            pending.next_resend = now + pending.backoff.next_delay()
        self._pending[pending.key] = pending
        self._broadcast(pending)
        self._fired += 1
        c = self._fired_census
        c["arrivals"] = c.get("arrivals", 0) + 1
        c["reads" if arr.read else "writes"] = (
            c.get("reads" if arr.read else "writes", 0) + 1
        )
        big = arr.payload_len >= self.spec.large_payload
        c["large" if big else "small"] = (
            c.get("large" if big else "small", 0) + 1
        )
        gk = f"group_{arr.group}"
        c[gk] = c.get(gk, 0) + 1

    async def _retransmit_sweep(self) -> None:
        """Product-client retransmission semantics at pool scale: each
        unresolved request re-broadcasts on its own capped-exponential
        ladder, EXCEPT while a BUSY hold is active (the admission
        contract — retransmitting into saturation deepens it)."""
        if self._retransmit_interval is None:
            return
        while True:
            await asyncio.sleep(min(self._retransmit_interval / 2, 0.25))
            now = time.monotonic()
            for pending in list(self._pending.values()):
                if pending.resolved or pending.backoff is None:
                    continue
                if now < pending.next_resend:
                    continue
                pending.next_resend = now + pending.backoff.next_delay()
                if now < pending.busy_until:
                    continue  # honored hold: skip this tick, ladder climbs
                if pending.read:
                    # A fast read needs ALL n replies to MATCH; votes
                    # sampled across concurrent write commits can mix
                    # states and would never converge — each retry is a
                    # fresh all-n sample.
                    pending.votes.clear()
                    pending.count_by_digest.clear()
                self._broadcast(pending)

    async def run(self) -> dict:
        """Execute the schedule; returns :meth:`report`."""
        prepared = await self._prepare()
        await self._open_slots()
        sweeper = asyncio.get_running_loop().create_task(
            self._retransmit_sweep()
        )
        try:
            self._start_mono = time.monotonic()
            for arr, pending in prepared:
                target = self._start_mono + pending.sched_s
                delay = target - time.monotonic()
                if delay > 0:
                    await asyncio.sleep(delay)
                # NO wait on stragglers: fire at (or as close as the
                # event loop allows to) the scheduled instant.
                self._fire(arr, pending)
            deadline = time.monotonic() + self._drain_s
            while self._pending and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
        finally:
            sweeper.cancel()
            try:
                await sweeper
            except asyncio.CancelledError:
                pass
            for slot in self._slots:
                for t in slot.tasks:
                    t.cancel()
            for slot in self._slots:
                await asyncio.gather(*slot.tasks, return_exceptions=True)
            for conn in self._connectors:
                close = getattr(conn, "close", None)
                if close is not None:
                    try:
                        await close()
                    except Exception:
                        pass
        return self.report()

    # -- reporting ----------------------------------------------------------

    def _percentiles(self, series: List[float]) -> Tuple[float, float]:
        if not series:
            return 0.0, 0.0
        s = sorted(series)

        def pct(q: float) -> float:
            idx = min(len(s) - 1, max(0, int(q * len(s) + 0.5) - 1))
            return s[idx]

        return pct(0.50), pct(0.99)

    def report(self) -> dict:
        """The curve point: offered rate in, goodput + latency + shed
        visibility out.  ``census_ok`` is the faultnet-style replay
        check: live fired-census == seed-recomputed census."""
        sched_lat = []
        send_lat = []
        for p in self._resolved:
            sched_lat.append(
                p.resolve_mono - (self._start_mono + p.sched_s)
            )
            send_lat.append(p.resolve_mono - p.send_mono)
        p50, p99 = self._percentiles(sched_lat)
        send_p50, send_p99 = self._percentiles(send_lat)
        # Finality series (obs/slo.py semantics): every FIRED request is
        # charged from its SCHEDULED arrival; still-unresolved requests
        # contribute their age-so-far — a finite, honest lower bound that
        # diverges from p99_ms exactly under overload, where dropping
        # timeouts would flatter the tail (coordinated omission again,
        # one level up).
        now = time.monotonic()
        finality = list(sched_lat)
        for p in self._pending.values():
            finality.append(now - (self._start_mono + p.sched_s))
        _, finality_p99 = self._percentiles(finality)
        target_s = self._slo_target_ms / 1e3
        good = sum(1 for lat in sched_lat if lat <= target_s)
        resolved = len(self._resolved)
        expected = self.schedule.census()
        # Wall-clock-honest committed rate: resolved over the span to the
        # LAST resolve.  Under overload the schedule window ends before
        # the backlog drains, so resolved/duration_s would exceed the
        # cluster's real capacity — this is the curve's goodput axis.
        last = max(
            (p.resolve_mono for p in self._resolved),
            default=self._start_mono,
        )
        wall_s = max(last - self._start_mono, self.spec.duration_s)
        return {
            "process": self.spec.process,
            "offered_per_sec": round(self.spec.rate, 3),
            "duration_s": self.spec.duration_s,
            "n_clients": self.spec.n_clients,
            "n_groups": self.spec.n_groups,
            "pool_connections": len(self._connectors) * self.n,
            "arrivals": len(self.schedule.arrivals),
            "fired": self._fired,
            "resolved": resolved,
            "timeouts": self._fired - resolved,
            "goodput_per_sec": round(resolved / self.spec.duration_s, 3),
            "wall_s": round(wall_s, 3),
            "sustained_per_sec": round(resolved / wall_s, 3),
            "p50_ms": round(p50 * 1e3, 3),
            "p99_ms": round(p99 * 1e3, 3),
            # SLO surface (perf/SLO.md): unresolved requests count as
            # breached, so good_fraction is over FIRED, not resolved.
            "slo_target_ms": round(self._slo_target_ms, 3),
            "finality_p99_ms": round(finality_p99 * 1e3, 3),
            "slo_good_fraction": round(good / max(self._fired, 1), 6),
            # Send-origin counterfactual (coordinated-omission witness):
            # the REPORTED p50/p99 above are scheduled-origin.
            "send_p50_ms": round(send_p50 * 1e3, 3),
            "send_p99_ms": round(send_p99 * 1e3, 3),
            "late_fire_max_ms": round(self._late_fire_max_s * 1e3, 3),
            "busy_received": self._busy_received,
            "busy_rejected": self._busy_rejected,
            "census": dict(self._fired_census),
            "census_ok": self._fired_census == expected,
            "schedule_digest": self.schedule.digest,
            "seed": self.spec.seed,
        }

    def sched_doc(self) -> dict:
        """Scheduled-origin metadata doc for :func:`obs.slo.breach_report`:
        per-request finality from the SCHEDULED arrival, keyed
        ``"cid:seq"``.  Feeding this alongside replica trace dumps
        upgrades breach classification from recv-origin to
        scheduled-origin (the coordinated-omission rule of perf/LOAD.md
        applied to the forensics path, not just the percentile path)."""
        sched_lat_ns = {}
        for p in self._resolved:
            cid, seq = p.key  # (client_id, seq) — a public identity pair
            sched_lat_ns[f"{cid}:{seq}"] = int(
                (p.resolve_mono - (self._start_mono + p.sched_s)) * 1e9
            )
        return {
            "kind": "loadgen",
            "slo_target_ms": self._slo_target_ms,
            "schedule_digest": self.schedule.digest,
            "sched_lat_ns": sched_lat_ns,
        }

    def slo_ring(self, interval_s: float = 1.0):
        """Replay the run's good/breached classifications into a
        :class:`~minbft_tpu.obs.timeseries.TimeSeries` ring, so
        :func:`obs.slo.burn_rates` reads post-hoc burn exactly as a
        live sampler would have.  Ring slots are wall-clock (the
        TimeSeries convention), so monotonic resolve stamps are shifted
        by the current mono->wall offset; still-unresolved fired
        requests land as breached in the current (newest) slot."""
        from ..obs.timeseries import TimeSeries

        span = time.monotonic() - self._start_mono if self._start_mono else 0
        ts = TimeSeries(
            interval_s=interval_s,
            capacity=max(512, int(span / interval_s) + 64),
        )
        wall_off = time.time() - time.monotonic()
        target_s = self._slo_target_ms / 1e3
        for p in self._resolved:
            lat = p.resolve_mono - (self._start_mono + p.sched_s)
            ts.record(
                "slo_good" if lat <= target_s else "slo_breached",
                1,
                "rate",
                t=p.resolve_mono + wall_off,
            )
        for p in self._pending.values():
            ts.record("slo_breached", 1, "rate")
        return ts
