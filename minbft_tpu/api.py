"""External-module API contracts.

Mirrors the reference ``api`` package (reference api/api.go:26-159): the core
protocol engine sees *only* these interfaces; concrete crypto, transport,
config, and state-machine implementations are plugged in from outside
(reference README.md:460-478 design stance).  The asyncio re-design changes
two things relative to the Go contracts:

- Message streams are ``AsyncIterator[bytes]`` instead of Go channels
  (reference api/api.go:80-91 ``MessageStreamHandler.HandleMessageStream``).
- ``Authenticator.verify_message_authen_tag`` is a **coroutine**: the TPU
  authenticator accumulates concurrent verifications into one batched XLA
  kernel dispatch, so verification must be awaitable (the reference verifies
  serially and synchronously, sample/authentication/crypto.go:79-89 — this
  is the north-star restructuring).
"""

from __future__ import annotations

import abc
import enum
from typing import AsyncIterator, Awaitable, Optional


class AuthenticationRole(enum.Enum):
    """Which key family authenticates a message
    (reference api/authentication.go roles; api/api.go:99-120)."""

    REPLICA = "replica"  # replica signatures (REPLY, REQ-VIEW-CHANGE)
    CLIENT = "client"  # client signatures (REQUEST)
    USIG = "usig"  # USIG UI certificates (PREPARE, COMMIT)


class AuthenticationError(Exception):
    """Tag failed to verify."""


class ReadOnlyQueryError(Exception):
    """A read-only request failed cluster-side: a reply-quorum of
    replicas signed error replies (consumer lacks query() support, or
    query() raised on the operation).  Distinguished from a timeout —
    the cluster is healthy and answered; the READ is what failed."""


class EmbeddedRequestAuthError(AuthenticationError):
    """A UI-certified proposal (PREPARE/COMMIT) embeds a REQUEST whose
    client authentication fails locally while the proposal's own UI is
    valid.  Under signature schemes every correct replica agrees on the
    check, but under per-pair MAC authentication a faulty client can
    craft a MAC vector that verifies at the primary and fails at a
    backup — the backup then cannot capture the primary's UI counter and
    every later message from that primary parks behind the gap.  Raised
    distinctly so message handling can demand a view change (depose the
    wedged primary) instead of stalling silently."""


class Authenticator(abc.ABC):
    """Message authentication provider (reference api/api.go:93-132).

    ``generate`` is synchronous (local signing, serial per-key by nature —
    the USIG counter must increment atomically).  ``verify`` is awaitable so
    implementations can batch many in-flight verifications into one TPU
    kernel dispatch (see minbft_tpu/parallel/engine.py).

    ``generate_message_authen_tag_async`` is the batch-aware sign surface:
    implementations that can co-batch many in-flight signatures (the
    engine's sign queue over the fixed-base comb kernels) override it for
    the CLIENT/REPLICA roles; the default delegates to the synchronous
    path.  The USIG role must stay on the synchronous path in every
    implementation — the UI counter is incremented only after the
    certificate exists (reference usig/sgx/enclave/usig.c:66-69), an
    inherently serial per-key discipline that batching would break.
    """

    @abc.abstractmethod
    def generate_message_authen_tag(
        self, role: AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        """Sign/certify ``msg`` under own key for ``role`` -> tag bytes.

        ``audience``: the recipient principal id when the tag is
        recipient-specific (a MAC-scheme REPLY is keyed to one client);
        -1 = everyone (signatures, MAC vectors over all replicas).
        Signature-scheme implementations ignore it."""

    async def generate_message_authen_tag_async(
        self, role: AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        """Awaitable tag generation for callers already running on the
        event loop (client REQUEST signing, replica REPLY emission).
        Default: the synchronous path, unchanged semantics."""
        return self.generate_message_authen_tag(role, msg, audience)

    @abc.abstractmethod
    async def verify_message_authen_tag(
        self, role: AuthenticationRole, peer_id: int, msg: bytes, tag: bytes
    ) -> None:
        """Verify ``tag`` over ``msg`` against ``peer_id``'s key for
        ``role``; raises :class:`AuthenticationError` on failure."""

    @property
    def supports_batch_verify(self) -> bool:
        """True when :meth:`verify_message_authen_tags` lands a bundle on
        a shared batching engine whose in-flight coalescing makes a
        fire-and-forget SEED call free for the per-message verifications
        that follow (the bundle-ingest runtime's preverify).  False — the
        default — means batch verification is just a serial loop, and
        seeding it would verify everything twice."""
        return False

    async def verify_message_authen_tags(
        self, role: AuthenticationRole, items
    ) -> list:
        """Batch verification surface for the bundle-ingest runtime:
        ``items = [(peer_id, msg, tag), ...]`` -> one entry per item,
        ``None`` on success or the :class:`AuthenticationError` VALUE on
        failure (errors are item-wise — one bad tag must never poison a
        bundle).  The default verifies serially through
        :meth:`verify_message_authen_tag`; implementations with a batch
        engine (the sample authenticator) override it to land the whole
        bundle in one engine call."""
        out = []
        for peer_id, msg, tag in items:
            try:
                await self.verify_message_authen_tag(role, peer_id, msg, tag)
                out.append(None)
            except AuthenticationError as e:
                out.append(e)
        return out


class Configer(abc.ABC):
    """Protocol configuration provider (reference api/api.go:34-53)."""

    @property
    @abc.abstractmethod
    def n(self) -> int:
        """Total number of replicas."""

    @property
    @abc.abstractmethod
    def f(self) -> int:
        """Maximum tolerated faulty replicas (n >= 2f+1)."""

    @property
    def checkpoint_period(self) -> int:
        """Reserved (reference roadmap README.md:492-493)."""
        return 0

    @property
    def logsize(self) -> int:
        """Reserved (reference roadmap README.md:492-493)."""
        return 0

    @property
    def timeout_request(self) -> float:
        """Seconds before a pending request triggers view-change demand."""
        return 2.0

    @property
    def timeout_prepare(self) -> float:
        """Seconds a backup waits for its request to be prepared before
        forwarding it to the primary."""
        return 1.0


class MessageStreamHandler(abc.ABC):
    """Bidirectional stream of serialized messages
    (reference api/api.go:80-91): consume an async stream of request bytes,
    yield reply bytes.  Eventual delivery / ordering caveats as documented
    at reference api/api.go:69-78."""

    @abc.abstractmethod
    def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        ...


class ConnectionHandler(abc.ABC):
    """Server side of a connection: resolves per-kind stream handlers
    (reference api/api.go:55-67)."""

    @abc.abstractmethod
    def peer_message_stream_handler(self) -> MessageStreamHandler:
        ...

    @abc.abstractmethod
    def client_message_stream_handler(self) -> MessageStreamHandler:
        ...


class ReplicaConnector(abc.ABC):
    """Client side of connections to replicas (reference api/api.go:64-78)."""

    @abc.abstractmethod
    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[MessageStreamHandler]:
        """Handler speaking to ``replica_id``; None if unknown."""


class RequestConsumer(abc.ABC):
    """The replicated state machine (reference api/api.go:134-153)."""

    @abc.abstractmethod
    def deliver(self, operation: bytes) -> "Awaitable[bytes]":
        """Execute an ordered operation; awaitable resolves to the result
        bytes (reference: Deliver returns a result channel,
        sample/requestconsumer/simpleledger.go:146-151)."""

    @abc.abstractmethod
    def state_digest(self) -> bytes:
        """Digest of the current application state
        (reference api/api.go:148-152)."""

    def snapshot(self) -> bytes:
        """Serialized application state for checkpoint state transfer.
        Must round-trip: ``install_snapshot(snapshot())`` on a fresh
        instance yields the same ``state_digest()``.  Optional — but
        without it the replica keeps its full message log (checkpoints
        still stabilize; log truncation is disabled, because dropped
        history could strand a lagging replica that then has no snapshot
        to catch up from)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support state snapshots"
        )

    def query(self, operation: bytes) -> "Awaitable[bytes]":
        """Answer a READ-ONLY operation from current committed state,
        without ordering it (the reference lists read-only requests as a
        roadmap item, README.md:503-504).  Must be deterministic in the
        state: replicas at the same committed prefix return the same
        bytes, because the client accepts a fast read only when ALL n
        replies match (the n=2f+1 read-quorum bound: any smaller quorum
        cannot guarantee intersection with a write quorum in a correct
        replica).  Optional — replicas whose consumer lacks it drop
        read-only requests, and the client falls back to an ordered
        request.

        Capability probing: the core uses :func:`consumer_supports_query`
        — a consumer that DELEGATES query to a wrapped consumer (metrics
        shims, access-control decorators) should set the
        ``supports_query`` attribute explicitly, since the structural
        did-you-override-it fallback cannot see through delegation."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support read-only queries"
        )

    def install_snapshot(self, data: bytes) -> None:
        """Atomically replace the application state with a snapshot.
        Implementations must validate internal integrity and leave the
        prior state untouched on failure — the caller verifies
        ``snapshot_digest`` against an f+1-certified checkpoint digest
        before installing."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support state snapshots"
        )

    def snapshot_digest(self, data: bytes) -> bytes:
        """The ``state_digest()`` the snapshot would produce once
        installed, computed WITHOUT mutating local state — lets a receiver
        check a transferred snapshot against a certified checkpoint digest
        before committing to it.  Raises ``ValueError`` on a malformed
        snapshot."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support state snapshots"
        )


def consumer_supports_query(consumer: "RequestConsumer") -> bool:
    """Feature-probe a consumer's fast-read capability (ADVICE low-#3).

    A ``supports_query`` attribute wins outright — that is how a
    delegating wrapper (whose ``query`` override forwards to a wrapped
    consumer) keeps the fast-read path, and how a consumer can
    explicitly opt out.  Absent that, fall back to the structural probe:
    did the class override :meth:`RequestConsumer.query` at all."""
    flag = getattr(consumer, "supports_query", None)
    if flag is not None:
        return bool(flag)
    meth = getattr(type(consumer), "query", None)
    if meth is None:
        # Duck-typed consumer (e.g. a __getattr__ delegator that never
        # subclassed RequestConsumer): probe the instance.
        return callable(getattr(consumer, "query", None))
    return meth is not RequestConsumer.query


class Replica(abc.ABC):
    """A running replica instance (reference api/api.go:155-159)."""

    @abc.abstractmethod
    def peer_message_stream_handler(self) -> MessageStreamHandler:
        ...

    @abc.abstractmethod
    def client_message_stream_handler(self) -> MessageStreamHandler:
        ...

    @abc.abstractmethod
    async def start(self) -> None:
        """Connect to peers and start processing."""

    @abc.abstractmethod
    async def stop(self) -> None:
        """Stop background tasks."""
