"""Multi-group replica runtime: G consensus cores, one transport, one engine.

Layout (ROADMAP item 2; the DSig cross-flow amortization argument):

- :class:`GroupRuntime` hosts G independent :class:`~minbft_tpu.core.
  replica._Replica` cores behind ONE listener and ONE set of peer
  connections.  Each core owns its group's full protocol state — view,
  sequence spaces, USIG counter space (a per-group authenticator
  instance), message log, checkpoints — exactly as if it ran alone.
- The wire carries a transport-level group envelope
  (:func:`minbft_tpu.messages.codec.pack_group`; group 0 stays bare, so
  a G=1 runtime is wire-identical to the ungrouped one).  The envelope
  is framing, never signed: :class:`GroupAuthenticator` domain-separates
  the SIGNATURES per group instead, so a frame re-tagged to another
  group can never verify there.
- **Shared engine coalescing is by construction, not by scheduling**:
  every core's authenticator lands verify/sign traffic in the SAME
  ``parallel/engine`` queue instances, and the grouped client stream
  runs ONE bundle-ingest drain — a tick's decoded bundle spans groups,
  and each group's ``preverify_requests`` seed fires in the same loop
  turn, so the engine's batch fill rises with G at fixed per-group load
  (pinned by tests/test_groups.py).

Concurrency: every mux/demux structure below is confined to the owning
event loop (LD-spec'd in tools/analyze/project.py).  Per-group queues
are BOUNDED and drop-on-full — one wedged group may lose frames (its
gap/idle watchdogs heal via redial replay) but can never head-of-line
block another group's traffic on the shared channel (the group-isolation
contract, also pinned by tests).
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Dict, List, Optional, Set, Tuple

from .. import api
from ..core.admission import AdmissionController, admission_enabled
from ..core.message_handling import (
    _BundleIngestor,
    _ConcurrentStreamProcessor,
    _TurnSequencer,
    bundle_ingest_enabled,
)
from ..core.replica import _Replica
from ..messages import (
    GROUP_MAX,
    CodecError,
    Request,
    drain_multi,
    marshal,
    pack_group,
    split_group,
    split_group_batch,
    split_multi,
    unmarshal_batch,
)
from ..messages.codec import _TAG_HELLO, _TAG_MULTI
from ..obs import trace as obs_trace

# codec._TAG_MULTI: the grouped client drain must split one more
# container level — the client's own coalescing rides inside the group
# envelope.  Imported (not re-declared) so a tag renumbering in the
# codec, which owns the tag space, can never silently desync the demux.
_MULTI_TAG = _TAG_MULTI

# Frames buffered per group between the shared channel and one group's
# consumer.  Bounded + drop-on-full: a full queue means that group's
# pipeline is wedged or saturated, and blocking the SHARED demux on it
# would stall every other group (the isolation contract).  Dropped
# certified traffic heals through the per-group gap/idle redial
# watchdogs, dropped requests through client retransmission.
_GROUP_RX_BOUND = 1024

_EOF = object()


class GroupAuthenticator(api.Authenticator):
    """Per-group signature domain separation over one base authenticator.

    The group envelope is transport framing — unsigned by design (it
    must be strippable before decode).  Without domain separation, a
    REQUEST/REPLY/HELLO signed for group g would verify verbatim in
    group g' whenever the two groups share key material (the keystore
    deployment: one key per replica, one per client), and per-group
    sequence spaces would then execute the replay in the wrong shard.
    Prefixing every signed byte string with the group id closes that:
    both sides wrap symmetrically, so in-group verification is
    unchanged and cross-group replays fail as bad signatures.

    Group 0 keeps the EMPTY prefix: its signatures — like its wire
    frames — are byte-identical to the ungrouped runtime's, so a plain
    client can talk to group 0 of a grouped cluster.

    The USIG role passes through with the same prefix; counter state
    lives in the BASE authenticator, which is why the runtime requires
    one base instance per group (shared counters would break per-group
    UI contiguity).  Unknown attributes (``reset_usig_epoch``,
    ``allow_epoch_capture_from``, ``supports_query`` probes) delegate to
    the base."""

    def __init__(self, base: api.Authenticator, group: int):
        self._base = base
        self.group = int(group)
        self._prefix = b"" if group == 0 else b"minbft-group:%d|" % group

    def _msg(self, msg: bytes) -> bytes:
        p = self._prefix
        return msg if not p else p + msg

    def generate_message_authen_tag(
        self, role: api.AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        return self._base.generate_message_authen_tag(
            role, self._msg(msg), audience
        )

    async def generate_message_authen_tag_async(
        self, role: api.AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        return await self._base.generate_message_authen_tag_async(
            role, self._msg(msg), audience
        )

    async def verify_message_authen_tag(
        self, role: api.AuthenticationRole, peer_id: int, msg: bytes, tag: bytes
    ) -> None:
        await self._base.verify_message_authen_tag(
            role, peer_id, self._msg(msg), tag
        )

    @property
    def supports_batch_verify(self) -> bool:
        return self._base.supports_batch_verify

    async def verify_message_authen_tags(
        self, role: api.AuthenticationRole, items
    ) -> list:
        return await self._base.verify_message_authen_tags(
            role, [(p, self._msg(m), t) for p, m, t in items]
        )

    def __getattr__(self, name):
        return getattr(self._base, name)


# ---------------------------------------------------------------------------
# Shared-channel mux: one physical stream per destination, G logical
# per-group streams over it.


class _SharedChannel:
    """ONE physical stream to one destination, carrying every group's
    logical stream as group-tagged frames.

    Dial side of the shared transport: the first logical attach opens
    the physical stream (a driver task that demuxes incoming frames
    into bounded per-group queues and pumps a shared tx queue out,
    ``drain_multi``-coalescing across groups); later attaches ride it.
    When the physical stream dies, every logical consumer sees EOF and
    its own redial loop re-attaches — the first re-attach redials the
    physical stream.

    A group-level teardown (the gap or idle watchdog closing its
    logical stream) leaves the physical stream ALONE — one chaotic
    group redialing in a storm must never churn the channel every other
    group shares (the isolation contract; an early design that reset
    the physical stream on detach measurably starved healthy groups
    under the chaos soak).  The re-attach's fresh HELLO restarts the
    group's server-side subscription instead — see
    :class:`_GroupedPeerStreamHandler`'s HELLO-restart rule."""

    def __init__(
        self,
        handler: api.MessageStreamHandler,
        log: logging.Logger,
    ):
        self._handler = handler
        self._log = log
        self._tx: Optional[asyncio.Queue] = None
        self._rx: Dict[int, asyncio.Queue] = {}
        self._driver: Optional[asyncio.Task] = None
        self._closed = False

    def _ensure_driver(self) -> None:
        if self._driver is None or self._driver.done():
            tx: asyncio.Queue = asyncio.Queue()
            self._tx = tx
            self._driver = asyncio.get_running_loop().create_task(
                self._drive(tx)
            )

    async def _drive(self, tx: asyncio.Queue) -> None:
        async def phys_out() -> AsyncIterator[bytes]:
            while True:
                data, _ = drain_multi(await tx.get(), tx)
                yield data

        try:
            async for data in self._handler.handle_message_stream(phys_out()):
                try:
                    frames = split_multi(data)
                except CodecError as e:
                    self._log.warning("shared channel: bad frame: %s", e)
                    continue
                for fr in frames:
                    try:
                        gid, inner = split_group(fr)
                    except CodecError as e:
                        self._log.warning("shared channel: bad envelope: %s", e)
                        continue
                    q = self._rx.get(gid)
                    if q is None:
                        continue  # group not attached (or unknown): drop
                    try:
                        q.put_nowait(inner)
                    except asyncio.QueueFull:
                        # Group isolation: a wedged group loses ITS
                        # frames, never the channel (redial replay /
                        # retransmission heal the loss).
                        self._log.warning(
                            "shared channel: group %d rx full, dropping", gid
                        )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # transport failure: logical redials recover
            self._log.warning("shared channel failed: %r", e)
        finally:
            for q in self._rx.values():
                try:
                    q.put_nowait(_EOF)
                except asyncio.QueueFull:
                    # The consumer is parked mid-drain, not in get(): it
                    # re-checks the driver on its next get and exits.
                    pass

    async def _pump_out(
        self, gid: int, outgoing: AsyncIterator[bytes], tx: asyncio.Queue
    ) -> None:
        try:
            async for fr in outgoing:
                await tx.put(pack_group(gid, fr))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._log.warning("group %d outgoing pump failed: %r", gid, e)

    def _attach(self, gid: int) -> asyncio.Queue:
        """Register group ``gid``'s rx queue (sync — loop-atomic with the
        driver's demux by construction)."""
        q: asyncio.Queue = asyncio.Queue(maxsize=_GROUP_RX_BOUND)
        self._rx[gid] = q
        return q

    def _detach(self, gid: int, q: asyncio.Queue) -> None:
        """Drop ``gid``'s registration iff it is still ``q`` — a redial
        may have re-attached a fresh queue under the same gid."""
        if self._rx.get(gid) is q:
            del self._rx[gid]

    async def logical(
        self, gid: int, outgoing: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        """Group ``gid``'s logical stream over this channel (the body of
        its :class:`_GroupStreamHandler`)."""
        if self._closed:
            return
        self._ensure_driver()
        driver = self._driver
        q = self._attach(gid)
        pump = asyncio.get_running_loop().create_task(
            self._pump_out(gid, outgoing, self._tx)
        )
        try:
            while True:
                if q.empty() and driver.done():
                    return  # EOF sentinel was dropped by a full queue
                fr = await q.get()
                if fr is _EOF:
                    return
                yield fr
        finally:
            pump.cancel()
            pump.add_done_callback(lambda t: t.cancelled() or t.exception())
            self._detach(gid, q)

    def _shutdown(self) -> Optional[asyncio.Task]:
        """Sync half of :meth:`close`: latch closed, cancel and hand back
        the driver (loop-atomic — no attach can interleave)."""
        self._closed = True
        driver, self._driver = self._driver, None
        if driver is not None:
            driver.cancel()
        return driver

    async def close(self) -> None:
        driver = self._shutdown()
        if driver is not None:
            try:
                await driver
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass


class _GroupStreamHandler(api.MessageStreamHandler):
    def __init__(self, channel: _SharedChannel, gid: int):
        self._channel = channel
        self._gid = gid

    def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        return self._channel.logical(self._gid, in_stream)


class _GroupConnector(api.ReplicaConnector):
    """One group's view of the shared mux: an ordinary ReplicaConnector
    whose streams are logical sub-streams of the per-destination shared
    channels — the group cores (and inner clients) use it unchanged."""

    def __init__(self, mux: "SharedChannelMux", gid: int):
        self._mux = mux
        self._gid = gid

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        ch = self._mux.channel(replica_id)
        if ch is None:
            return None
        return _GroupStreamHandler(ch, self._gid)


class SharedChannelMux:
    """Per-destination :class:`_SharedChannel` registry over one real
    connector — the dial side of the shared transport (peer dials in
    :class:`GroupRuntime`, replica dials in
    :class:`~minbft_tpu.groups.router.MultiGroupClient`)."""

    def __init__(
        self,
        connector: api.ReplicaConnector,
        log: Optional[logging.Logger] = None,
    ):
        self._connector = connector
        self._log = log or logging.getLogger("minbft.groups.mux")
        self._channels: Dict[int, _SharedChannel] = {}

    def group_connector(self, gid: int) -> api.ReplicaConnector:
        return _GroupConnector(self, gid)

    def channel(self, dest_id: int) -> Optional[_SharedChannel]:
        ch = self._channels.get(dest_id)
        if ch is None:
            handler = self._connector.replica_message_stream_handler(dest_id)
            if handler is None:
                return None
            ch = _SharedChannel(handler, self._log)
            self._channels[dest_id] = ch
        return ch

    def seal(self) -> None:
        """Refuse new logical attaches/driver starts — called before a
        multi-core teardown so one core's stream closure (which resets
        live shared channels by design) cannot race the next core's
        redial loop into opening fresh physical streams mid-shutdown."""
        for ch in self._channels.values():
            ch._closed = True

    def _drain_channels(self) -> List[_SharedChannel]:
        """Sync half of :meth:`close`: empty the registry loop-atomically
        so no task can dial a drained entry mid-teardown."""
        chans = list(self._channels.values())
        self._channels.clear()
        return chans

    async def close(self) -> None:
        for ch in self._drain_channels():
            await ch.close()


# ---------------------------------------------------------------------------
# Server side: demux one incoming stream to per-group cores.


# HELLO's wire tag (codec._TAG_HELLO, imported above): the grouped peer
# demux peeks ONE byte to spot a logical redial — see the restart rule
# below.
_HELLO_TAG = _TAG_HELLO


class _GroupedPeerStreamHandler(api.MessageStreamHandler):
    """Server side of a shared peer connection: demux group-tagged
    frames to each group core's real
    :class:`~minbft_tpu.core.message_handling.PeerStreamHandler` (HELLO
    handshake, broadcast-log subscription and all), and merge their
    output streams back with group tags — one physical stream carries G
    broadcast logs.

    **HELLO-restart rule**: a fresh HELLO for a group that already has a
    live sub-stream means the dialer's LOGICAL stream redialed (gap/idle
    watchdog) while the shared physical stream stayed up — the old
    subscription cannot serve the replay the watchdog redialed for, so
    the sub-stream is torn down and restarted from the new HELLO (its
    ``resume_counter`` scopes the replay).  The dialer's peer-stream
    direction carries nothing but HELLOs, so the one-byte peek cannot
    misfire on protocol traffic; a Byzantine peer spamming HELLOs only
    churns its own sub-stream (HELLO replay is harmless by the
    messages.Hello invariant)."""

    def __init__(self, runtime: "GroupRuntime"):
        self._rt = runtime

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        rt = self._rt
        out: asyncio.Queue = asyncio.Queue()
        subs: Dict[int, asyncio.Queue] = {}
        gtasks: Dict[int, asyncio.Task] = {}
        loop = asyncio.get_running_loop()

        def start_group(gid: int) -> Optional[asyncio.Queue]:
            core = rt.core_or_none(gid)
            if core is None:
                rt.log.warning("peer stream for unknown group %d dropped", gid)
                return None
            in_q: asyncio.Queue = asyncio.Queue(maxsize=_GROUP_RX_BOUND)
            subs[gid] = in_q

            async def gen() -> AsyncIterator[bytes]:
                while True:
                    fr = await in_q.get()
                    if fr is _EOF:
                        return
                    yield fr

            handler = core.peer_message_stream_handler()

            async def run() -> None:
                try:
                    async for data in handler.handle_message_stream(gen()):
                        await out.put(pack_group(gid, data))
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # One group's handler failing (bad HELLO, auth
                    # error) costs that group's sub-stream only.
                    rt.log.warning("group %d peer sub-stream failed: %r", gid, e)

            gtasks[gid] = loop.create_task(run())
            return in_q

        def restart_group(gid: int) -> Optional[asyncio.Queue]:
            task = gtasks.pop(gid, None)
            if task is not None:
                task.cancel()
            subs.pop(gid, None)
            return start_group(gid)

        async def demux() -> None:
            async for data in in_stream:
                try:
                    frames = split_multi(data)
                except CodecError as e:
                    rt.log.warning("grouped peer stream: bad frame: %s", e)
                    continue
                for fr in frames:
                    try:
                        gid, inner = split_group(fr)
                    except CodecError as e:
                        rt.log.warning("grouped peer stream: bad envelope: %s", e)
                        continue
                    q = subs.get(gid)
                    if q is None:
                        q = start_group(gid)
                        if q is None:
                            continue
                    elif inner and inner[0] == _HELLO_TAG:
                        # logical redial: restart from this HELLO
                        q = restart_group(gid)
                        if q is None:
                            continue
                    elif gtasks[gid].done():
                        # dead sub-stream, non-HELLO frame: the dialer's
                        # watchdogs will redial with a HELLO — drop.
                        continue
                    try:
                        q.put_nowait(inner)
                    except asyncio.QueueFull:
                        # isolation: drop this group's frame, never block
                        core = rt.core_or_none(gid)
                        if core is not None:
                            core.handlers.metrics.inc("messages_dropped")

        demux_task = loop.create_task(demux())
        try:
            while True:
                fr = await out.get()
                data, _ = drain_multi(fr, out)
                yield data
        finally:
            # Cancel-and-await: a demux() failure (not just cancellation)
            # re-raises here instead of rotting as an unretrieved task
            # exception.
            demux_task.cancel()
            for t in gtasks.values():
                t.cancel()
            try:
                await demux_task
            except asyncio.CancelledError:
                pass


class _GroupBundleIngestor(_BundleIngestor):
    """The grouped client stream's SHARED rx drain: one pump + one tick
    loop for the whole stream, so a tick's bundle spans groups.

    A tick strips the group envelopes with one vectorized classify
    (``split_group_batch``), decodes EVERY group's frames in ONE
    ``unmarshal_batch`` call, then per group seeds the engine verify
    queue (``preverify_requests``) and fans out — all G seeds fire in
    the same loop turn, before any per-message validation awaits, so
    the whole cross-group bundle lands in the shared ``_SchemeQueue``
    pending set ahead of one flush decision.  THIS is where verify
    batch fill rises with G by construction."""

    def __init__(self, runtime: "GroupRuntime", state, on_error):
        # The anchor (group 0) handlers only receive the base class's
        # stream-level accounting (pump errors); per-group metrics ride
        # the per-group handlers below.
        super().__init__(runtime.anchor_handlers, on_error, submit=None)
        self._rt = runtime
        self._state = state  # gid -> per-group stream state (or None)

    async def _ingest(self, frames: list) -> None:
        if not frames:
            return
        gids: List[int] = []
        inners: List[bytes] = []
        for gid, inner in split_group_batch(frames):
            if isinstance(gid, CodecError):
                self._on_error(gid)
                continue
            # The envelope wraps a LOGICAL transport frame: the client's
            # own drain_multi coalescing rides INSIDE it (the mux's
            # physical coalescing was already split by the base tick
            # loop), so one more container level can appear here.
            if inner and inner[0] == _MULTI_TAG:
                try:
                    sub = split_multi(inner)
                except CodecError as e:
                    self._on_error(e)
                    continue
                gids.extend([gid] * len(sub))
                inners.extend(sub)
            else:
                gids.append(gid)
                inners.append(inner)
        if not inners:
            return
        per: Dict[int, list] = {}
        for gid, m in zip(gids, unmarshal_batch(inners)):
            if isinstance(m, CodecError):
                self._on_error(m)
            else:
                per.setdefault(gid, []).append(m)
        # Seed EVERY group's engine checks first (same loop turn — the
        # cross-group coalescing point), then fan out per group.
        states = []
        for gid, msgs in per.items():
            st = self._state(gid)
            if st is None:
                self._rt.log.warning(
                    "client bundle for unknown group %d dropped (%d frames)",
                    gid,
                    len(msgs),
                )
                continue
            h = st.h
            h.metrics.observe_ingest(len(msgs))
            tr = h.trace
            if tr is not None:
                for m in msgs:
                    if isinstance(m, Request):
                        tr.note(obs_trace.R_INGEST, m.client_id, m.seq)
            sl = h.slo
            if sl is not None:
                for m in msgs:
                    if isinstance(m, Request):
                        sl.arrive(m.client_id, m.seq)
            h.preverify_requests(msgs)
            states.append((st, msgs))
        for st, msgs in states:
            for m in msgs:
                # Drop-on-saturation, never block: a wedged group's full
                # processor sheds its own messages (client retransmission
                # heals), the shared tick loop keeps draining the other
                # groups — the isolation contract, at the handler layer.
                # With admission control on, the shed is signaled (signed
                # group-tagged BUSY) instead of silent.
                if st.adm is not None:
                    await st.adm.submit_msg(m)
                elif not await st.proc.try_submit_msg(m):
                    st.h.metrics.inc("messages_dropped")
                    st.h.log.warning(
                        "group processor saturated, dropping client message"
                    )


class _GroupClientState:
    """Per-group slice of one grouped client stream: the group's
    handlers, its arrival-order sequencer, and its bounded concurrent
    processor (exactly the trio the ungrouped ClientStreamHandler keeps
    per stream)."""

    __slots__ = ("h", "turns", "proc", "adm")


class _GroupedClientStreamHandler(api.MessageStreamHandler):
    """Server side of a shared client connection: REQUESTs of every
    group in, group-tagged REPLYs out.

    Unlike the peer side (which demuxes to per-group sub-streams so the
    HELLO/log-replay machinery stays untouched), the client side runs
    ONE bundle ingest drain across groups — see
    :class:`_GroupBundleIngestor`.  Per-group ordering is preserved:
    arrival-order tickets are issued per group in fan-out order, and
    fan-out order is bundle order is arrival order."""

    def __init__(self, runtime: "GroupRuntime"):
        self._rt = runtime

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        rt = self._rt
        out_queue: asyncio.Queue = asyncio.Queue()
        FIN = object()
        states: Dict[int, Optional[_GroupClientState]] = {}

        def state(gid: int) -> Optional[_GroupClientState]:
            st = states.get(gid)
            if st is None and gid not in states:
                core = rt.core_or_none(gid)
                if core is None:
                    states[gid] = None  # cache the unknown-group verdict
                    return None
                st = _GroupClientState()
                st.h = core.handlers
                st.turns = _TurnSequencer()

                async def handle_one(
                    msg, _h=st.h, _turns=st.turns, _gid=gid
                ) -> None:
                    t = _turns.ticket()
                    try:
                        reply = await _h.handle_client_message(
                            msg, turn=(_turns, t)
                        )
                    finally:
                        _turns.finish(t)
                    if reply is None:
                        return
                    data = pack_group(_gid, marshal(reply))
                    tr = _h.trace
                    if tr is not None:
                        tr.note(
                            obs_trace.R_REPLY_SENT, reply.client_id, reply.seq
                        )
                    await out_queue.put(data)

                def _drop(e: Exception, _h=st.h) -> None:
                    _h.metrics.inc("messages_dropped")
                    _h.log.warning("dropping client message: %s", e)

                st.proc = _ConcurrentStreamProcessor(handle_one, _drop)
                st.adm = (
                    AdmissionController(
                        st.h,
                        st.proc,
                        out_queue,
                        wrap=lambda b, _gid=gid: pack_group(_gid, b),
                    )
                    if admission_enabled()
                    else None
                )
                states[gid] = st
            return st

        def _drop_stream(e: Exception) -> None:
            # Envelope/codec errors at the shared drain are not
            # attributable to a group: account them on the anchor.
            rt.anchor_handlers.metrics.inc("messages_dropped")
            rt.log.warning("dropping client frame: %s", e)

        async def consume() -> None:
            if bundle_ingest_enabled():
                await _GroupBundleIngestor(rt, state, _drop_stream).run(
                    in_stream
                )
            else:
                async for data in in_stream:
                    try:
                        frames = split_multi(data)
                    except CodecError as e:
                        _drop_stream(e)
                        continue
                    for fr in frames:
                        try:
                            gid, inner = split_group(fr)
                            sub = split_multi(inner)
                        except CodecError as e:
                            _drop_stream(e)
                            continue
                        st = state(gid)
                        if st is not None:
                            for one in sub:
                                # same drop-on-saturation isolation
                                # contract as the bundle path above
                                if st.adm is not None:
                                    await st.adm.submit(one)
                                elif not await st.proc.try_submit(one):
                                    st.h.metrics.inc("messages_dropped")
            for st in states.values():
                if st is not None:
                    await st.proc.drain()
            await out_queue.put(FIN)

        consumer_task = asyncio.get_running_loop().create_task(consume())
        try:
            while True:
                item = await out_queue.get()
                if item is FIN:
                    break
                data, fin = drain_multi(item, out_queue, stop=FIN)
                yield data
                if fin:
                    break
        finally:
            consumer_task.cancel()
            try:
                await consumer_task
            except asyncio.CancelledError:
                pass


# ---------------------------------------------------------------------------
# The runtime.


class GroupRuntime(api.Replica):
    """G independent MinBFT group cores in one replica process, over one
    connector and one engine.

    ``authenticators`` must be one PER-GROUP base instance each (own
    USIG counter state — shared counters would break per-group UI
    contiguity); the runtime wraps each in :class:`GroupAuthenticator`
    for signature domain separation unless ``domain_separation=False``.
    ``consumers`` is one state machine per group (one key-space shard
    each).  ``wrap_group_connector(gid, connector)`` lets tests inject
    group-scoped faults between a core and the shared mux (the
    multi-group chaos soak partitions ONE group this way)."""

    def __init__(
        self,
        replica_id: int,
        configer: api.Configer,
        authenticators: List[api.Authenticator],
        connector: api.ReplicaConnector,
        consumers: List[api.RequestConsumer],
        timer_provider=None,
        logger: Optional[logging.Logger] = None,
        domain_separation: bool = True,
        wrap_group_connector=None,
        engine_pool=None,
        state_dir: Optional[str] = None,
    ):
        if not authenticators:
            raise ValueError("need at least one group authenticator")
        if len(authenticators) > GROUP_MAX + 1:
            # fail at construction, not as a CodecError deep in the
            # first send pump (the envelope's gid field is a u16)
            raise ValueError(
                f"{len(authenticators)} groups exceed the wire envelope's "
                f"maximum of {GROUP_MAX + 1}"
            )
        if len(consumers) != len(authenticators):
            raise ValueError(
                f"{len(consumers)} consumers for {len(authenticators)} groups"
            )
        self.id = replica_id
        self.n_groups = len(authenticators)
        self.log = logger or logging.getLogger(
            f"minbft.replica{replica_id}.groups"
        )
        self._mux = SharedChannelMux(connector, log=self.log)
        # Multi-device engine pool (ISSUE 17): when provided, each
        # group's BASE authenticator is late-bound to its home-chip
        # engine facade (pool placement: group → exactly one chip) so
        # all groups homed on a chip coalesce into THAT chip's queues —
        # the PR-8 cross-group fill win, replicated per chip.  Binding
        # happens before the GroupAuthenticator wrap (the wrapper
        # delegates, it doesn't copy) and never overrides an engine the
        # caller already injected.
        self.engine_pool = engine_pool
        self.cores: List[_Replica] = []
        for g, (auth, consumer) in enumerate(zip(authenticators, consumers)):
            if engine_pool is not None and hasattr(auth, "bind_engine"):
                auth.bind_engine(engine_pool.engine_for(g))
            if domain_separation:
                auth = GroupAuthenticator(auth, g)
            conn_g = self._mux.group_connector(g)
            if wrap_group_connector is not None:
                conn_g = wrap_group_connector(g, conn_g)
            core = _Replica(
                replica_id,
                configer,
                auth,
                conn_g,
                consumer,
                timer_provider,
                logging.getLogger(f"minbft.replica{replica_id}.g{g}"),
                group=g,
                # store_path gives each group core its own group<g>/
                # subdirectory under the shared state dir.
                state_dir=state_dir,
            )
            self.cores.append(core)
        # Stale-group detector state (ISSUE 14): per-group
        # (requests_executed count, monotonic stamp of last change),
        # lazily refreshed by stale_groups() — no watcher task.
        self._progress: Dict[int, Tuple[int, float]] = {}

    # -- api.Replica ---------------------------------------------------

    def peer_message_stream_handler(self) -> api.MessageStreamHandler:
        return _GroupedPeerStreamHandler(self)

    def client_message_stream_handler(self) -> api.MessageStreamHandler:
        return _GroupedClientStreamHandler(self)

    async def start(self) -> None:
        for core in self.cores:
            await core.start()

    async def stop(self) -> None:
        self._mux.seal()
        for core in self.cores:
            await core.stop()
        await self._mux.close()

    # -- accessors ------------------------------------------------------

    def group(self, gid: int) -> _Replica:
        return self.cores[gid]

    def core_or_none(self, gid: int) -> Optional[_Replica]:
        if 0 <= gid < len(self.cores):
            return self.cores[gid]
        return None

    @property
    def anchor_handlers(self):
        """Group 0's handlers: the accounting anchor for shared-stream
        events no single group owns (pump errors, bad envelopes)."""
        return self.cores[0].handlers

    @property
    def metrics(self):
        """Group 0's metrics, for ungrouped callers; per-group metrics
        live on each core (``runtime.group(g).metrics``), and
        :meth:`metrics_aggregate` folds them."""
        return self.cores[0].metrics

    def metrics_aggregate(self) -> dict:
        from ..utils.metrics import aggregate

        return aggregate(core.metrics.snapshot() for core in self.cores)

    def stale_groups(self, threshold_s: float = 30.0) -> Set[int]:
        """Groups whose commit counter has not moved for ``threshold_s``
        while at least one sibling group progressed within that window.

        The sibling clause keeps an idle cluster healthy: staleness is
        *relative* starvation (one group wedged while others commit),
        not absence of load.  State is refreshed lazily on each call —
        callers (the Prometheus scrape, ``peer top``) poll anyway, so a
        watcher task would add nothing but a thread.
        """
        import time as _time

        now = _time.monotonic()
        freshest = None
        for core in self.cores:
            count = core.metrics.counters.get("requests_executed", 0)
            prev = self._progress.get(core.group)
            if prev is None or prev[0] != count:
                self._progress[core.group] = (count, now)
                changed = now
            else:
                changed = prev[1]
            if freshest is None or changed > freshest:
                freshest = changed
        if freshest is None or now - freshest > threshold_s:
            # Everyone is quiet (or there are no cores): idle, not stale.
            return set()
        return {
            g
            for g, (_, changed) in self._progress.items()
            if now - changed > threshold_s
        }

    def dump_trace(self, base=None) -> List[str]:
        """Dump every group core's flight recorder (one file per core —
        the group rides the filename AND the doc)."""
        paths = []
        for core in self.cores:
            p = core.dump_trace(base=base)
            if p is not None:
                paths.append(p)
        return paths


def new_group_runtime(
    replica_id: int,
    configer: api.Configer,
    authenticators: List[api.Authenticator],
    connector: api.ReplicaConnector,
    consumers: List[api.RequestConsumer],
    **kw,
) -> GroupRuntime:
    """Create a multi-group replica runtime (the ``new_replica`` sibling
    for ``peer run --groups G``)."""
    return GroupRuntime(
        replica_id, configer, authenticators, connector, consumers, **kw
    )
