"""Client-side shard routing for the multi-group runtime.

A request key deterministically names its consensus group
(:func:`group_for_key`: stable SHA-256 hash — same key, same group,
across restarts, processes, and languages that can compute SHA-256), and
:class:`MultiGroupClient` keeps one inner
:class:`~minbft_tpu.client.client.Client` per group: each group gets its
own client sequence space and its own per-request reply-quorum tracking,
so groups never serialize each other and a replayed (cid, seq) can never
collide across shards.  All G inner clients share ONE physical stream
per replica (:class:`~minbft_tpu.groups.runtime.SharedChannelMux`) —
the client side of the shared-transport design.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Union

from .. import api
from ..client.client import Client
from ..messages import GROUP_MAX
from .runtime import GroupAuthenticator, SharedChannelMux


def group_for_key(key: bytes, n_groups: int) -> int:
    """Stable key-space shard map: SHA-256 of the key, first 8 bytes as
    a big-endian integer, mod G.  Deliberately hash-based (not range-
    based): request keys are operator-chosen byte strings with unknown
    distribution, and a cryptographic hash spreads any of them evenly.
    Deterministic across restarts by construction — no state, no seed."""
    if not 0 < n_groups <= GROUP_MAX + 1:
        raise ValueError(
            f"n_groups must be in 1..{GROUP_MAX + 1}, got {n_groups}"
        )
    if n_groups == 1:
        return 0
    h = hashlib.sha256(key).digest()
    return int.from_bytes(h[:8], "big") % n_groups


class ShardRouter:
    """Key → group mapping for a G-group cluster.  Stateless beyond G;
    exists as an object so callers hold the shard count in one place
    (and so a future directory-based router can swap in behind the same
    two methods)."""

    def __init__(self, n_groups: int):
        if not 0 < n_groups <= GROUP_MAX + 1:
            raise ValueError(
                f"n_groups must be in 1..{GROUP_MAX + 1}, got {n_groups}"
            )
        self.n_groups = n_groups

    def group_for(self, key: bytes) -> int:
        return group_for_key(key, self.n_groups)


class MultiGroupClient:
    """Facade over G per-group clients with shard routing.

    ``authenticators`` is either ONE base client authenticator (shared
    key material — each group's view is domain-separated via
    :class:`GroupAuthenticator`; clients carry no USIG so sharing the
    base across groups is safe) or a list of G per-group instances
    (independent key material, still wrapped for symmetry with the
    replica side).  ``request(operation, key=...)`` routes by the shard
    key (default: the operation bytes themselves), or pin a group
    explicitly with ``group=``.
    """

    def __init__(
        self,
        client_id: int,
        n: int,
        f: int,
        n_groups: int,
        authenticators: Union[api.Authenticator, List[api.Authenticator]],
        connector: api.ReplicaConnector,
        seq_start: Optional[int] = None,
        max_inflight: Optional[int] = None,
        retransmit_interval: Optional[float] = None,
        trace: bool = False,
        domain_separation: bool = True,
    ):
        self.client_id = client_id
        self.router = ShardRouter(n_groups)
        if isinstance(authenticators, list):
            if len(authenticators) != n_groups:
                raise ValueError(
                    f"{len(authenticators)} authenticators for "
                    f"{n_groups} groups"
                )
            auths = list(authenticators)
        else:
            auths = [authenticators] * n_groups
        self._mux = SharedChannelMux(connector)
        self._clients: List[Client] = []
        for g in range(n_groups):
            auth = auths[g]
            if domain_separation:
                auth = GroupAuthenticator(auth, g)
            self._clients.append(
                Client(
                    client_id,
                    n,
                    f,
                    auth,
                    self._mux.group_connector(g),
                    seq_start=seq_start,
                    max_inflight=max_inflight,
                    retransmit_interval=retransmit_interval,
                    trace=trace,
                    group=g,
                )
            )

    @property
    def n_groups(self) -> int:
        return self.router.n_groups

    def client(self, gid: int) -> Client:
        """The inner per-group client (its pending map IS the per-group
        quorum tracking; its ``_seq`` the per-group sequence space)."""
        return self._clients[gid]

    def group_for(self, key: bytes) -> int:
        return self.router.group_for(key)

    async def start(self) -> None:
        for c in self._clients:
            await c.start()

    async def stop(self) -> None:
        self._mux.seal()
        for c in self._clients:
            await c.stop()
        await self._mux.close()

    async def request(
        self,
        operation: bytes,
        key: Optional[bytes] = None,
        group: Optional[int] = None,
        **kw,
    ) -> bytes:
        """Submit ``operation`` to its shard's group.  ``key`` is the
        shard key (default: the operation bytes); ``group`` pins a group
        outright (operator tooling, tests).  Everything else — timeouts,
        read_only, pipelining — is the inner client's contract."""
        if group is None:
            group = self.router.group_for(operation if key is None else key)
        elif not 0 <= group < self.n_groups:
            raise ValueError(f"group {group} out of range (G={self.n_groups})")
        return await self._clients[group].request(operation, **kw)
