"""Multi-group consensus sharding: one engine, G groups.

One MinBFT group can never feed the chip (~164k verifies/s against
~1k committed req/s end-to-end); G independent groups — one per
key-space shard — can, and the engine's verify/sign queues are exactly
the right place to coalesce batches ACROSS groups so the device sees
one big batch regardless of group count (the DSig amortization
argument, PAPERS.md).

- :class:`GroupRuntime` — N replica processes each hosting G
  independent replica cores (own view/sequence/USIG-counter space, own
  message log and checkpoints) over SHARED transport and ONE shared
  ``parallel/engine``; frames carry a transport-level group tag
  (``messages.codec.pack_group``) and the grouped client stream runs
  one bundle-ingest drain whose tick bundles span groups.
- :class:`ShardRouter` / :class:`MultiGroupClient` — client-side
  key-space sharding: a stable hash maps request keys to groups, each
  group gets its own client sequence space and reply-quorum tracking.
- :class:`GroupAuthenticator` — per-group signature domain separation
  (the group tag is transport-level and unsigned; without domain
  separation a message signed for group g could replay into group g').
"""

from .router import MultiGroupClient, ShardRouter, group_for_key
from .runtime import (
    GroupAuthenticator,
    GroupRuntime,
    SharedChannelMux,
    new_group_runtime,
)

__all__ = [
    "GroupAuthenticator",
    "GroupRuntime",
    "MultiGroupClient",
    "ShardRouter",
    "SharedChannelMux",
    "group_for_key",
    "new_group_runtime",
]
