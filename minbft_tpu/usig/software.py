"""Software USIG implementations (the reference's SGX-SIM-mode analogue).

Both schemes certify ``SHA256(digest32 || epoch_be8 || counter_be8)`` —
the same packed layout idea as the enclave's signed struct (reference
usig/sgx/enclave/usig.c:36-76, which signs {digest, epoch, counter}) — and
uphold increment-after-sign and per-instance random epochs.

Thread-safety: ``create_ui`` takes a lock, mirroring the reference's
``ecallLock`` around the single-threaded enclave (reference
usig/sgx/usig-enclave.go:105-114).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import secrets
import threading
from typing import Callable, Optional, Tuple

from ..utils import hostcrypto as hc
from .usig import UI, USIG, UsigError

_EPOCH_LEN = 8


def _signed_payload(digest: bytes, epoch: bytes, counter: int) -> bytes:
    return hashlib.sha256(
        digest + epoch + counter.to_bytes(8, "big")
    ).digest()


class _BaseUSIG(USIG):
    def __init__(self, epoch: Optional[bytes] = None):
        self._epoch = epoch if epoch is not None else secrets.token_bytes(_EPOCH_LEN)
        self._counter = 1  # counters start at 1 (reference usig.c:181, test usig_test.c:34-60)
        self._lock = threading.Lock()

    @property
    def epoch(self) -> bytes:
        return self._epoch

    def create_ui(self, message: bytes) -> UI:
        digest = hashlib.sha256(message).digest()
        with self._lock:
            counter = self._counter
            cert = self._epoch + self._certify(
                _signed_payload(digest, self._epoch, counter)
            )
            # Increment only after the certificate exists, so this counter
            # value can never be issued again (reference usig.c:66-69).
            self._counter = counter + 1
        return UI(counter=counter, cert=cert)

    def verify_ui(self, message: bytes, ui: UI, usig_id: bytes) -> None:
        if ui.counter == 0:
            raise UsigError("zero counter")  # reference core/usig-ui.go:65-67
        if len(ui.cert) < _EPOCH_LEN:
            raise UsigError("certificate too short")
        cert_epoch, sig = ui.cert[:_EPOCH_LEN], ui.cert[_EPOCH_LEN:]
        id_epoch, key_material = usig_id[:_EPOCH_LEN], usig_id[_EPOCH_LEN:]
        if cert_epoch != id_epoch:
            raise UsigError("epoch mismatch")  # reference sgx-usig.go:86-90
        digest = hashlib.sha256(message).digest()
        payload = _signed_payload(digest, cert_epoch, ui.counter)
        if not self._verify(key_material, payload, sig):
            raise UsigError("invalid UI certificate")

    # -- scheme hooks -------------------------------------------------------

    def _certify(self, payload: bytes) -> bytes:
        raise NotImplementedError

    def _verify(self, key_material: bytes, payload: bytes, sig: bytes) -> bool:
        raise NotImplementedError


class HmacUSIG(_BaseUSIG):
    """SGX-less symmetric USIG (BASELINE config[0]).

    A cluster-shared 32-byte MAC key stands in for hardware trust: any
    holder can verify (and forge!) certificates, so this is a SIM/test
    scheme, exactly like running the reference enclave in SGX SIM mode.
    ID = epoch || SHA256(key) (fingerprint only — never the key itself).
    """

    SCHEME = "hmac-sha256"

    def __init__(self, key: bytes, epoch: Optional[bytes] = None):
        super().__init__(epoch)
        if len(key) != 32:
            raise ValueError("HmacUSIG key must be 32 bytes")
        self._key = key

    def id(self) -> bytes:
        return self._epoch + hashlib.sha256(self._key).digest()

    def _certify(self, payload: bytes) -> bytes:
        return hmac_mod.new(self._key, payload, hashlib.sha256).digest()

    def _verify(self, key_material: bytes, payload: bytes, sig: bytes) -> bool:
        # key_material is the fingerprint; verification requires holding the
        # same shared key.
        if key_material != hashlib.sha256(self._key).digest():
            return False
        expect = hmac_mod.new(self._key, payload, hashlib.sha256).digest()
        return hmac_mod.compare_digest(expect, sig)


class EcdsaUSIG(_BaseUSIG):
    """ECDSA-P256 USIG — the reference enclave's scheme
    (reference usig/sgx/enclave/usig.c:36-76, sgx-usig.go:81-97).

    Cert = epoch || r(32) || s(32); ID = epoch || x(32) || y(32).
    Public verification — batchable on TPU via
    :func:`minbft_tpu.ops.p256.ecdsa_verify_kernel` (the TPU-USIG path
    routes verification through the batching engine instead of calling
    :meth:`verify_ui` serially).
    """

    SCHEME = "ecdsa-p256"

    def __init__(
        self,
        private_key: Optional[int] = None,
        epoch: Optional[bytes] = None,
        sign_fn: Optional[Callable[[bytes], Tuple[int, int]]] = None,
    ):
        super().__init__(epoch)
        if private_key is None:
            private_key, public = hc.keygen()
        else:
            public = hc.scalar_mult(private_key, (hc.GX, hc.GY))
        self._d = private_key
        self._q = public
        self._sign_fn = sign_fn  # native-module override hook

    @property
    def public_key(self) -> Tuple[int, int]:
        return self._q

    def id(self) -> bytes:
        x, y = self._q
        return self._epoch + x.to_bytes(32, "big") + y.to_bytes(32, "big")

    def _certify(self, payload: bytes) -> bytes:
        if self._sign_fn is not None:
            r, s = self._sign_fn(payload)
        else:
            r, s = hc.ecdsa_sign(self._d, payload)
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def _verify(self, key_material: bytes, payload: bytes, sig: bytes) -> bool:
        if len(key_material) != 64 or len(sig) != 64:
            return False
        q = (
            int.from_bytes(key_material[:32], "big"),
            int.from_bytes(key_material[32:], "big"),
        )
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        return hc.ecdsa_verify(q, payload, (r, s))


def parse_usig_id(usig_id: bytes) -> Tuple[bytes, bytes]:
    """Split a USIG ID into (epoch, key material)."""
    if len(usig_id) < _EPOCH_LEN:
        raise UsigError("USIG ID too short")
    return usig_id[:_EPOCH_LEN], usig_id[_EPOCH_LEN:]


def usig_verify_items(
    message: bytes, ui: UI, usig_id: bytes
) -> Tuple[Tuple[int, int], bytes, Tuple[int, int]]:
    """Decompose an ECDSA UI verification into the (pubkey, digest, sig)
    triple consumed by the TPU batch verifier
    (:func:`minbft_tpu.ops.p256.prepare_batch`).

    Raises :class:`UsigError` for structurally invalid inputs (those the
    batch path must reject before building the fixed-shape batch).
    """
    if ui.counter == 0:
        raise UsigError("zero counter")
    if len(ui.cert) != _EPOCH_LEN + 64:
        # Exact length: padding or trailing bytes would otherwise verify on
        # the batch path but be rejected by the serial verifier
        # (certificate-encoding malleability).
        raise UsigError("malformed certificate")
    cert_epoch, sig = ui.cert[:_EPOCH_LEN], ui.cert[_EPOCH_LEN:]
    id_epoch, key_material = parse_usig_id(usig_id)
    if cert_epoch != id_epoch or len(key_material) != 64:
        raise UsigError("epoch mismatch")
    digest = hashlib.sha256(message).digest()
    payload = _signed_payload(digest, cert_epoch, ui.counter)
    q = (
        int.from_bytes(key_material[:32], "big"),
        int.from_bytes(key_material[32:], "big"),
    )
    return q, payload, (int.from_bytes(sig[:32], "big"), int.from_bytes(sig[32:], "big"))
