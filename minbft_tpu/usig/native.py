"""ctypes binding for the native C++ USIG module.

The shim layer of the reference is a cgo bridge that dlopens
``libusig_shim.so`` and calls through function pointers
(reference usig/sgx/usig-enclave.go:97-114, 337-347); here the bridge is
ctypes over ``minbft_tpu/native/libusig.so``.  The module is optional:
:func:`load` returns None when the library isn't built and callers fall
back to the pure-Python :class:`minbft_tpu.usig.software.EcdsaUSIG`.

``NativeEcdsaUSIG`` produces byte-identical UI certificates to
``EcdsaUSIG`` (cert = epoch8 || r32 || s32, ID = epoch8 || x32 || y32), so
its UIs verify on the TPU batch path (usig_verify_items) unchanged.  Unlike
the Python class it supports key **sealing**: ``seal()`` exports a blob
that ``from_sealed`` restores — the durable-state story of the reference
(sealed USIG key in keys.yaml, reference keymanager.go:299-328).  Only the
KEY is sealed: every init draws a fresh random epoch (reference
usig/sgx/enclave/usig.c:168-186), so a restored instance — whose counter
restarts at 1 — can never re-certify (epoch, cv) values issued by a
previous instance of the same key.  Verifiers learn the new epoch
trust-on-first-use (SampleAuthenticator epoch capture, reference
sample/authentication/crypto.go:204-218).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

from .usig import UI, USIG, UsigError

_EPOCH_LEN = 8

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libusig.so"))

USIG_OK = 0

_lib = None
_load_attempted = False


def build(quiet: bool = True) -> bool:
    """Build the native module in-tree (requires g++).  True on success."""
    try:
        # noqa: AH101 - one-shot native build at first load (gated by _load_attempted)
        res = subprocess.run(
            ["make", "libusig.so"],
            cwd=os.path.abspath(_NATIVE_DIR),
            capture_output=quiet,
            timeout=120,
        )
        return res.returncode == 0
    except Exception:
        return False


def load(auto_build: bool = False) -> Optional[ctypes.CDLL]:
    """Load (optionally building) the native library; None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted and not auto_build:
        return None
    _load_attempted = True
    if not os.path.exists(_LIB_PATH) and auto_build:
        build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    if not hasattr(lib, "usig_init2") and auto_build:
        # Stale build predating encrypted sealing (v3): rebuild + reload.
        # The Makefile links to a temp name and renames, so the rebuilt
        # file is a fresh inode and dlopen yields a new handle.
        if build():
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError:
                return None
    # A stale-but-functional pre-v3 library (no compiler to rebuild with)
    # still serves everything except encrypted sealing — bind what exists.
    _bind(lib)
    _lib = lib
    return _lib


def _bind(lib) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.usig_init.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.usig_destroy.argtypes = [ctypes.c_void_p]
    lib.usig_create_ui.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        u8p,
    ]
    lib.usig_get_epoch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.usig_get_pubkey.argtypes = [ctypes.c_void_p, u8p]
    lib.usig_sealed_size.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.usig_seal.argtypes = [
        ctypes.c_void_p,
        u8p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.usig_verify_ui.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_char_p,
    ]
    lib.usig_native_version.restype = ctypes.c_char_p
    if hasattr(lib, "usig_init2"):
        lib.usig_init2.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.usig_sealed_size2.argtypes = [
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.usig_seal2.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            u8p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]


def available(auto_build: bool = False) -> bool:
    return load(auto_build=auto_build) is not None


class NativeEcdsaUSIG(USIG):
    """USIG backed by the native module (reference SGXUSIG analogue,
    usig/sgx/sgx-usig.go:42-62)."""

    SCHEME = "ecdsa-p256"

    def __init__(
        self,
        sealed: Optional[bytes] = None,
        secret: Optional[bytes] = None,
        _lib_override=None,
    ):
        lib = _lib_override or load(auto_build=True)
        if lib is None:
            raise UsigError("native USIG module not available (build failed?)")
        self._lib = lib
        handle = ctypes.c_void_p()
        if hasattr(lib, "usig_init2"):
            rc = lib.usig_init2(
                ctypes.byref(handle),
                sealed if sealed is not None else None,
                len(sealed) if sealed is not None else 0,
                secret if secret else None,
                len(secret) if secret else 0,
            )
        elif secret or (sealed is not None and sealed[:4] == b"USG3"):
            raise UsigError(
                "this libusig.so predates encrypted sealing (v3); rebuild "
                "the native module to use a sealing secret"
            )
        else:
            rc = lib.usig_init(
                ctypes.byref(handle),
                sealed if sealed is not None else None,
                len(sealed) if sealed is not None else 0,
            )
        if rc != USIG_OK:
            raise UsigError(
                "usig_init failed: encrypted blob needs the sealing secret"
                if rc == 6
                else f"usig_init failed (rc={rc})"
            )
        self._h = handle
        epoch = ctypes.c_uint64()
        if lib.usig_get_epoch(self._h, ctypes.byref(epoch)) != USIG_OK:
            raise UsigError("usig_get_epoch failed")
        self._epoch = int(epoch.value).to_bytes(8, "big")
        pub = (ctypes.c_uint8 * 64)()
        if lib.usig_get_pubkey(self._h, pub) != USIG_OK:
            raise UsigError("usig_get_pubkey failed")
        self._pub = bytes(pub)

    def __del__(self):  # release the native instance
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.usig_destroy(h)
            except Exception:
                pass
            self._h = None

    # -- USIG interface ------------------------------------------------------

    @property
    def epoch(self) -> bytes:
        return self._epoch

    @property
    def public_key(self):
        return (
            int.from_bytes(self._pub[:32], "big"),
            int.from_bytes(self._pub[32:], "big"),
        )

    def id(self) -> bytes:
        return self._epoch + self._pub

    def create_ui(self, message: bytes) -> UI:
        digest = hashlib.sha256(message).digest()
        counter = ctypes.c_uint64()
        sig = (ctypes.c_uint8 * 64)()
        rc = self._lib.usig_create_ui(self._h, digest, ctypes.byref(counter), sig)
        if rc != USIG_OK:
            raise UsigError(f"usig_create_ui failed (rc={rc})")
        return UI(counter=int(counter.value), cert=self._epoch + bytes(sig))

    def verify_ui(self, message: bytes, ui: UI, usig_id: bytes) -> None:
        if ui.counter == 0:
            raise UsigError("zero counter")
        if len(ui.cert) != _EPOCH_LEN + 64:
            raise UsigError("malformed certificate")
        cert_epoch, sig = ui.cert[:_EPOCH_LEN], ui.cert[_EPOCH_LEN:]
        if len(usig_id) != _EPOCH_LEN + 64:
            raise UsigError("malformed USIG ID")
        id_epoch, pub = usig_id[:_EPOCH_LEN], usig_id[_EPOCH_LEN:]
        if cert_epoch != id_epoch:
            raise UsigError("epoch mismatch")
        digest = hashlib.sha256(message).digest()
        rc = self._lib.usig_verify_ui(
            pub,
            int.from_bytes(id_epoch, "big"),
            digest,
            ui.counter,
            sig,
        )
        if rc != USIG_OK:
            raise UsigError("invalid UI certificate")

    # -- sealing (durable state) --------------------------------------------

    def seal(self, secret: Optional[bytes] = None) -> bytes:
        """Export the sealed key blob (reference SealedKey,
        usig/sgx/usig-enclave.go:254-268).  The epoch is volatile by
        design and is not part of the blob.  With ``secret`` the blob is
        AES-256-GCM encrypted inside the native module (v3 — the
        sgx_seal_data confidentiality analogue, reference
        usig/sgx/enclave/usig.c:107-116); without, the plaintext v2
        layout."""
        if not hasattr(self._lib, "usig_seal2"):
            if secret:
                raise UsigError(
                    "this libusig.so predates encrypted sealing (v3); "
                    "rebuild the native module to use a sealing secret"
                )
            need = ctypes.c_size_t()
            if self._lib.usig_sealed_size(self._h, ctypes.byref(need)) != USIG_OK:
                raise UsigError("usig_sealed_size failed")
            buf = (ctypes.c_uint8 * need.value)()
            out_len = ctypes.c_size_t()
            rc = self._lib.usig_seal(
                self._h, buf, need.value, ctypes.byref(out_len)
            )
            if rc != USIG_OK:
                raise UsigError(f"usig_seal failed (rc={rc})")
            return bytes(buf[: out_len.value])
        need = ctypes.c_size_t()
        if (
            self._lib.usig_sealed_size2(
                self._h, len(secret) if secret else 0, ctypes.byref(need)
            )
            != USIG_OK
        ):
            raise UsigError("usig_sealed_size failed")
        buf = (ctypes.c_uint8 * need.value)()
        out_len = ctypes.c_size_t()
        rc = self._lib.usig_seal2(
            self._h,
            secret if secret else None,
            len(secret) if secret else 0,
            buf,
            need.value,
            ctypes.byref(out_len),
        )
        if rc != USIG_OK:
            raise UsigError(f"usig_seal failed (rc={rc})")
        return bytes(buf[: out_len.value])

    @classmethod
    def from_sealed(
        cls, sealed: bytes, secret: Optional[bytes] = None
    ) -> "NativeEcdsaUSIG":
        """Restore an instance: same key, FRESH epoch, counter restarts
        at 1 (reference usig.c:168-186).  ``secret`` is required for v3
        (encrypted) blobs."""
        return cls(sealed=sealed, secret=secret)
