"""USIG — Unique Sequential Identifier Generator (the trusted component).

Mirrors the reference ``usig`` package (reference usig/usig.go:28-51) and the
SGX enclave semantics (reference usig/sgx/enclave/usig.c:36-76): a per-
replica monotonic counter bound to message digests under a per-instance
epoch, such that a (digest, counter) pair can never be produced twice —
the property that lets MinBFT run with n = 2f+1 replicas and 2 rounds.

Implementations:

- :class:`minbft_tpu.usig.software.HmacUSIG` — SGX-less symmetric mode
  (BASELINE config[0]); cluster-shared MAC key stands in for hardware trust.
- :class:`minbft_tpu.usig.software.EcdsaUSIG` — the reference enclave's
  scheme (ECDSA-P256 over {digest, epoch, counter}); public verification,
  batchable on TPU via :mod:`minbft_tpu.ops.p256`.
- ``minbft_tpu.native`` — C++ implementation of the same semantics with
  key sealing (the reference's enclave/shim equivalent), preferred when
  built.
"""

from .usig import UI, USIG, UsigError, ui_from_bytes, ui_to_bytes

__all__ = ["UI", "USIG", "UsigError", "ui_from_bytes", "ui_to_bytes"]
