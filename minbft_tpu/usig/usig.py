"""USIG interface and UI certificate structure.

Reference usig/usig.go:28-102: ``USIG`` {CreateUI, VerifyUI, ID} and
``UI`` {Counter, Cert} with big-endian binary marshalling.  The UI dataclass
is shared with the messages layer (:class:`minbft_tpu.messages.UI`) — the
wire form is the same object.
"""

from __future__ import annotations

import abc

from ..messages.message import UI

__all__ = ["UI", "USIG", "UsigError", "ui_to_bytes", "ui_from_bytes"]


class UsigError(Exception):
    """UI creation/verification failure."""


def ui_to_bytes(ui: UI) -> bytes:
    """Marshal a UI big-endian (reference usig/usig.go:84-102)."""
    return ui.to_bytes()


def ui_from_bytes(data: bytes) -> UI:
    return UI.from_bytes(data)


class USIG(abc.ABC):
    """The trusted component interface (reference usig/usig.go:28-41).

    Semantics every implementation must uphold (reference
    usig/sgx/enclave/usig.c:36-76):

    - ``create_ui`` assigns the *current* counter value and increments the
      counter only after the certificate is produced, so no counter value
      can ever certify two different messages (comment at usig.c:66-69).
    - Counters start at 1 and are strictly sequential per instance.
    - A fresh random 64-bit ``epoch`` is drawn per instance (usig.c:181);
      certificates from different epochs never verify against each other,
      so a restarted replica cannot equivocate using a reset counter.
    """

    @abc.abstractmethod
    def create_ui(self, message: bytes) -> UI:
        """Certify ``message`` with the next counter value."""

    @abc.abstractmethod
    def verify_ui(self, message: bytes, ui: UI, usig_id: bytes) -> None:
        """Verify ``ui`` over ``message`` against the instance identified by
        ``usig_id``; raises :class:`UsigError` on failure."""

    @abc.abstractmethod
    def id(self) -> bytes:
        """Opaque identity of this instance (epoch + public key material;
        reference usig/sgx/sgx-usig.go:105-122)."""
