"""Deterministic flat binary codec for protocol messages.

Replaces the reference's protobuf wire format (reference
messages/protobuf/pb/messages.proto:24-33, one ``Message`` wrapper with a
``oneof typed``) with a canonical hand-rolled layout:

    byte 0          kind tag
    then fields     big-endian fixed-width ints; bytes fields length-prefixed
                    with u32; embedded messages as length-prefixed marshalled
                    bytes.

Determinism is load-bearing: USIG certificates and signatures cover digests
of these exact bytes (see :mod:`minbft_tpu.messages.authen`), and protobuf
does not guarantee canonical serialization.  A flat codec is also much
cheaper to encode/decode on the host, which keeps the Python side of the
pipeline off the critical path while the TPU does the crypto.
"""

from __future__ import annotations

import struct
from collections import OrderedDict
from typing import List, Tuple

import numpy as np

from .message import (
    CERTIFIED_MESSAGES,
    UI,
    Busy,
    Checkpoint,
    Commit,
    Hello,
    LogBase,
    Message,
    NewView,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    SnapshotReq,
    SnapshotResp,
    StateChunk,
    StateDone,
    StateReq,
    ViewChange,
)

# Kind tags (wire stable).
_TAG_HELLO = 0x01
_TAG_REQUEST = 0x02
_TAG_REPLY = 0x03
_TAG_PREPARE = 0x04
_TAG_COMMIT = 0x05
_TAG_REQ_VIEW_CHANGE = 0x06
_TAG_VIEW_CHANGE = 0x07
_TAG_NEW_VIEW = 0x08
_TAG_CHECKPOINT = 0x09
_TAG_LOG_BASE = 0x0A
_TAG_SNAPSHOT_REQ = 0x0B
_TAG_SNAPSHOT_RESP = 0x0C
_TAG_BUSY = 0x0D
_TAG_STATE_REQ = 0x0E
_TAG_STATE_CHUNK = 0x0F
_TAG_STATE_DONE = 0x10
# Transport-level container: several messages coalesced into ONE stream
# frame (amortizes the per-frame gRPC/asyncio cost, which dominates the
# multi-process deployment's throughput on small hosts).  Deliberately far
# from the message tags — a multi frame is framing, not a message, and
# never nests.
_TAG_MULTI = 0xF0
# Transport-level group envelope (the multi-group runtime's demux tag,
# minbft_tpu/groups): [0xF1][u16 group id][inner frame].  Framing, not a
# message — it wraps exactly one message frame (or one multi container on
# the mux's physical hop), is stripped before decode, and NEVER nests.
# An untagged frame is group 0 by definition, so a single-group runtime's
# wire format is byte-identical to the ungrouped one.
_TAG_GROUP = 0xF1
_U16 = struct.Struct(">H")
GROUP_MAX = 0xFFFF

_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")


class CodecError(ValueError):
    pass


def _pack_u32(v: int) -> bytes:
    if not 0 <= v < 2**32:
        raise CodecError(f"u32 field out of range: {v}")
    return _U32.pack(v)


def _pack_u64(v: int) -> bytes:
    if not 0 <= v < 2**64:
        raise CodecError(f"u64 field out of range: {v}")
    return _U64.pack(v)


def _pack_bytes(b: bytes) -> bytes:
    return _U32.pack(len(b)) + b


def _read_bytes(data: bytes, off: int) -> Tuple[bytes, int]:
    if off + 4 > len(data):
        raise CodecError("truncated length prefix")
    (n,) = _U32.unpack_from(data, off)
    off += 4
    if off + n > len(data):
        raise CodecError("truncated bytes field")
    return data[off : off + n], off + n


def _read_bounded_byte(
    data: bytes, off: int, bound: int, what: str
) -> Tuple[int, int]:
    """One strict bounded byte: values above ``bound`` are rejected so a
    message has exactly ONE encoding (determinism is load-bearing for
    signatures over marshaled bytes).  bound=1 decodes booleans; bound=2
    the Request read_mode (0 write / 1 fast read / 2 ordered read)."""
    if off + 1 > len(data):
        raise CodecError(f"truncated {what}")
    b = data[off]
    if b > bound:
        raise CodecError(f"invalid {what} byte")
    return b, off + 1


def _read_u32(data: bytes, off: int) -> Tuple[int, int]:
    if off + 4 > len(data):
        raise CodecError("truncated u32")
    return _U32.unpack_from(data, off)[0], off + 4


def _read_u64(data: bytes, off: int) -> Tuple[int, int]:
    if off + 8 > len(data):
        raise CodecError("truncated u64")
    return _U64.unpack_from(data, off)[0], off + 8


def _pack_ui(ui) -> bytes:
    if ui is None:
        return _pack_bytes(b"")
    try:
        return _pack_bytes(ui.to_bytes())
    except OverflowError as e:
        raise CodecError(f"UI counter out of range: {e}") from e


def _parse_ui(uib: bytes):
    if not uib:
        return None
    try:
        return UI.from_bytes(uib)
    except ValueError as e:
        raise CodecError(f"malformed UI: {e}") from e


def marshal(m: Message) -> bytes:
    """Serialize a message to canonical bytes
    (reference messages/protobuf/impl.go:87-107 equivalent)."""
    if isinstance(m, Hello):
        return (
            bytes([_TAG_HELLO])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.resume_counter)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, Request):
        return (
            bytes([_TAG_REQUEST])
            + _pack_u32(m.client_id)
            + _pack_u64(m.seq)
            + bytes([m.read_mode])
            + _pack_bytes(m.operation)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, Reply):
        return (
            bytes([_TAG_REPLY])
            + _pack_u32(m.replica_id)
            + _pack_u32(m.client_id)
            + _pack_u64(m.seq)
            + bytes([1 if m.read_only else 0])
            + bytes([1 if m.error else 0])
            + _pack_bytes(m.result)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, Busy):
        return (
            bytes([_TAG_BUSY])
            + _pack_u32(m.replica_id)
            + _pack_u32(m.client_id)
            + _pack_u64(m.seq)
            + _pack_u32(m.retry_after_ms)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, Prepare):
        return (
            bytes([_TAG_PREPARE])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.view)
            + _pack_u32(len(m.requests))
            + b"".join(_pack_bytes(marshal(r)) for r in m.requests)
            + _pack_bytes(m.requests_digest)
            + _pack_ui(m.ui)
        )
    if isinstance(m, Commit):
        return (
            bytes([_TAG_COMMIT])
            + _pack_u32(m.replica_id)
            + _pack_bytes(marshal(m.prepare))
            + _pack_ui(m.ui)
        )
    if isinstance(m, ReqViewChange):
        return (
            bytes([_TAG_REQ_VIEW_CHANGE])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.new_view)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, ViewChange):
        return (
            bytes([_TAG_VIEW_CHANGE])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.new_view)
            + _pack_u32(len(m.log))
            + b"".join(_pack_bytes(marshal(e)) for e in m.log)
            + _pack_bytes(m.log_digest)
            + _pack_u64(m.log_base)
            + _pack_u32(len(m.checkpoint_cert))
            + b"".join(_pack_bytes(marshal(c)) for c in m.checkpoint_cert)
            + _pack_ui(m.ui)
        )
    if isinstance(m, NewView):
        return (
            bytes([_TAG_NEW_VIEW])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.new_view)
            + _pack_u32(len(m.view_changes))
            + b"".join(_pack_bytes(marshal(vc)) for vc in m.view_changes)
            + _pack_bytes(m.vcs_digest)
            + _pack_ui(m.ui)
        )
    if isinstance(m, Checkpoint):
        return (
            bytes([_TAG_CHECKPOINT])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.count)
            + _pack_bytes(m.digest)
            + _pack_u64(m.view)
            + _pack_u64(m.cv)
            + _pack_u32(len(m.bounds))
            + b"".join(_pack_u32(p) + _pack_u64(b) for p, b in m.bounds)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, LogBase):
        return (
            bytes([_TAG_LOG_BASE])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.base)
            + _pack_u32(len(m.cert))
            + b"".join(_pack_bytes(marshal(c)) for c in m.cert)
        )
    if isinstance(m, SnapshotReq):
        return (
            bytes([_TAG_SNAPSHOT_REQ])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.count)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, SnapshotResp):
        return (
            bytes([_TAG_SNAPSHOT_RESP])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.count)
            + _pack_u64(m.view)
            + _pack_u64(m.cv)
            + _pack_bytes(m.app_state)
            + _pack_u32(len(m.watermarks))
            + b"".join(_pack_u32(c) + _pack_u64(s) for c, s in m.watermarks)
            + _pack_u32(len(m.cert))
            + b"".join(_pack_bytes(marshal(c)) for c in m.cert)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, StateReq):
        return (
            bytes([_TAG_STATE_REQ])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.count)
            + _pack_u64(m.offset)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, StateChunk):
        return (
            bytes([_TAG_STATE_CHUNK])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.count)
            + _pack_u64(m.offset)
            + _pack_u64(m.total)
            + _pack_bytes(m.data)
            + _pack_bytes(m.chain)
            + _pack_bytes(m.signature)
        )
    if isinstance(m, StateDone):
        return (
            bytes([_TAG_STATE_DONE])
            + _pack_u32(m.replica_id)
            + _pack_u64(m.count)
            + _pack_u64(m.view)
            + _pack_u64(m.cv)
            + _pack_u64(m.total)
            + _pack_u32(len(m.watermarks))
            + b"".join(_pack_u32(c) + _pack_u64(s) for c, s in m.watermarks)
            + _pack_u32(len(m.cert))
            + b"".join(_pack_bytes(marshal(c)) for c in m.cert)
            + _pack_bytes(m.signature)
        )
    raise CodecError(f"unknown message type {type(m)!r}")


# Decode interning: the same REQUEST bytes arrive once from the client and
# again embedded in the PREPARE and in every COMMIT (which embeds the full
# PREPARE) — on a receiving replica that's ~n parses of identical bytes per
# message.  Interning by exact wire bytes collapses them to one parse, and
# the shared object also shares its authen-bytes/marshal memos.  Safe
# because received messages' protocol *fields* are never mutated
# (signatures/UIs are assigned only to own generated messages,
# pre-serialization); the only writes to a shared object are idempotent
# memo attributes (_authen_bytes, _wire_bytes, and the token-keyed
# _validated_by set from core/message_handling.py).  LRU bounded by
# *accumulated key bytes*, not entry count: a batched PREPARE's wire bytes
# are O(batch * request size), so an entry-count cap could retain hundreds
# of MB.
#
# Two documented assumptions (deliberate trade-offs, not invariants):
# - The cache is populated with PRE-authentication bytes, so a peer or
#   client flooding distinct REQUEST/PREPARE wire bytes fills the LRU with
#   junk and evicts the hot legitimate entries.  That degrades the
#   parse/dedup amortization (perf only — correctness never depends on an
#   intern hit); interning post-validation would shrink the attack surface
#   at the cost of the first-parse dedup that the n-replica fan-in relies
#   on.
# - Access is assumed single-threaded on one asyncio event loop (true for
#   grpc.aio and the in-process connector); the OrderedDict is not locked.
_INTERN_MAX_BYTES = 32 * 1024 * 1024
_intern: "OrderedDict[bytes, Message]" = OrderedDict()
_intern_bytes = 0
_INTERNABLE = (_TAG_REQUEST, _TAG_PREPARE)


# Deepest legitimate embedding: NEW-VIEW → VIEW-CHANGE → COMMIT → PREPARE
# → REQUEST = 5 levels; the cap rejects crafted self-nesting (a ~15KB
# message of VIEW-CHANGE-in-VIEW-CHANGE would otherwise blow the Python
# recursion limit before any authentication, and RecursionError is not a
# CodecError — peers would misclassify it as a local internal bug).
_MAX_NESTING = 8


def unmarshal(data: bytes, _depth: int = 0) -> Message:
    """Parse canonical bytes back into a typed message
    (reference messages.MessageImpl.NewFromBinary, messages/api.go:26)."""
    global _intern_bytes
    if _depth > _MAX_NESTING:
        raise CodecError("message nesting too deep")
    if data and data[0] in _INTERNABLE:
        m = _intern.get(data)
        if m is not None:
            _intern.move_to_end(data)
            return m
    m, off = _unmarshal_at(data, 0, _depth)
    if off != len(data):
        raise CodecError("trailing bytes after message")
    if data[0] in _INTERNABLE and len(data) < _INTERN_MAX_BYTES // 4:
        _intern[data] = m
        _intern_bytes += len(data)
        while _intern_bytes > _INTERN_MAX_BYTES:
            evicted, _ = _intern.popitem(last=False)
            _intern_bytes -= len(evicted)
    return m


def _unmarshal_at(data: bytes, off: int, depth: int = 0) -> Tuple[Message, int]:
    if off >= len(data):
        raise CodecError("empty message")
    tag = data[off]
    off += 1
    if tag == _TAG_HELLO:
        rid, off = _read_u32(data, off)
        resume, off = _read_u64(data, off)
        sig, off = _read_bytes(data, off)
        return Hello(replica_id=rid, signature=sig, resume_counter=resume), off
    if tag == _TAG_REQUEST:
        cid, off = _read_u32(data, off)
        seq, off = _read_u64(data, off)
        mode, off = _read_bounded_byte(data, off, 2, "read_mode")
        op, off = _read_bytes(data, off)
        sig, off = _read_bytes(data, off)
        return (
            Request(
                client_id=cid, seq=seq, operation=op, signature=sig, read_mode=mode
            ),
            off,
        )
    if tag == _TAG_REPLY:
        rid, off = _read_u32(data, off)
        cid, off = _read_u32(data, off)
        seq, off = _read_u64(data, off)
        rb, off = _read_bounded_byte(data, off, 1, "read_only flag")
        eb, off = _read_bounded_byte(data, off, 1, "error flag")
        result, off = _read_bytes(data, off)
        sig, off = _read_bytes(data, off)
        return (
            Reply(
                replica_id=rid,
                client_id=cid,
                seq=seq,
                result=result,
                signature=sig,
                read_only=bool(rb),
                error=bool(eb),
            ),
            off,
        )
    if tag == _TAG_BUSY:
        rid, off = _read_u32(data, off)
        cid, off = _read_u32(data, off)
        seq, off = _read_u64(data, off)
        retry, off = _read_u32(data, off)
        sig, off = _read_bytes(data, off)
        return (
            Busy(
                replica_id=rid,
                client_id=cid,
                seq=seq,
                retry_after_ms=retry,
                signature=sig,
            ),
            off,
        )
    if tag == _TAG_PREPARE:
        rid, off = _read_u32(data, off)
        view, off = _read_u64(data, off)
        count, off = _read_u32(data, off)
        reqs = []
        for _ in range(count):
            reqb, off = _read_bytes(data, off)
            req = unmarshal(reqb, depth + 1)
            if not isinstance(req, Request):
                raise CodecError("PREPARE must embed REQUESTs")
            reqs.append(req)
        rdig, off = _read_bytes(data, off)
        if count == 0 and not rdig:
            raise CodecError(
                "PREPARE must embed at least one REQUEST or a stub digest"
            )
        uib, off = _read_bytes(data, off)
        ui = _parse_ui(uib)
        return (
            Prepare(
                replica_id=rid, view=view, requests=reqs, ui=ui,
                requests_digest=rdig,
            ),
            off,
        )
    if tag == _TAG_COMMIT:
        rid, off = _read_u32(data, off)
        prepb, off = _read_bytes(data, off)
        uib, off = _read_bytes(data, off)
        prep = unmarshal(prepb, depth + 1)
        if not isinstance(prep, Prepare):
            raise CodecError("COMMIT must embed a PREPARE")
        ui = _parse_ui(uib)
        return Commit(replica_id=rid, prepare=prep, ui=ui), off
    if tag == _TAG_REQ_VIEW_CHANGE:
        rid, off = _read_u32(data, off)
        nv, off = _read_u64(data, off)
        sig, off = _read_bytes(data, off)
        return ReqViewChange(replica_id=rid, new_view=nv, signature=sig), off
    if tag == _TAG_VIEW_CHANGE:
        rid, off = _read_u32(data, off)
        nv, off = _read_u64(data, off)
        count, off = _read_u32(data, off)
        entries = []
        for _ in range(count):
            eb, off = _read_bytes(data, off)
            entry = unmarshal(eb, depth + 1)
            if not isinstance(entry, CERTIFIED_MESSAGES):
                raise CodecError("VIEW-CHANGE log entries must be certified")
            entries.append(entry)
        digest, off = _read_bytes(data, off)
        base, off = _read_u64(data, off)
        ccount, off = _read_u32(data, off)
        cert = []
        for _ in range(ccount):
            cb, off = _read_bytes(data, off)
            cp = unmarshal(cb, depth + 1)
            if not isinstance(cp, Checkpoint):
                raise CodecError("VIEW-CHANGE cert entries must be CHECKPOINTs")
            cert.append(cp)
        uib, off = _read_bytes(data, off)
        return (
            ViewChange(
                replica_id=rid, new_view=nv, log=tuple(entries),
                ui=_parse_ui(uib), log_digest=digest,
                log_base=base, checkpoint_cert=tuple(cert),
            ),
            off,
        )
    if tag == _TAG_NEW_VIEW:
        rid, off = _read_u32(data, off)
        nv, off = _read_u64(data, off)
        count, off = _read_u32(data, off)
        vcs = []
        for _ in range(count):
            vcb, off = _read_bytes(data, off)
            vc = unmarshal(vcb, depth + 1)
            if not isinstance(vc, ViewChange):
                raise CodecError("NEW-VIEW must embed VIEW-CHANGEs")
            vcs.append(vc)
        digest, off = _read_bytes(data, off)
        uib, off = _read_bytes(data, off)
        return (
            NewView(
                replica_id=rid, new_view=nv, view_changes=tuple(vcs),
                ui=_parse_ui(uib), vcs_digest=digest,
            ),
            off,
        )
    if tag == _TAG_CHECKPOINT:
        rid, off = _read_u32(data, off)
        count, off = _read_u64(data, off)
        digest, off = _read_bytes(data, off)
        view, off = _read_u64(data, off)
        cv, off = _read_u64(data, off)
        bcount, off = _read_u32(data, off)
        bounds = []
        for _ in range(bcount):
            p, off = _read_u32(data, off)
            b, off = _read_u64(data, off)
            bounds.append((p, b))
        sig, off = _read_bytes(data, off)
        return (
            Checkpoint(
                replica_id=rid, count=count, digest=digest, view=view,
                cv=cv, bounds=tuple(bounds), signature=sig,
            ),
            off,
        )
    if tag == _TAG_LOG_BASE:
        rid, off = _read_u32(data, off)
        base, off = _read_u64(data, off)
        ccount, off = _read_u32(data, off)
        cert = []
        for _ in range(ccount):
            cb, off = _read_bytes(data, off)
            cp = unmarshal(cb, depth + 1)
            if not isinstance(cp, Checkpoint):
                raise CodecError("LOG-BASE cert entries must be CHECKPOINTs")
            cert.append(cp)
        return LogBase(replica_id=rid, base=base, cert=tuple(cert)), off
    if tag == _TAG_SNAPSHOT_REQ:
        rid, off = _read_u32(data, off)
        count, off = _read_u64(data, off)
        sig, off = _read_bytes(data, off)
        return SnapshotReq(replica_id=rid, count=count, signature=sig), off
    if tag == _TAG_SNAPSHOT_RESP:
        rid, off = _read_u32(data, off)
        count, off = _read_u64(data, off)
        view, off = _read_u64(data, off)
        cv, off = _read_u64(data, off)
        app, off = _read_bytes(data, off)
        wcount, off = _read_u32(data, off)
        marks = []
        for _ in range(wcount):
            c, off = _read_u32(data, off)
            s, off = _read_u64(data, off)
            marks.append((c, s))
        ccount, off = _read_u32(data, off)
        cert = []
        for _ in range(ccount):
            cb, off = _read_bytes(data, off)
            cp = unmarshal(cb, depth + 1)
            if not isinstance(cp, Checkpoint):
                raise CodecError("SNAPSHOT-RESP cert entries must be CHECKPOINTs")
            cert.append(cp)
        sig, off = _read_bytes(data, off)
        return (
            SnapshotResp(
                replica_id=rid, count=count, view=view, cv=cv,
                app_state=app, watermarks=tuple(marks), cert=tuple(cert),
                signature=sig,
            ),
            off,
        )
    if tag == _TAG_STATE_REQ:
        rid, off = _read_u32(data, off)
        count, off = _read_u64(data, off)
        soff, off = _read_u64(data, off)
        sig, off = _read_bytes(data, off)
        return (
            StateReq(replica_id=rid, count=count, offset=soff, signature=sig),
            off,
        )
    if tag == _TAG_STATE_CHUNK:
        rid, off = _read_u32(data, off)
        count, off = _read_u64(data, off)
        soff, off = _read_u64(data, off)
        total, off = _read_u64(data, off)
        chunk, off = _read_bytes(data, off)
        chain, off = _read_bytes(data, off)
        sig, off = _read_bytes(data, off)
        return (
            StateChunk(
                replica_id=rid, count=count, offset=soff, total=total,
                data=chunk, chain=chain, signature=sig,
            ),
            off,
        )
    if tag == _TAG_STATE_DONE:
        rid, off = _read_u32(data, off)
        count, off = _read_u64(data, off)
        view, off = _read_u64(data, off)
        cv, off = _read_u64(data, off)
        total, off = _read_u64(data, off)
        wcount, off = _read_u32(data, off)
        marks = []
        for _ in range(wcount):
            c, off = _read_u32(data, off)
            s, off = _read_u64(data, off)
            marks.append((c, s))
        ccount, off = _read_u32(data, off)
        cert = []
        for _ in range(ccount):
            cb, off = _read_bytes(data, off)
            cp = unmarshal(cb, depth + 1)
            if not isinstance(cp, Checkpoint):
                raise CodecError("STATE-DONE cert entries must be CHECKPOINTs")
            cert.append(cp)
        sig, off = _read_bytes(data, off)
        return (
            StateDone(
                replica_id=rid, count=count, view=view, cv=cv, total=total,
                watermarks=tuple(marks), cert=tuple(cert), signature=sig,
            ),
            off,
        )
    raise CodecError(f"unknown message tag {tag:#x}")


# ---------------------------------------------------------------------------
# Vectorized bundle decode (the batch-ingest runtime's codec stage).


def _intern_put(data: bytes, m: Message) -> None:
    """Insert one decoded message into the intern LRU with the same
    accumulated-bytes accounting as :func:`unmarshal`."""
    global _intern_bytes
    if len(data) >= _INTERN_MAX_BYTES // 4:
        return
    _intern[data] = m
    _intern_bytes += len(data)
    while _intern_bytes > _INTERN_MAX_BYTES:
        evicted, _ = _intern.popitem(last=False)
        _intern_bytes -= len(evicted)


def _decode_one(data: bytes):
    """Item-wise decode: a malformed frame becomes its CodecError VALUE
    (never raised), so one corrupt frame cannot poison a bundle."""
    try:
        return unmarshal(data)
    except CodecError as e:
        return e


# Below this many frames the numpy set-up costs more than it saves
# (measured on the dev container: 0.94x at 32 frames, 1.6x at 128); the
# scalar loop is the same item-wise contract either way.
_BATCH_MIN = 48
# Fixed REQUEST header: tag(1) + client u32 + seq u64 + mode(1) + oplen
# u32 + siglen u32 — the minimum well-formed REQUEST frame (empty op and
# empty signature).
_REQ_FIXED = 22


def _gather_be(arr: np.ndarray, offs: np.ndarray, width: int) -> np.ndarray:
    """Big-endian integer fields at per-frame offsets: ``width`` byte
    gathers composed into one uint64 column (the flat codec's fixed-width
    fields ARE contiguous bytes, so a field across the whole bundle is
    ``width`` fancy-indexed loads)."""
    v = np.zeros(len(offs), dtype=np.uint64)
    for k in range(width):
        v = (v << np.uint64(8)) | arr[offs + k].astype(np.uint64)
    return v


def unmarshal_batch(frames) -> List[object]:
    """Decode a bundle of flat wire frames, item-wise.

    Returns one entry per frame: the decoded :class:`Message`, or the
    :class:`CodecError` that frame produced (errors are VALUES here —
    a corrupt frame fails alone, never the bundle).

    The hot kind is vectorized: frames are classified by tag with one
    numpy gather over the concatenated bundle, and REQUEST frames — the
    client-stream hot path — have their fixed-width fields (client id,
    seq, read mode, length prefixes) extracted as whole-bundle array
    operations; only the final per-object construction is Python.  Any
    frame the vector checks cannot fully validate falls back to the
    scalar :func:`unmarshal`, so the two paths can never disagree on
    accept/reject (tests/test_batch_ingest.py pins this differentially).
    Interning semantics match :func:`unmarshal` exactly.
    """
    n = len(frames)
    if n < _BATCH_MIN:
        return [_decode_one(fr) for fr in frames]
    out: List[object] = [None] * n
    # Intern hits first (the n-replica fan-in makes these common), and
    # collect the rest for classification.  Duplicate internable frames
    # WITHIN the bundle collapse to one decode too — the scalar loop gets
    # that for free (frame k populates the intern frame k+1 hits), so the
    # batch path must match it or retransmit-heavy bundles decode twice.
    todo: List[int] = []
    first_seen: dict = {}
    dups: List[Tuple[int, int]] = []
    for i, fr in enumerate(frames):
        if fr and fr[0] in _INTERNABLE:
            m = _intern.get(fr)
            if m is not None:
                _intern.move_to_end(fr)
                out[i] = m
                continue
            j = first_seen.get(fr)
            if j is not None:
                dups.append((i, j))
                continue
            first_seen[fr] = i
        todo.append(i)
    if not todo:
        return out
    lens = np.fromiter((len(frames[i]) for i in todo), dtype=np.int64, count=len(todo))
    # Pad the tail so fixed-header gathers on a truncated LAST frame stay
    # in-bounds (their rows are discarded by the validity mask anyway).
    buf = b"".join([frames[i] for i in todo] + [b"\x00" * (_REQ_FIXED + 4)])
    arr = np.frombuffer(buf, dtype=np.uint8)
    offs = np.zeros(len(todo), dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    ends = offs + lens
    tags = np.where(lens > 0, arr[offs], -1)
    req_rows = np.nonzero((tags == _TAG_REQUEST) & (lens >= _REQ_FIXED))[0]
    vectored = np.zeros(len(todo), dtype=bool)
    if len(req_rows):
        base = offs[req_rows]
        end = ends[req_rows]
        cid = _gather_be(arr, base + 1, 4)
        seq = _gather_be(arr, base + 5, 8)
        mode = arr[base + 13].astype(np.int64)
        oplen = _gather_be(arr, base + 14, 4).astype(np.int64)
        op_end = base + 18 + oplen
        fits = (op_end + 4 <= end) & (mode <= 2)
        # Clamp the variable-offset gather to a row's own base when the
        # operation length already overruns — the row is discarded, the
        # gather just has to stay in-bounds.
        sig_at = np.where(fits, op_end, base)
        siglen = _gather_be(arr, sig_at, 4).astype(np.int64)
        ok = fits & (op_end + 4 + siglen == end)
        ok_rows = req_rows[ok]
        vectored[ok_rows] = True
        cid_l = cid[ok].tolist()
        seq_l = seq[ok].tolist()
        mode_l = mode[ok].tolist()
        op0_l = (base[ok] + 18).tolist()
        ope_l = op_end[ok].tolist()
        end_l = end[ok].tolist()
        for j, row in enumerate(ok_rows.tolist()):
            i = todo[row]
            ope = ope_l[j]
            m = Request(
                client_id=cid_l[j],
                seq=seq_l[j],
                operation=buf[op0_l[j] : ope],
                signature=buf[ope + 4 : end_l[j]],
                read_mode=mode_l[j],
            )
            out[i] = m
            _intern_put(frames[i], m)
    # Everything the vector path did not fully validate — other kinds,
    # short/overrun/trailing-byte REQUESTs — takes the scalar decoder so
    # malformed frames produce their exact per-item CodecError.
    for row in np.nonzero(~vectored)[0].tolist():
        i = todo[row]
        out[i] = _decode_one(frames[i])
    for i, j in dups:
        out[i] = out[j]
    return out


def pack_multi(frames) -> bytes:
    """Coalesce several wire frames into one transport frame (len==1 stays
    bare — the container only exists to amortize per-frame stream costs)."""
    if len(frames) == 1:
        return frames[0]
    out = [bytes([_TAG_MULTI]), _pack_u32(len(frames))]
    for fr in frames:
        out.append(_pack_u32(len(fr)))
        out.append(fr)
    return b"".join(out)


def split_multi(data: bytes):
    """Inverse of :func:`pack_multi`: a bare frame comes back as [data];
    a container is split into its messages (malformed containers raise
    CodecError like any bad wire bytes)."""
    if not data or data[0] != _TAG_MULTI:
        return [data]
    n, off = _read_u32(data, 1)
    if n > 65536:
        raise CodecError(f"multi frame claims {n} messages")
    frames = []
    for _ in range(n):
        ln, off = _read_u32(data, off)
        if off + ln > len(data):
            raise CodecError("truncated multi frame")
        frames.append(data[off : off + ln])
        off += ln
    if off != len(data):
        raise CodecError("trailing bytes in multi frame")
    return frames


def pack_group(gid: int, frame: bytes) -> bytes:
    """Wrap one wire frame in the group envelope.  Group 0 stays BARE —
    the untagged encoding IS group 0 (single-group wire compatibility),
    and keeping one canonical encoding per (gid, frame) means the demux
    never has to dedup tagged-vs-untagged spellings of the same frame."""
    if gid == 0:
        return frame
    if not 0 < gid <= GROUP_MAX:
        raise CodecError(f"group id out of range: {gid}")
    return bytes([_TAG_GROUP]) + _U16.pack(gid) + frame


def split_group(frame: bytes):
    """Inverse of :func:`pack_group`: ``(gid, inner frame)``.  Untagged
    frames are group 0; a truncated envelope raises like any bad wire
    bytes."""
    if not frame or frame[0] != _TAG_GROUP:
        return 0, frame
    if len(frame) < 3:
        raise CodecError("truncated group envelope")
    return _U16.unpack_from(frame, 1)[0], frame[3:]


def split_group_batch(frames):
    """Whole-bundle group demux: ``[(gid, inner), ...]`` — the grouped
    ingest tick's classification stage.  Large bundles classify the
    envelope tag with one numpy gather over the concatenated frames
    (the same trick :func:`unmarshal_batch` uses for message tags);
    malformed envelopes become item-wise ``CodecError`` VALUES in the
    gid slot (``(err, frame)``) so one bad frame cannot poison the
    bundle."""
    n = len(frames)
    out = []
    if n < _BATCH_MIN:
        for fr in frames:
            try:
                out.append(split_group(fr))
            except CodecError as e:
                out.append((e, fr))
        return out
    lens = np.fromiter((len(fr) for fr in frames), dtype=np.int64, count=n)
    buf = b"".join(frames) + b"\x00" * 3
    arr = np.frombuffer(buf, dtype=np.uint8)
    offs = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    tags = np.where(lens > 0, arr[offs], -1)
    grouped = tags == _TAG_GROUP
    gids = np.where(
        grouped & (lens >= 3), _gather_be(arr, offs + 1, 2), 0
    ).astype(np.int64)
    grouped_l = grouped.tolist()
    gids_l = gids.tolist()
    lens_l = lens.tolist()
    for i, fr in enumerate(frames):
        if not grouped_l[i]:
            out.append((0, fr))
        elif lens_l[i] < 3:
            out.append((CodecError("truncated group envelope"), fr))
        else:
            out.append((gids_l[i], fr[3:]))
    return out


# Coalescing bounds shared by every stream pump: one frame can neither
# starve its stream (message count) nor trip gRPC's 4MB default (bytes).
MULTI_MAX_MSGS = 128
MULTI_MAX_BYTES = 256 * 1024


def drain_multi(first: bytes, queue, encode=None, stop=None):
    """Coalesce ``first`` plus whatever is ALREADY queued into one packed
    frame -> (frame, saw_stop).  ``encode`` maps queue items to wire bytes
    (identity by default); ``stop`` is an optional sentinel that ends the
    drain and is reported instead of being packed.  Never blocks — only
    items reachable via ``get_nowait`` ride along."""
    frames = [first]
    total = len(first)
    saw_stop = False
    while (
        len(frames) < MULTI_MAX_MSGS
        and total < MULTI_MAX_BYTES
        and not queue.empty()
    ):
        item = queue.get_nowait()
        if stop is not None and item is stop:
            saw_stop = True
            break
        fr = encode(item) if encode is not None else item
        frames.append(fr)
        total += len(fr)
    return pack_multi(frames), saw_stop
