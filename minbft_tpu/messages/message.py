"""Typed protocol messages.

Mirrors the abstract message hierarchy of the reference
(reference messages/api.go:35-118): Message → {ClientMessage, ReplicaMessage,
PeerMessage, CertifiedMessage, SignedMessage} → six concrete kinds.

Embedding structure is preserved exactly: a COMMIT embeds the full PREPARE it
commits to, and a PREPARE embeds the full REQUEST it orders
(reference messages/api.go:88-101).  That embedding is what lets a backup
re-validate everything it acts on without extra round trips.

Unlike the reference's protobuf implementation, serialization here is a flat,
deterministic, hand-rolled binary codec (:mod:`minbft_tpu.messages.codec`) —
there is no schema compiler in the loop and byte layouts are canonical, which
matters because signatures and USIG certificates are computed over
:func:`minbft_tpu.messages.authen.authen_bytes` of these exact bytes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass
class UI:
    """Unique Identifier produced by a USIG.

    Mirrors reference usig/usig.go:44-51: a monotonic counter value plus a
    certificate binding (message digest, epoch, counter) under the replica's
    trusted key.  Marshalled big-endian (reference usig/usig.go:84-102).
    """

    counter: int
    cert: bytes = b""

    def to_bytes(self) -> bytes:
        return self.counter.to_bytes(8, "big") + self.cert

    @classmethod
    def from_bytes(cls, data: bytes) -> "UI":
        if len(data) < 8:
            raise ValueError("UI too short")
        return cls(counter=int.from_bytes(data[:8], "big"), cert=data[8:])


class Message:
    """Base for all protocol messages."""

    KIND: str = "?"

    def to_bytes(self) -> bytes:
        from . import codec

        return codec.marshal(self)


@dataclasses.dataclass
class Hello(Message):
    """Peer handshake announcing the sender's replica ID.

    Sent once when a replica opens a peer connection; the receiver responds by
    streaming its broadcast + unicast-to-that-peer message logs
    (reference core/message-handling.go:269-290, 316-350).

    **Signed** (beyond the reference, which binds the unicast replay to an
    unauthenticated id — reference core/message-handling.go:316-350): the
    receiver verifies the replica signature over the claimed id before
    attaching the sender's unicast log, so an id-spoofing peer cannot
    subscribe to another replica's unicast stream.  A *replayed* signed
    HELLO still subscribes the replayer — harmless, but only because of
    the unicast-log CONTENT invariant pinned at
    ``UNICAST_LOG_MESSAGES`` below: read that note before adding any
    kind to a unicast log.

    ``resume_counter`` makes the replay RESUMABLE: the dialer stamps the
    next UI counter it expects from this peer (everything below it is
    already captured), and the publisher skips certified log entries
    with lower counters.  Through a lossy link this is the difference
    between healing a gap and a redial storm — a full replay must
    traverse the whole retained log intact to reach the gap counter
    (success probability ``(1-p)^N``), a resumed one only the missed
    tail.  Signed along with the id, so an in-path attacker cannot
    inflate it to starve the subscriber of entries it still needs.  A
    replayed old HELLO carries a STALE (lower) resume point — more
    replay, still harmless; ``0`` (the default) means replay everything.
    The wire format is NOT backward compatible (the u64 sits between
    replica_id and the signature, and both codec and authen-bytes
    include it) — all peers of a cluster run the same build, as
    everywhere else in this codec.
    """

    KIND = "HELLO"
    replica_id: int
    signature: bytes = b""
    resume_counter: int = 0


@dataclasses.dataclass
class Request(Message):
    """Client request: (client, seq, operation), signed by the client
    (reference messages/api.go:47-56)."""

    KIND = "REQUEST"
    client_id: int
    seq: int
    operation: bytes
    signature: bytes = b""
    # Read-only support (reference roadmap README.md:503-504), covered by
    # the client's signature (authen.py) so it cannot be flipped in
    # flight: 0 = ordered write; 1 = FAST read (answered from committed
    # state without ordering — never valid inside a PREPARE); 2 = ORDERED
    # read (rides consensus for linearization but executes via
    # consumer.query, mutating nothing — the fast read's fallback).
    read_mode: int = 0

    @property
    def is_read(self) -> bool:
        return self.read_mode != 0

    @property
    def is_fast_read(self) -> bool:
        return self.read_mode == 1


@dataclasses.dataclass
class Reply(Message):
    """Replica's signed reply to a client (reference messages/api.go:75-86)."""

    KIND = "REPLY"
    replica_id: int
    client_id: int
    seq: int
    result: bytes
    signature: bytes = b""
    # Marks a read-only fast-path answer; covered by the replica's
    # signature so an ordered reply cannot be replayed as a read.
    read_only: bool = False
    # Signed failure signal for read-only requests (query unsupported or
    # raised): a quorum of these resolves the client's request with a
    # typed error instead of a fabricated result — and instead of NO
    # reply, which would park the replica-side reply waiters forever.
    error: bool = False


@dataclasses.dataclass
class Busy(Message):
    """Replica's signed admission-shed signal to a client (ISSUE 15).

    Emitted instead of silence when the replica sheds an inbound REQUEST
    at the admission boundary (rx queue saturated / stream processor out
    of permits).  Signed like a Reply so a network adversary cannot forge
    backoff and starve a client; ``retry_after_ms`` is a hint scaled by
    the observed rx saturation, honored by the client's RetransmitBackoff
    (retransmits are suppressed until the hold expires, the pending
    request itself stays live).
    """

    KIND = "BUSY"
    replica_id: int
    client_id: int
    seq: int
    retry_after_ms: int
    signature: bytes = b""


@dataclasses.dataclass(init=False)
class Prepare(Message):
    """Primary's ordering proposal for a **batch** of requests, certified by
    the primary's USIG (reference messages/api.go:58-65).

    The reference orders one request per PREPARE; request batching is an
    explicitly unimplemented roadmap item there (reference README.md:505).
    Here a PREPARE carries an ordered tuple of requests assigned to one
    USIG counter value: the batch commits atomically and executes in list
    order, amortizing the PREPARE/COMMIT round (and its UI verifications)
    over the whole batch.  A single-request PREPARE (``request=`` keyword)
    is the degenerate batch, keeping reference-shaped call sites working.
    """

    KIND = "PREPARE"
    replica_id: int
    view: int
    requests: Tuple[Request, ...]
    ui: Optional[UI] = None
    # Canonical digest of the (possibly stubbed-away) request batch: a
    # **stub** PREPARE carries ``requests=()`` with this digest filled, and
    # has the *same* authen bytes as the full original — so the primary's
    # UI certificate (which also binds view and counter) still verifies on
    # it.  Stubs appear only inside checkpoint-truncated VIEW-CHANGE logs
    # and log replays, proving a counter slot's occupant without carrying
    # the batch content; live processing captures them but never applies
    # or executes them (a stub reaching execution would let a Byzantine
    # primary equivocate full-vs-stub under one UI).
    requests_digest: bytes = b""

    def __init__(
        self,
        replica_id: int,
        view: int,
        request: Optional[Request] = None,
        ui: Optional[UI] = None,
        requests: Optional[Sequence[Request]] = None,
        requests_digest: bytes = b"",
    ):
        if request is not None and requests is not None:
            raise ValueError("pass at most one of request= / requests=")
        self.replica_id = replica_id
        self.view = view
        self.requests = (
            (request,) if request is not None else tuple(requests or ())
        )
        if not self.requests and not requests_digest:
            raise ValueError(
                "PREPARE must order at least one request (or be a stub "
                "carrying the batch digest)"
            )
        self.ui = ui
        self.requests_digest = requests_digest

    @property
    def request(self) -> Request:
        """The first (often only) request of the batch."""
        return self.requests[0]

    @property
    def is_stub(self) -> bool:
        """True for a checkpoint-covered stub (digest kept, batch dropped)."""
        return not self.requests


@dataclasses.dataclass
class Commit(Message):
    """Backup's commitment to a PREPARE; embeds the full PREPARE and is
    certified by the backup's USIG (reference messages/api.go:67-73)."""

    KIND = "COMMIT"
    replica_id: int
    prepare: Prepare
    ui: Optional[UI] = None


@dataclasses.dataclass
class ReqViewChange(Message):
    """Signed request to move to a new view
    (reference messages/api.go:103-110)."""

    KIND = "REQ-VIEW-CHANGE"
    replica_id: int
    new_view: int
    signature: bytes = b""


@dataclasses.dataclass
class ViewChange(Message):
    """A replica's vote to enter ``new_view``, certified by its USIG and
    carrying its complete certified-message log since the genesis
    checkpoint (**beyond the reference**, whose view change stops at the
    REQ-VIEW-CHANGE demand — reference core/message-handling.go:419 "Not
    implemented"; protocol per the MinBFT paper §IV-B).

    The log is what makes n = 2f+1 view changes safe: a quorum member
    cannot *omit* a message it sent — every certified message consumes one
    USIG counter value, so receivers check the log's counters are exactly
    1..k with the VIEW-CHANGE itself at k+1, and any omission is a visible
    gap.  Whoever of the commit quorum lands in the view-change quorum
    therefore exposes the commitment evidence, faulty or not.

    Prior VIEW-CHANGE/NEW-VIEW messages appear in the log **trimmed**:
    their own payload emptied and ``log_digest`` carrying the canonical
    digest of what they covered.  A trimmed copy has the *same* authen
    bytes as the original (the digest substitutes for the recomputation),
    so the original UI certificate still verifies — the counter slot stays
    provably occupied without nesting the prior log, which would otherwise
    double the message per view change (exponential growth).  Log size is
    thus linear in certified PREPAREs/COMMITs — the same unboundedness as
    the reference's in-memory message log; checkpointing/GC is a roadmap
    item in both builds.
    """

    KIND = "VIEW-CHANGE"
    replica_id: int
    new_view: int
    log: Tuple[Message, ...]
    ui: Optional[UI] = None
    # Canonical digest of the (possibly trimmed-away) log contents; filled
    # on the wire so trimmed copies keep the original's authen bytes.
    log_digest: bytes = b""
    # Checkpoint truncation (phase 2 — core/checkpoint.py): the log may
    # omit the sender's certified messages with counters <= log_base,
    # provided checkpoint_cert carries f+1 matching CHECKPOINTs whose
    # per-peer coverage bounds for this sender are >= log_base — at least
    # one attester is correct, so the dropped prefix provably holds no
    # commit evidence beyond the certified checkpoint.  log_base == 0 is
    # the untruncated (genesis) form.
    log_base: int = 0
    checkpoint_cert: Tuple["Checkpoint", ...] = ()


@dataclasses.dataclass
class NewView(Message):
    """The new primary's certified announcement of ``new_view``: carries
    f+1 VIEW-CHANGEs (its quorum, own included) from which every replica
    deterministically derives the re-proposal set (see
    :func:`minbft_tpu.core.viewchange.compute_new_view_set`).  The
    NEW-VIEW's own UI counter is the base the new primary's PREPARE
    counters continue from."""

    KIND = "NEW-VIEW"
    replica_id: int
    new_view: int
    view_changes: Tuple["ViewChange", ...]
    ui: Optional[UI] = None
    # Same trimming mechanism as ViewChange.log_digest.
    vcs_digest: bytes = b""


@dataclasses.dataclass
class Checkpoint(Message):
    """A replica's **signed** snapshot claim: after executing ``count``
    requests — through batch ``(view, cv)``, which every correct replica
    reaches with the same deterministic execution history — its composite
    state digest is ``digest``.  f+1 matching claims on
    (count, view, cv, digest) make the checkpoint *stable* (beyond the
    reference, whose checkpointing is a reserved config knob —
    README.md:492-493; see :mod:`minbft_tpu.core.checkpoint`).

    Signed, not USIG-certified: a checkpoint consumes no USIG counter, so
    the primary emits them too without splitting its prepare-CV sequence
    (closing the liveness margin where f crashed backups left only f
    claims — the round-3 advisor finding), and checkpoint claims never
    occupy slots in the certified log the view change reasons about.

    ``bounds`` is the sender's per-peer coverage attestation: for each
    peer p it has processed, the highest own-USIG-counter b such that
    every certified message of p with counter <= b is *covered* by this
    checkpoint (its batch executed within (view, cv), or its view-change
    transition concluded at a view <= view).  f+1 checkpoints each with
    bounds[p] >= β license p to truncate its log prefix 1..β — the
    validator-checkable completeness that makes GC safe at n = 2f+1,
    where quorum intersections can be entirely Byzantine and hiding
    evidence must be structurally impossible.
    """

    KIND = "CHECKPOINT"
    replica_id: int
    count: int
    digest: bytes
    view: int = 0
    cv: int = 0
    bounds: Tuple[Tuple[int, int], ...] = ()  # sorted (peer_id, bound)
    signature: bytes = b""

    def bound_for(self, peer_id: int) -> int:
        for p, b in self.bounds:
            if p == peer_id:
                return b
        return 0


@dataclasses.dataclass
class LogBase(Message):
    """Log-truncation announcement, streamed first when a replica's
    broadcast log no longer starts at USIG counter 1: counters 1..base are
    gone, and ``cert`` (f+1 matching CHECKPOINTs, each with a coverage
    bound for this sender >= base) proves the dropped prefix held no
    evidence beyond the certified checkpoint.  Carries no signature of its
    own — the embedded certificate is the entire claim, and understating
    ``base`` only withholds the sender's own messages (self-harm).

    A receiver fast-forwards its per-peer counter capture to base+1; if
    its own execution count is behind the certificate's, it must fetch the
    certified state first (:class:`SnapshotReq`)."""

    KIND = "LOG-BASE"
    replica_id: int
    base: int
    cert: Tuple[Checkpoint, ...] = ()


@dataclasses.dataclass
class SnapshotReq(Message):
    """Signed request for the state snapshot at stable checkpoint
    ``count`` (state transfer, phase 2 of checkpointing).  A responder
    that no longer retains that exact snapshot may answer with a NEWER
    certified one, attaching its certificate (see SnapshotResp.cert)."""

    KIND = "SNAPSHOT-REQ"
    replica_id: int
    count: int = 0
    signature: bytes = b""


@dataclasses.dataclass
class SnapshotResp(Message):
    """Signed state-transfer payload: the application snapshot plus the
    deterministic protocol watermarks at checkpoint ``count``.  The
    receiver verifies the composite checkpoint digest recomputed from this
    payload against an f+1-certified stable digest before installing —
    the sender's signature authenticates the unicast, the certificate
    authenticates the *content*.  ``cert`` is attached when the response
    is for a newer checkpoint than requested (the exact one aged out of
    the retention window); the receiver validates it independently and
    upgrades its target."""

    KIND = "SNAPSHOT-RESP"
    replica_id: int
    count: int
    view: int
    cv: int
    app_state: bytes
    # Sorted (client, seq) pairs; per client: retire floor first, then
    # the individually retired seqs above it (clientstate.retire_watermarks).
    watermarks: Tuple[Tuple[int, int], ...] = ()
    cert: Tuple[Checkpoint, ...] = ()
    signature: bytes = b""


@dataclasses.dataclass
class StateReq(Message):
    """Signed request for a **chunked** state stream starting at byte
    ``offset`` of the snapshot at stable checkpoint ``count`` (the
    ``Hello.resume_counter`` pattern generalized to state — ISSUE 20).
    ``count == 0`` asks for the responder's latest stable snapshot;
    ``offset > 0`` resumes a transfer severed mid-stream: the requester
    stamps how many bytes it has already verified against the chunk
    digest chain, and the responder serves only the missing tail.  The
    offset is signed with the id, so an in-path attacker can neither
    rewind the stream (waste) nor fast-forward it (starve the requester
    of bytes it still needs)."""

    KIND = "STATE-REQ"
    replica_id: int
    count: int = 0
    offset: int = 0
    signature: bytes = b""


@dataclasses.dataclass
class StateChunk(Message):
    """One signed slice of a snapshot stream: ``data`` is the snapshot
    bytes at ``offset`` of the ``total``-byte snapshot certified at
    stable checkpoint ``count``.  ``chain`` is the running digest
    ``chain_k = sha256(chain_{k-1} || data_k)`` (empty-string seed),
    recomputed by the responder from byte 0 regardless of the resume
    offset — chunking is deterministic (fixed chunk size), so any two
    honest responders produce byte-identical chunks and a resumed fetch
    can switch peers mid-stream.  The receiver extends its own chain
    and drops the transfer on the FIRST mismatching chunk (early
    Byzantine detection), but final authority stays with the f+1
    checkpoint certificate the assembled snapshot is verified against
    before install — the chain alone proves nothing."""

    KIND = "STATE-CHUNK"
    replica_id: int
    count: int
    offset: int
    total: int
    data: bytes
    chain: bytes = b""
    signature: bytes = b""


@dataclasses.dataclass
class StateDone(Message):
    """Signed terminal frame of a chunked state stream: the protocol
    position (view, cv) and deterministic watermarks at checkpoint
    ``count``, with ``total`` pinning the stream length.  ``cert`` is
    attached when the stream served a NEWER stable checkpoint than the
    requested one (the exact snapshot aged out of the retention
    window); the receiver validates it independently — exactly the
    SnapshotResp upgrade rule — before accepting the new target."""

    KIND = "STATE-DONE"
    replica_id: int
    count: int
    view: int
    cv: int
    total: int
    # Same layout as SnapshotResp.watermarks.
    watermarks: Tuple[Tuple[int, int], ...] = ()
    cert: Tuple[Checkpoint, ...] = ()
    signature: bytes = b""


# ---------------------------------------------------------------------------
# Classification helpers (reference messages/api.go interface hierarchy).

CLIENT_MESSAGES = (Request,)
REPLICA_MESSAGES = (
    Reply, Busy, Prepare, Commit, ReqViewChange, ViewChange, NewView,
    Checkpoint, LogBase, SnapshotReq, SnapshotResp, StateReq, StateChunk,
    StateDone,
)
PEER_MESSAGES = (
    Prepare, Commit, ReqViewChange, ViewChange, NewView, Checkpoint,
    LogBase, SnapshotReq, SnapshotResp, StateReq, StateChunk, StateDone,
)
CERTIFIED_MESSAGES = (Prepare, Commit, ViewChange, NewView)  # carry a USIG UI
SIGNED_MESSAGES = (
    Request, Reply, Busy, ReqViewChange, Checkpoint, SnapshotReq,
    SnapshotResp, StateReq, StateChunk, StateDone,
)  # carry a plain signature

# The kinds that may enter a per-peer UNICAST log (forwarded starved
# REQUESTs and the state-transfer pair) — enforced at the core's append
# sites (message_handling._unicast_append).
#
# Replay-harmlessness invariant (the reason a REPLAYED signed HELLO is
# safe to serve — see Hello): every kind listed here is public protocol
# content, individually signed or certificate-backed, with NO
# confidentiality claim — so an extra unicast subscriber obtained by
# replaying a peer's HELLO learns nothing and steals nothing (log streams
# are replay-then-follow; the genuine peer keeps receiving).  This note
# lives NEXT TO the content definition on purpose: if a unicast log ever
# gains a kind carrying non-public content (a secret-bearing state
# transfer, an unencrypted key share), the HELLO handshake must gain
# replay protection (a challenge nonce) IN THE SAME CHANGE, or a replayed
# HELLO becomes an exfiltration channel (ADVICE low-#2).
# The chunked state-transfer trio (ISSUE 20) satisfies the invariant the
# same way the monolithic pair does: chunks carry slices of a snapshot
# whose WHOLE content is certificate-backed public protocol state.
UNICAST_LOG_MESSAGES = (
    Request, SnapshotReq, SnapshotResp, StateReq, StateChunk, StateDone,
)


def is_peer_message(m: Message) -> bool:
    return isinstance(m, PEER_MESSAGES)


def is_client_message(m: Message) -> bool:
    return isinstance(m, CLIENT_MESSAGES)
