"""Typed protocol messages.

Mirrors the abstract message hierarchy of the reference
(reference messages/api.go:35-118): Message → {ClientMessage, ReplicaMessage,
PeerMessage, CertifiedMessage, SignedMessage} → six concrete kinds.

Embedding structure is preserved exactly: a COMMIT embeds the full PREPARE it
commits to, and a PREPARE embeds the full REQUEST it orders
(reference messages/api.go:88-101).  That embedding is what lets a backup
re-validate everything it acts on without extra round trips.

Unlike the reference's protobuf implementation, serialization here is a flat,
deterministic, hand-rolled binary codec (:mod:`minbft_tpu.messages.codec`) —
there is no schema compiler in the loop and byte layouts are canonical, which
matters because signatures and USIG certificates are computed over
:func:`minbft_tpu.messages.authen.authen_bytes` of these exact bytes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass
class UI:
    """Unique Identifier produced by a USIG.

    Mirrors reference usig/usig.go:44-51: a monotonic counter value plus a
    certificate binding (message digest, epoch, counter) under the replica's
    trusted key.  Marshalled big-endian (reference usig/usig.go:84-102).
    """

    counter: int
    cert: bytes = b""

    def to_bytes(self) -> bytes:
        return self.counter.to_bytes(8, "big") + self.cert

    @classmethod
    def from_bytes(cls, data: bytes) -> "UI":
        if len(data) < 8:
            raise ValueError("UI too short")
        return cls(counter=int.from_bytes(data[:8], "big"), cert=data[8:])


class Message:
    """Base for all protocol messages."""

    KIND: str = "?"

    def to_bytes(self) -> bytes:
        from . import codec

        return codec.marshal(self)


@dataclasses.dataclass
class Hello(Message):
    """Peer handshake announcing the sender's replica ID.

    Sent once when a replica opens a peer connection; the receiver responds by
    streaming its broadcast + unicast-to-that-peer message logs
    (reference core/message-handling.go:269-290, 316-350).
    """

    KIND = "HELLO"
    replica_id: int


@dataclasses.dataclass
class Request(Message):
    """Client request: (client, seq, operation), signed by the client
    (reference messages/api.go:47-56)."""

    KIND = "REQUEST"
    client_id: int
    seq: int
    operation: bytes
    signature: bytes = b""


@dataclasses.dataclass
class Reply(Message):
    """Replica's signed reply to a client (reference messages/api.go:75-86)."""

    KIND = "REPLY"
    replica_id: int
    client_id: int
    seq: int
    result: bytes
    signature: bytes = b""


@dataclasses.dataclass(init=False)
class Prepare(Message):
    """Primary's ordering proposal for a **batch** of requests, certified by
    the primary's USIG (reference messages/api.go:58-65).

    The reference orders one request per PREPARE; request batching is an
    explicitly unimplemented roadmap item there (reference README.md:505).
    Here a PREPARE carries an ordered tuple of requests assigned to one
    USIG counter value: the batch commits atomically and executes in list
    order, amortizing the PREPARE/COMMIT round (and its UI verifications)
    over the whole batch.  A single-request PREPARE (``request=`` keyword)
    is the degenerate batch, keeping reference-shaped call sites working.
    """

    KIND = "PREPARE"
    replica_id: int
    view: int
    requests: Tuple[Request, ...]
    ui: Optional[UI] = None

    def __init__(
        self,
        replica_id: int,
        view: int,
        request: Optional[Request] = None,
        ui: Optional[UI] = None,
        requests: Optional[Sequence[Request]] = None,
    ):
        if (request is None) == (requests is None):
            raise ValueError("pass exactly one of request= / requests=")
        self.replica_id = replica_id
        self.view = view
        self.requests = (request,) if request is not None else tuple(requests)
        if not self.requests:
            raise ValueError("PREPARE must order at least one request")
        self.ui = ui

    @property
    def request(self) -> Request:
        """The first (often only) request of the batch."""
        return self.requests[0]


@dataclasses.dataclass
class Commit(Message):
    """Backup's commitment to a PREPARE; embeds the full PREPARE and is
    certified by the backup's USIG (reference messages/api.go:67-73)."""

    KIND = "COMMIT"
    replica_id: int
    prepare: Prepare
    ui: Optional[UI] = None


@dataclasses.dataclass
class ReqViewChange(Message):
    """Signed request to move to a new view
    (reference messages/api.go:103-110)."""

    KIND = "REQ-VIEW-CHANGE"
    replica_id: int
    new_view: int
    signature: bytes = b""


@dataclasses.dataclass
class ViewChange(Message):
    """A replica's vote to enter ``new_view``, certified by its USIG and
    carrying its complete certified-message log since the genesis
    checkpoint (**beyond the reference**, whose view change stops at the
    REQ-VIEW-CHANGE demand — reference core/message-handling.go:419 "Not
    implemented"; protocol per the MinBFT paper §IV-B).

    The log is what makes n = 2f+1 view changes safe: a quorum member
    cannot *omit* a message it sent — every certified message consumes one
    USIG counter value, so receivers check the log's counters are exactly
    1..k with the VIEW-CHANGE itself at k+1, and any omission is a visible
    gap.  Whoever of the commit quorum lands in the view-change quorum
    therefore exposes the commitment evidence, faulty or not.

    Prior VIEW-CHANGE/NEW-VIEW messages appear in the log **trimmed**:
    their own payload emptied and ``log_digest`` carrying the canonical
    digest of what they covered.  A trimmed copy has the *same* authen
    bytes as the original (the digest substitutes for the recomputation),
    so the original UI certificate still verifies — the counter slot stays
    provably occupied without nesting the prior log, which would otherwise
    double the message per view change (exponential growth).  Log size is
    thus linear in certified PREPAREs/COMMITs — the same unboundedness as
    the reference's in-memory message log; checkpointing/GC is a roadmap
    item in both builds.
    """

    KIND = "VIEW-CHANGE"
    replica_id: int
    new_view: int
    log: Tuple[Message, ...]
    ui: Optional[UI] = None
    # Canonical digest of the (possibly trimmed-away) log contents; filled
    # on the wire so trimmed copies keep the original's authen bytes.
    log_digest: bytes = b""


@dataclasses.dataclass
class NewView(Message):
    """The new primary's certified announcement of ``new_view``: carries
    f+1 VIEW-CHANGEs (its quorum, own included) from which every replica
    deterministically derives the re-proposal set (see
    :func:`minbft_tpu.core.viewchange.compute_new_view_set`).  The
    NEW-VIEW's own UI counter is the base the new primary's PREPARE
    counters continue from."""

    KIND = "NEW-VIEW"
    replica_id: int
    new_view: int
    view_changes: Tuple["ViewChange", ...]
    ui: Optional[UI] = None
    # Same trimming mechanism as ViewChange.log_digest.
    vcs_digest: bytes = b""


@dataclasses.dataclass
class Checkpoint(Message):
    """A replica's certified snapshot claim: after executing ``count``
    requests its state machine digest is ``digest``.  f+1 matching
    claims make the checkpoint *stable* (beyond the reference, whose
    checkpointing is a reserved config knob — README.md:492-493;
    see :mod:`minbft_tpu.core.checkpoint`)."""

    KIND = "CHECKPOINT"
    replica_id: int
    count: int
    digest: bytes
    ui: Optional[UI] = None


# ---------------------------------------------------------------------------
# Classification helpers (reference messages/api.go interface hierarchy).

CLIENT_MESSAGES = (Request,)
REPLICA_MESSAGES = (
    Reply, Prepare, Commit, ReqViewChange, ViewChange, NewView, Checkpoint,
)
PEER_MESSAGES = (Prepare, Commit, ReqViewChange, ViewChange, NewView, Checkpoint)
CERTIFIED_MESSAGES = (
    Prepare, Commit, ViewChange, NewView, Checkpoint,
)  # carry a USIG UI
SIGNED_MESSAGES = (Request, Reply, ReqViewChange)  # carry a plain signature


def is_peer_message(m: Message) -> bool:
    return isinstance(m, PEER_MESSAGES)


def is_client_message(m: Message) -> bool:
    return isinstance(m, CLIENT_MESSAGES)
