"""Typed protocol messages.

Mirrors the abstract message hierarchy of the reference
(reference messages/api.go:35-118): Message → {ClientMessage, ReplicaMessage,
PeerMessage, CertifiedMessage, SignedMessage} → six concrete kinds.

Embedding structure is preserved exactly: a COMMIT embeds the full PREPARE it
commits to, and a PREPARE embeds the full REQUEST it orders
(reference messages/api.go:88-101).  That embedding is what lets a backup
re-validate everything it acts on without extra round trips.

Unlike the reference's protobuf implementation, serialization here is a flat,
deterministic, hand-rolled binary codec (:mod:`minbft_tpu.messages.codec`) —
there is no schema compiler in the loop and byte layouts are canonical, which
matters because signatures and USIG certificates are computed over
:func:`minbft_tpu.messages.authen.authen_bytes` of these exact bytes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass
class UI:
    """Unique Identifier produced by a USIG.

    Mirrors reference usig/usig.go:44-51: a monotonic counter value plus a
    certificate binding (message digest, epoch, counter) under the replica's
    trusted key.  Marshalled big-endian (reference usig/usig.go:84-102).
    """

    counter: int
    cert: bytes = b""

    def to_bytes(self) -> bytes:
        return self.counter.to_bytes(8, "big") + self.cert

    @classmethod
    def from_bytes(cls, data: bytes) -> "UI":
        if len(data) < 8:
            raise ValueError("UI too short")
        return cls(counter=int.from_bytes(data[:8], "big"), cert=data[8:])


class Message:
    """Base for all protocol messages."""

    KIND: str = "?"

    def to_bytes(self) -> bytes:
        from . import codec

        return codec.marshal(self)


@dataclasses.dataclass
class Hello(Message):
    """Peer handshake announcing the sender's replica ID.

    Sent once when a replica opens a peer connection; the receiver responds by
    streaming its broadcast + unicast-to-that-peer message logs
    (reference core/message-handling.go:269-290, 316-350).
    """

    KIND = "HELLO"
    replica_id: int


@dataclasses.dataclass
class Request(Message):
    """Client request: (client, seq, operation), signed by the client
    (reference messages/api.go:47-56)."""

    KIND = "REQUEST"
    client_id: int
    seq: int
    operation: bytes
    signature: bytes = b""


@dataclasses.dataclass
class Reply(Message):
    """Replica's signed reply to a client (reference messages/api.go:75-86)."""

    KIND = "REPLY"
    replica_id: int
    client_id: int
    seq: int
    result: bytes
    signature: bytes = b""


@dataclasses.dataclass(init=False)
class Prepare(Message):
    """Primary's ordering proposal for a **batch** of requests, certified by
    the primary's USIG (reference messages/api.go:58-65).

    The reference orders one request per PREPARE; request batching is an
    explicitly unimplemented roadmap item there (reference README.md:505).
    Here a PREPARE carries an ordered tuple of requests assigned to one
    USIG counter value: the batch commits atomically and executes in list
    order, amortizing the PREPARE/COMMIT round (and its UI verifications)
    over the whole batch.  A single-request PREPARE (``request=`` keyword)
    is the degenerate batch, keeping reference-shaped call sites working.
    """

    KIND = "PREPARE"
    replica_id: int
    view: int
    requests: Tuple[Request, ...]
    ui: Optional[UI] = None

    def __init__(
        self,
        replica_id: int,
        view: int,
        request: Optional[Request] = None,
        ui: Optional[UI] = None,
        requests: Optional[Sequence[Request]] = None,
    ):
        if (request is None) == (requests is None):
            raise ValueError("pass exactly one of request= / requests=")
        self.replica_id = replica_id
        self.view = view
        self.requests = (request,) if request is not None else tuple(requests)
        if not self.requests:
            raise ValueError("PREPARE must order at least one request")
        self.ui = ui

    @property
    def request(self) -> Request:
        """The first (often only) request of the batch."""
        return self.requests[0]


@dataclasses.dataclass
class Commit(Message):
    """Backup's commitment to a PREPARE; embeds the full PREPARE and is
    certified by the backup's USIG (reference messages/api.go:67-73)."""

    KIND = "COMMIT"
    replica_id: int
    prepare: Prepare
    ui: Optional[UI] = None


@dataclasses.dataclass
class ReqViewChange(Message):
    """Signed request to move to a new view
    (reference messages/api.go:103-110)."""

    KIND = "REQ-VIEW-CHANGE"
    replica_id: int
    new_view: int
    signature: bytes = b""


# ---------------------------------------------------------------------------
# Classification helpers (reference messages/api.go interface hierarchy).

CLIENT_MESSAGES = (Request,)
REPLICA_MESSAGES = (Reply, Prepare, Commit, ReqViewChange)
PEER_MESSAGES = (Prepare, Commit, ReqViewChange)
CERTIFIED_MESSAGES = (Prepare, Commit)  # carry a USIG UI
SIGNED_MESSAGES = (Request, Reply, ReqViewChange)  # carry a plain signature


def is_peer_message(m: Message) -> bool:
    return isinstance(m, PEER_MESSAGES)


def is_client_message(m: Message) -> bool:
    return isinstance(m, CLIENT_MESSAGES)
