"""Diagnostic message rendering (reference messages/utils.go:25-63)."""

from __future__ import annotations

from .message import Commit, Hello, Message, Prepare, ReqViewChange, Reply, Request


def stringify(m: Message) -> str:
    if isinstance(m, Hello):
        return f"<HELLO replica={m.replica_id}>"
    if isinstance(m, Request):
        return f"<REQUEST client={m.client_id} seq={m.seq} op={len(m.operation)}B>"
    if isinstance(m, Reply):
        return (
            f"<REPLY replica={m.replica_id} client={m.client_id} "
            f"seq={m.seq} result={len(m.result)}B>"
        )
    if isinstance(m, Prepare):
        cv = m.ui.counter if m.ui else None
        reqs = ", ".join(stringify(r) for r in m.requests)
        return (
            f"<PREPARE cv={cv} replica={m.replica_id} view={m.view} "
            f"requests=[{reqs}]>"
        )
    if isinstance(m, Commit):
        cv = m.ui.counter if m.ui else None
        return (
            f"<COMMIT cv={cv} replica={m.replica_id} "
            f"prepare={stringify(m.prepare)}>"
        )
    if isinstance(m, ReqViewChange):
        return f"<REQ-VIEW-CHANGE replica={m.replica_id} new_view={m.new_view}>"
    return f"<{type(m).__name__}>"
