"""Diagnostic message rendering (reference messages/utils.go:25-63)."""

from __future__ import annotations

from .message import (
    Checkpoint,
    Commit,
    Hello,
    Message,
    NewView,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    ViewChange,
)


def stringify(m: Message) -> str:
    if isinstance(m, Hello):
        return f"<HELLO replica={m.replica_id}>"
    if isinstance(m, Request):
        return f"<REQUEST client={m.client_id} seq={m.seq} op={len(m.operation)}B>"
    if isinstance(m, Reply):
        return (
            f"<REPLY replica={m.replica_id} client={m.client_id} "
            f"seq={m.seq} result={len(m.result)}B>"
        )
    if isinstance(m, Prepare):
        cv = m.ui.counter if m.ui else None
        reqs = ", ".join(stringify(r) for r in m.requests)
        return (
            f"<PREPARE cv={cv} replica={m.replica_id} view={m.view} "
            f"requests=[{reqs}]>"
        )
    if isinstance(m, Commit):
        cv = m.ui.counter if m.ui else None
        return (
            f"<COMMIT cv={cv} replica={m.replica_id} "
            f"prepare={stringify(m.prepare)}>"
        )
    if isinstance(m, ReqViewChange):
        return f"<REQ-VIEW-CHANGE replica={m.replica_id} new_view={m.new_view}>"
    if isinstance(m, ViewChange):
        cv = m.ui.counter if m.ui else None
        return (
            f"<VIEW-CHANGE cv={cv} replica={m.replica_id} "
            f"new_view={m.new_view} log={len(m.log)}>"
        )
    if isinstance(m, NewView):
        cv = m.ui.counter if m.ui else None
        return (
            f"<NEW-VIEW cv={cv} replica={m.replica_id} "
            f"new_view={m.new_view} vcs={len(m.view_changes)}>"
        )
    if isinstance(m, Checkpoint):
        cv = m.ui.counter if m.ui else None
        return (
            f"<CHECKPOINT cv={cv} replica={m.replica_id} "
            f"count={m.count} digest={m.digest.hex()[:12]}>"
        )
    return f"<{type(m).__name__}>"
