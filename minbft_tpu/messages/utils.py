"""Diagnostic message rendering (reference messages/utils.go:25-63)."""

from __future__ import annotations

from .message import (
    Checkpoint,
    Commit,
    Hello,
    LogBase,
    Message,
    NewView,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    SnapshotReq,
    SnapshotResp,
    StateChunk,
    StateDone,
    StateReq,
    ViewChange,
)


def stringify(m: Message) -> str:
    if isinstance(m, Hello):
        return f"<HELLO replica={m.replica_id}>"
    if isinstance(m, Request):
        return f"<REQUEST client={m.client_id} seq={m.seq} op={len(m.operation)}B>"
    if isinstance(m, Reply):
        return (
            f"<REPLY replica={m.replica_id} client={m.client_id} "
            f"seq={m.seq} result={len(m.result)}B>"
        )
    if isinstance(m, Prepare):
        cv = m.ui.counter if m.ui else None
        if m.is_stub:
            return (
                f"<PREPARE-STUB cv={cv} replica={m.replica_id} "
                f"view={m.view} digest={m.requests_digest.hex()[:12]}>"
            )
        reqs = ", ".join(stringify(r) for r in m.requests)
        return (
            f"<PREPARE cv={cv} replica={m.replica_id} view={m.view} "
            f"requests=[{reqs}]>"
        )
    if isinstance(m, Commit):
        cv = m.ui.counter if m.ui else None
        return (
            f"<COMMIT cv={cv} replica={m.replica_id} "
            f"prepare={stringify(m.prepare)}>"
        )
    if isinstance(m, ReqViewChange):
        return f"<REQ-VIEW-CHANGE replica={m.replica_id} new_view={m.new_view}>"
    if isinstance(m, ViewChange):
        cv = m.ui.counter if m.ui else None
        return (
            f"<VIEW-CHANGE cv={cv} replica={m.replica_id} "
            f"new_view={m.new_view} log={len(m.log)}>"
        )
    if isinstance(m, NewView):
        cv = m.ui.counter if m.ui else None
        return (
            f"<NEW-VIEW cv={cv} replica={m.replica_id} "
            f"new_view={m.new_view} vcs={len(m.view_changes)}>"
        )
    if isinstance(m, Checkpoint):
        return (
            f"<CHECKPOINT replica={m.replica_id} count={m.count} "
            f"view={m.view} cv={m.cv} digest={m.digest.hex()[:12]}>"
        )
    if isinstance(m, LogBase):
        return (
            f"<LOG-BASE replica={m.replica_id} base={m.base} "
            f"cert={len(m.cert)}>"
        )
    if isinstance(m, SnapshotReq):
        return f"<SNAPSHOT-REQ replica={m.replica_id} count={m.count}>"
    if isinstance(m, SnapshotResp):
        return (
            f"<SNAPSHOT-RESP replica={m.replica_id} count={m.count} "
            f"view={m.view} cv={m.cv} state={len(m.app_state)}B>"
        )
    if isinstance(m, StateReq):
        return (
            f"<STATE-REQ replica={m.replica_id} count={m.count} "
            f"offset={m.offset}>"
        )
    if isinstance(m, StateChunk):
        return (
            f"<STATE-CHUNK replica={m.replica_id} count={m.count} "
            f"offset={m.offset}/{m.total} data={len(m.data)}B>"
        )
    if isinstance(m, StateDone):
        return (
            f"<STATE-DONE replica={m.replica_id} count={m.count} "
            f"view={m.view} cv={m.cv} total={m.total} cert={len(m.cert)}>"
        )
    return f"<{type(m).__name__}>"
