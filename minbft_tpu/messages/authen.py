"""Canonical authentication bytes.

Mirrors reference messages/authen.go:27-82: for each signable/certifiable
message kind, a canonical byte string over which its signature or USIG UI is
computed — a tag string, big-endian fixed-width fields, and SHA-256 digests of
variable-length payloads.

Key structural properties preserved from the reference:

- A PREPARE's authen bytes cover the embedded REQUEST (including the client's
  signature), so a UI on a PREPARE transitively authenticates the exact
  request bytes being ordered.
- A COMMIT's authen bytes include the **primary's UI counter**
  (reference messages/authen.go:70), binding the commitment to the exact slot
  the primary assigned.
- A message's own signature/UI is never part of its own authen bytes.

The 32-byte :func:`authen_digest` of these bytes is the unit of work shipped
to the TPU batch verifiers: every scheme in :mod:`minbft_tpu.ops` operates on
fixed-width digests so batch shapes stay static under ``jit``.
"""

from __future__ import annotations

import hashlib
import struct

from . import codec
from .message import (
    Busy,
    Checkpoint,
    Commit,
    Hello,
    Message,
    NewView,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    SnapshotReq,
    SnapshotResp,
    StateChunk,
    StateDone,
    StateReq,
    ViewChange,
)

_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def authen_bytes(m: Message) -> bytes:
    """Canonical bytes a signature / UI certificate for ``m`` covers
    (reference messages/authen.go:27-82).

    Memoized per message object: every field covered is final by the time
    the first caller needs these bytes (signatures/UIs are excluded from
    their own message's authen bytes; a COMMIT's embedded prepare already
    carries its UI when the COMMIT is constructed), and the same message is
    re-authenticated at several pipeline stages."""
    cached = m.__dict__.get("_authen_bytes")
    if cached is not None:
        return cached
    ab = _authen_bytes(m)
    m.__dict__["_authen_bytes"] = ab
    return ab


def _authen_bytes(m: Message) -> bytes:
    if isinstance(m, Request):
        # read_mode is covered: flipping it in flight would bypass
        # ordering (write→fast read), mutate state with a read
        # (read→write), or silently weaken a fast read's all-n quorum
        # (fast→ordered).
        return (
            b"REQUEST"
            + _U32.pack(m.client_id)
            + _U64.pack(m.seq)
            + bytes([m.read_mode])
            + _sha256(m.operation)
        )
    if isinstance(m, Reply):
        return (
            b"REPLY"
            + _U32.pack(m.replica_id)
            + _U32.pack(m.client_id)
            + _U64.pack(m.seq)
            + bytes([1 if m.read_only else 0])
            + bytes([1 if m.error else 0])
            + _sha256(m.result)
        )
    if isinstance(m, Busy):
        # retry_after_ms is covered: an adversary rewriting the hint could
        # inflate a client's backoff into starvation.
        return (
            b"BUSY"
            + _U32.pack(m.replica_id)
            + _U32.pack(m.client_id)
            + _U64.pack(m.seq)
            + _U32.pack(m.retry_after_ms)
        )
    if isinstance(m, Prepare):
        # Covers every embedded request *with* its client signature (in
        # batch order), so the primary's UI authenticates the exact bytes —
        # and the exact order — it proposed.  A checkpoint-covered *stub*
        # (requests dropped, digest carried) authenticates identically —
        # and since view sits here in the clear and the counter inside the
        # UI certificate, a stub's (view, cv) coverage claim is itself
        # USIG-authenticated.
        return (
            b"PREPARE"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.view)
            + collection_digest(m.requests, m.requests_digest)
        )
    if isinstance(m, Commit):
        if m.prepare.ui is None:
            raise ValueError("COMMIT authen bytes require the primary's UI")
        # Binds the commitment to the prepare's content AND the primary's
        # USIG counter value (reference messages/authen.go:70).
        return (
            b"COMMIT"
            + _U32.pack(m.replica_id)
            + _sha256(authen_bytes(m.prepare))
            + _U64.pack(m.prepare.ui.counter)
        )
    if isinstance(m, ReqViewChange):
        return b"REQ-VIEW-CHANGE" + _U32.pack(m.replica_id) + _U64.pack(m.new_view)
    if isinstance(m, ViewChange):
        # Covers every log entry *with* its UI (in counter order) plus the
        # truncation base: the sender's USIG certifies exactly this claimed
        # history starting at log_base+1.  The checkpoint certificate is
        # deliberately NOT covered — it is transferable third-party
        # evidence the validator checks independently (any f+1 matching
        # attestation with bounds >= log_base serves), so trimmed copies
        # may drop it.  A trimmed copy (empty log, digest carried)
        # authenticates identically, so the original certificate verifies
        # on it (see ViewChange doc).
        return (
            b"VIEW-CHANGE"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.new_view)
            + _U64.pack(m.log_base)
            + collection_digest(m.log, m.log_digest)
        )
    if isinstance(m, NewView):
        # Covers the f+1 embedded VIEW-CHANGEs with their UIs — the quorum
        # that deterministically defines the re-proposal set.
        return (
            b"NEW-VIEW"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.new_view)
            + collection_digest(m.view_changes, m.vcs_digest)
        )
    if isinstance(m, Checkpoint):
        h = hashlib.sha256()
        for p, b in m.bounds:
            h.update(_U32.pack(p) + _U64.pack(b))
        return (
            b"CHECKPOINT"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.count)
            + _U64.pack(m.view)
            + _U64.pack(m.cv)
            + _sha256(m.digest)
            + h.digest()
        )
    if isinstance(m, Hello):
        return b"HELLO" + _U32.pack(m.replica_id) + _U64.pack(m.resume_counter)
    if isinstance(m, SnapshotReq):
        return b"SNAPSHOT-REQ" + _U32.pack(m.replica_id) + _U64.pack(m.count)
    if isinstance(m, SnapshotResp):
        h = hashlib.sha256()
        for c, s in m.watermarks:
            h.update(_U32.pack(c) + _U64.pack(s))
        return (
            b"SNAPSHOT-RESP"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.count)
            + _U64.pack(m.view)
            + _U64.pack(m.cv)
            + _sha256(m.app_state)
            + h.digest()
        )
    if isinstance(m, StateReq):
        # The resume offset is covered (see StateReq doc): rewinding or
        # fast-forwarding it in flight must fail verification.
        return (
            b"STATE-REQ"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.count)
            + _U64.pack(m.offset)
        )
    if isinstance(m, StateChunk):
        # Covers the slice position, the stream length, the data, and the
        # running chain digest — a Byzantine responder cannot splice a
        # validly-signed chunk of one stream into another position.
        return (
            b"STATE-CHUNK"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.count)
            + _U64.pack(m.offset)
            + _U64.pack(m.total)
            + _sha256(m.data)
            + _sha256(m.chain)
        )
    if isinstance(m, StateDone):
        # The checkpoint certificate is deliberately NOT covered — like a
        # VIEW-CHANGE's, it is transferable third-party evidence the
        # receiver validates independently (any f+1 matching attestation
        # serves).
        h = hashlib.sha256()
        for c, s in m.watermarks:
            h.update(_U32.pack(c) + _U64.pack(s))
        return (
            b"STATE-DONE"
            + _U32.pack(m.replica_id)
            + _U64.pack(m.count)
            + _U64.pack(m.view)
            + _U64.pack(m.cv)
            + _U64.pack(m.total)
            + h.digest()
        )
    raise TypeError(f"{type(m).__name__} has no authen bytes")


def collection_digest(entries, carried: bytes) -> bytes:
    """Digest of a message collection, or the carried digest for a trimmed
    copy.  Non-empty collections are always recomputed — a mismatched
    carried digest on a full message simply fails certificate verification
    (both sides apply the same rule)."""
    if not entries:
        return carried if carried else _sha256(b"")
    h = hashlib.sha256()
    for entry in entries:
        h.update(codec.marshal(entry))
    return h.digest()


def authen_digest(m: Message) -> bytes:
    """SHA-256 of :func:`authen_bytes` — the fixed-width unit shipped to the
    TPU batch verifiers."""
    return _sha256(authen_bytes(m))
