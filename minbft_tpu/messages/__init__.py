"""Protocol messages: typed classes, canonical codec, authen bytes.

Mirrors the reference ``messages`` package (reference messages/api.go,
messages/authen.go, messages/protobuf/) — see module docstrings.
"""

from .authen import authen_bytes, authen_digest
from .codec import (
    CodecError,
    drain_multi,
    marshal,
    pack_multi,
    split_multi,
    unmarshal,
)
from .message import (
    CERTIFIED_MESSAGES,
    CLIENT_MESSAGES,
    PEER_MESSAGES,
    REPLICA_MESSAGES,
    SIGNED_MESSAGES,
    UI,
    UNICAST_LOG_MESSAGES,
    Commit,
    Hello,
    LogBase,
    Message,
    Checkpoint,
    NewView,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    SnapshotReq,
    SnapshotResp,
    ViewChange,
    is_client_message,
    is_peer_message,
)
from .utils import stringify

__all__ = [
    "UI",
    "Message",
    "Hello",
    "Request",
    "Reply",
    "Prepare",
    "Commit",
    "ReqViewChange",
    "ViewChange",
    "NewView",
    "Checkpoint",
    "LogBase",
    "SnapshotReq",
    "SnapshotResp",
    "CLIENT_MESSAGES",
    "REPLICA_MESSAGES",
    "PEER_MESSAGES",
    "CERTIFIED_MESSAGES",
    "SIGNED_MESSAGES",
    "UNICAST_LOG_MESSAGES",
    "is_client_message",
    "is_peer_message",
    "marshal",
    "unmarshal",
    "pack_multi",
    "split_multi",
    "drain_multi",
    "CodecError",
    "authen_bytes",
    "authen_digest",
    "stringify",
]
