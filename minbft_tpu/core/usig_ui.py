"""USIG UI glue: verify, assign, and capture UIs on certified messages.

Reference core/usig-ui.go:37-91: the verifier rejects a zero counter then
delegates to the Authenticator with the marshalled UI as the tag; the
assigner calls GenerateMessageAuthenTag and attaches the UI; the capturer
enforces once-only in-counter-order processing via peerstate.

The verifier here is a coroutine — with the TPU authenticator, every
concurrently-validated PREPARE/COMMIT UI lands in the same batched kernel
dispatch (the north-star restructuring; the reference verifies these
serially under the processing goroutine).
"""

from __future__ import annotations

from typing import Awaitable, Callable

from .. import api
from ..messages import UI, Message, authen_bytes
from ..usig import ui_from_bytes, ui_to_bytes


def make_ui_verifier(
    authenticator: api.Authenticator,
) -> Callable[[Message], Awaitable[UI]]:
    """Verify a certified message's UI; returns the parsed UI
    (reference makeUIVerifier, core/usig-ui.go:55-77)."""

    async def verify_ui(msg) -> UI:
        ui = msg.ui
        if ui is None:
            raise api.AuthenticationError("missing UI")
        if ui.counter == 0:
            # reference core/usig-ui.go:65-67
            raise api.AuthenticationError("zero UI counter")
        await authenticator.verify_message_authen_tag(
            api.AuthenticationRole.USIG,
            msg.replica_id,
            authen_bytes(msg),
            ui_to_bytes(ui),
        )
        return ui

    return verify_ui


def make_ui_assigner(
    authenticator: api.Authenticator,
) -> Callable[[Message], None]:
    """Assign a fresh UI to an own certified message
    (reference makeUIAssigner, core/usig-ui.go:79-91)."""

    def assign_ui(msg) -> None:
        tag = authenticator.generate_message_authen_tag(
            api.AuthenticationRole.USIG, authen_bytes(msg)
        )
        msg.ui = ui_from_bytes(tag)

    return assign_ui


def make_ui_capturer(peer_states) -> Callable[[Message], Awaitable[bool]]:
    """Capture a peer's UI for exactly-once in-order processing
    (reference makeUICapturer, core/usig-ui.go:46-53 → peerstate.go:81-109)."""

    async def capture_ui(msg) -> bool:
        return await peer_states.peer(msg.replica_id).capture_ui(msg.ui.counter)

    return capture_ui
