"""Commit pipeline and quorum counting (reference core/commit.go).

A COMMIT must come from a backup (never the view's primary, reference
commit.go:78-80) and embeds the full PREPARE it commits to; validation
re-validates the embedded PREPARE *and* the backup's own UI — up to three
signature checks that the TPU authenticator folds into one batch.

The commitment collector is the quorum core (reference commit.go:108-201):

- the **acceptor** enforces that each replica's commitments arrive with
  sequential primary-CVs (no gaps, no replays) per view;
- the **counter** counts distinct committers per (view, primary-CV) and
  signals "done" at f+1 (the primary's own PREPARE counts itself);
- completed quorums release the executor strictly in primary-CV order.

In the reference all of this is mutex-serialized per message
(commit.go:128-129); here the await points sit *after* batched validation,
so quorum accounting is pure in-memory bookkeeping on the event loop.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, Tuple

from .. import api
from ..messages import Commit, Prepare
from . import utils


def make_commit_validator(
    n: int,
    validate_prepare,
    verify_ui,
) -> Callable[[Commit], Awaitable[None]]:
    """Reference makeCommitValidator (core/commit.go:74-92)."""

    async def validate_commit(commit: Commit) -> None:
        prepare = commit.prepare
        if utils.is_primary(prepare.view, commit.replica_id, n):
            raise api.AuthenticationError(
                "COMMIT must not come from the view's primary"
            )
        # Sequential, not gathered: the embedded PREPARE was almost always
        # validated when it arrived directly (verified-check memo), so the
        # first await usually resolves without suspending and gather's task
        # bookkeeping would be pure overhead on the hot path.
        await validate_prepare(prepare)
        await verify_ui(commit)

    return validate_commit


def make_commit_applier(
    collect_commitment,
) -> Callable[[Commit], Awaitable[None]]:
    """Reference makeCommitApplier (core/commit.go:96-104)."""

    async def apply_commit(commit: Commit) -> None:
        await collect_commitment(commit.replica_id, commit.prepare)

    return apply_commit


class CommitmentCollector:
    """Acceptor + counter + in-order executor release
    (reference makeCommitmentCollector/Acceptor/Counter,
    core/commit.go:108-201).

    Memory is bounded the way the reference bounds it: the acceptor keeps
    one (view, last-CV) pair per replica (commit.go:145-175) and the
    counter keeps only the **f highest primary-CVs** of the current view
    (commit.go:177-201) — a commitment is "done" exactly when f other
    replicas have already committed an equal-or-higher CV, which (because
    each replica's CVs are sequential) implies f+1 distinct replicas
    committed this CV.  Nothing grows with the number of requests served."""

    def __init__(self, f: int, execute_request, on_batch_end=None,
                 trace_quorum=None):
        self._f = f
        self._execute = execute_request
        # Flight-recorder COMMIT-QUORUM capture point (obs/trace.py):
        # noted per request when its batch's quorum releases in order,
        # immediately before execution.  None when tracing is off.
        self._trace_quorum = trace_quorum
        # Fired after each batch finishes executing, with (view, cv) — a
        # deterministic global position, which is what lets checkpoints
        # (core/checkpoint.py) claim a comparable (count, view, cv) on
        # every correct replica.  Never fired mid-batch.
        self._on_batch_end = on_batch_end
        self._lock = asyncio.Lock()
        self._exec_lock = asyncio.Lock()  # serializes state-machine execution
        # acceptor state: per replica, (view, last accepted primary-CV)
        self._accepted: Dict[int, Tuple[int, int]] = {}
        # counter state (reference commit.go:177-201): current view + the
        # f highest primary-CVs committed in it
        self._counter_view = 0
        self._highest = [0] * f
        # executor-release state: next primary CV to execute per view,
        # plus quorum-complete prepares awaiting in-order release
        self._next_exec_cv: Dict[int, int] = {}
        self._ready: Dict[Tuple[int, int], Prepare] = {}
        # per-view primary-CV base: view v's PREPARE counters continue from
        # the primary's USIG counter, which for v > 0 is wherever its
        # NEW-VIEW left it (the view-change protocol registers it); view 0
        # starts at 0 (counters begin at 1).
        self._view_base: Dict[int, int] = {0: 0}
        # stable checkpoint position (view, cv): a per-replica commitment
        # sequence may JUMP over batches at or below it — the skipped
        # commits were checkpoint-covered and pruned from the peer's
        # replayed log, and counting the jumper toward those batches is
        # sound because the certificate already proves they executed with
        # real f+1 quorums.  Uncovered gaps remain protocol violations.
        self._stable_view = 0
        self._stable_cv = 0

    def set_view_base(self, view: int, base_cv: int) -> None:
        """Register the primary-CV base for ``view`` (the NEW-VIEW's own
        counter): the view's first PREPARE must carry base_cv + 1.  Called
        by the view-change applier before the view activates.  Never
        trimmed here — a size-based eviction could drop the *current*
        view's base while its lease-holders are still applying (contested
        escalations register several candidate views before one wins);
        :meth:`prune_view_bases` retires concluded views instead."""
        self._view_base[view] = base_cv

    def prune_view_bases(self, active_view: int) -> None:
        """Drop bases of views below ``active_view`` — their messages are
        refused by the view check anyway.  Called after a view activates."""
        for v in [v for v in self._view_base if v < active_view]:
            del self._view_base[v]

    def note_stable(self, view: int, cv: int) -> None:
        """Record the stable checkpoint position (enables covered-gap
        acceptance — see the constructor comment)."""
        if (view, cv) > (self._stable_view, self._stable_cv):
            self._stable_view = view
            self._stable_cv = cv

    def install_checkpoint(self, view: int, cv: int) -> None:
        """State transfer: resume execution from certified position
        (view, cv).  Uses the view-base machinery — execution restarts at
        cv+1 in that view; commitments at or below the position are
        treated as replays.  Per-peer acceptance state is kept (peers'
        live commit sequences continued regardless of our jump; covered
        gaps are tolerated via note_stable)."""
        self.note_stable(view, cv)
        if view > self._counter_view:
            self._counter_view = view
            self._highest = [0] * self._f
        self._view_base.setdefault(view, 0)
        if view in self._next_exec_cv:
            self._next_exec_cv[view] = max(self._next_exec_cv[view], cv + 1)
        else:
            self._next_exec_cv[view] = cv + 1
        self._ready = {
            k: p for k, p in self._ready.items() if k > (view, cv)
        }

    def _count(self, view: int, primary_cv: int) -> bool:
        """Reference makeCommitmentCounter (commit.go:177-201): True when
        f commitments with CV ≥ primary_cv were already counted in this
        view (so with the current one the quorum is f+1)."""
        if view < self._counter_view:
            return False
        if view > self._counter_view:
            self._counter_view = view
            self._highest = [0] * self._f
        for i, cv in enumerate(self._highest):
            if primary_cv > cv:
                self._highest[i] = primary_cv
                return False
        return True

    async def collect(self, replica_id: int, prepare: Prepare) -> None:
        """Account one commitment by ``replica_id`` to ``prepare``; executes
        request(s) whose quorum completes.  Raises AuthenticationError for
        protocol violations (non-sequential CVs — reference
        commit.go:162-166)."""
        view = prepare.view
        primary_cv = prepare.ui.counter
        if getattr(prepare, "is_stub", False):
            # Defensive: stubs (checkpoint-covered digests) are captured
            # but never applied, so this cannot be reached through message
            # handling — executing one would let full-vs-stub encodings of
            # one UI diverge replicas.
            raise api.AuthenticationError("stub PREPARE cannot be committed")
        async with self._lock:
            base = self._view_base.get(view, 0)
            cur_view, last = self._accepted.get(replica_id, (view, base))
            if view < cur_view:
                return  # commitment from an abandoned view
            if view > cur_view:
                last = base  # new view: CV numbering restarts from its base
            if primary_cv <= last:
                return  # replayed commitment — already accounted
            if primary_cv != last + 1 and (view, primary_cv - 1) > (
                self._stable_view,
                self._stable_cv,
            ):
                raise api.AuthenticationError(
                    f"replica {replica_id} commitment skips CV "
                    f"{last + 1} -> {primary_cv} beyond the stable "
                    f"checkpoint"
                )
            self._accepted[replica_id] = (view, primary_cv)

            if not self._count(view, primary_cv):
                return
            ckey = (view, primary_cv)
            # The counter may report done again for stragglers of an
            # already-released quorum (it has no per-CV memory); the
            # in-order release watermark is the dedup.
            if (
                primary_cv < self._next_exec_cv.get(view, base + 1)
                or ckey in self._ready
            ):
                return
            self._ready[ckey] = prepare
        await self._drain(view)

    async def _drain(self, view: int) -> None:
        """Execute completed quorums strictly in primary-CV order.

        ``_exec_lock`` is held across ``deliver`` so a suspended execution
        (an actually-awaiting consumer) cannot be overtaken by a later CV
        whose quorum completes meanwhile — batched validation makes such
        reordering a real possibility, and hash-chained state machines
        diverge if two replicas execute in different orders."""
        async with self._exec_lock:
            while True:
                async with self._lock:
                    nxt = self._next_exec_cv.setdefault(
                        view, self._view_base.get(view, 0) + 1
                    )
                    prepare = self._ready.pop((view, nxt), None)
                    if prepare is not None:
                        self._next_exec_cv[view] = nxt + 1
                if prepare is None:
                    return
                # A batched prepare commits atomically: its requests execute
                # back-to-back in batch order on every replica.
                if self._trace_quorum is not None:
                    for req in prepare.requests:
                        self._trace_quorum(req)
                for req in prepare.requests:
                    await self._execute(req)
                if self._on_batch_end is not None:
                    await self._on_batch_end(view, prepare.ui.counter)


def make_commitment_collector(
    f: int, execute_request
) -> Callable[[int, Prepare], Awaitable[None]]:
    collector = CommitmentCollector(f, execute_request)

    async def collect_commitment(replica_id: int, prepare: Prepare) -> None:
        await collector.collect(replica_id, prepare)

    return collect_commitment
