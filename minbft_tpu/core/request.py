"""Request pipeline: validate → process → apply → execute → reply.

Reference core/request.go: the client's REQUEST is signature-checked, its
sequence number captured per-client (dedup + one-in-flight pipelining gate),
tracked in the pending list; the primary then emits a PREPARE while backups
start a prepare timer; on quorum the request is executed against the
consumer and a signed REPLY is produced.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict

from .. import api
from ..messages import Reply, Request
from . import utils


def make_request_validator(
    verify_message_signature,
) -> Callable[[Request], Awaitable[None]]:
    """Stateless REQUEST validation (reference makeRequestValidator,
    core/request.go:146-150): just the client signature."""

    async def validate_request(request: Request) -> None:
        await verify_message_signature(request)

    return validate_request


def make_request_processor(
    capture_seq,
    pending_requests,
    view_state,
    apply_request,
) -> Callable[[Request], Awaitable[bool]]:
    """Stateful REQUEST processing (reference makeRequestProcessor,
    core/request.go:155-178): capture seq (False = duplicate), track
    pending, snapshot the view, apply."""

    async def process_request(request: Request) -> bool:
        if request.is_fast_read:
            # FAST reads are answered by the client-stream path and must
            # never be ordered: a peer forwarding one (or a faulty client
            # broadcasting it into the ordering path) would otherwise
            # spend the seq on a request the client signed as unordered.
            # Ordered reads (read_mode=2) proceed normally.
            return False
        new = await capture_seq(request)
        if not new:
            return False
        pending_requests.add(request)
        # Apply under the view read-lease (the reference holds the view
        # across applyRequest, request.go:166-175).
        async with view_state.hold_view_lease() as (view, _):
            await apply_request(request, view)
        return True

    return process_request


def make_request_applier(
    replica_id: int,
    n: int,
    propose,
    start_prepare_timer,
    start_request_timer,
) -> Callable[[Request, int], Awaitable[None]]:
    """Apply a captured REQUEST in a view (reference makeRequestApplier,
    core/request.go:180-198): the primary proposes the request for a
    (batched) PREPARE; a backup starts the prepare timer
    (forward-to-primary fallback) — both start the request (view-change)
    timer."""

    async def apply_request(request: Request, view: int) -> None:
        start_request_timer(request, view)
        if utils.is_primary(view, replica_id, n):
            await propose(request, view)
        else:
            start_prepare_timer(request, view)

    return apply_request


def make_request_executor(
    replica_id: int,
    retire_seq,
    pending_requests,
    stop_timers,
    consumer: api.RequestConsumer,
    sign_message_async,
    add_reply,
    log=None,
    metrics=None,
    sign_message_sync=None,
    trace_execute=None,
    trace_reply_sign=None,
) -> Callable[[Request], Awaitable[None]]:
    """Execute a committed REQUEST exactly once (reference
    makeRequestExecutor, core/request.go:211-231): retire the seq (dedup),
    clear timers and pending state, deliver to the state machine, sign and
    buffer the REPLY.

    ``sign_message_async`` is the AWAITABLE signer, and the REPLY is
    signed OFF the execution chain: executions are strictly ordered
    (commit.py ``_drain`` holds its exec lock across ``deliver``), so
    awaiting a sign-queue round trip inline would serialize signature
    latency into the chain and pin the sign batches at size 1.  Instead
    each execution spawns its sign-and-buffer as a task and moves on —
    consecutive executions co-batch their REPLY signatures on the
    engine's sign queue (the DSig-style off-critical-path
    restructuring).

    BUFFERING stays in execution order even though SIGNING is concurrent:
    each spawned task waits for its PER-CLIENT predecessor before
    ``add_reply``.  ClientState.add_reply accepts out-of-order seqs (a
    reordering network legitimately executes a higher seq first under
    exact retirement), but its reply WINDOW is bounded: if sign batches
    resolved out of order and more than a window's worth of later
    replies buffered first, the window floor would pass the earlier seq
    and its reply would be dropped as pruned — permanently, since a
    retransmitted REQUEST dedups at retire_seq and can only re-serve a
    buffered reply.  Ordered buffering closes that window-overflow loss
    entirely.  The chain is keyed by client_id — the window is a
    per-client structure, and a global chain would let one hung sign
    batch (90s dispatch timeout) delay every OTHER client's
    already-signed replies.  It costs nothing in batching — every sign
    is already submitted to the queue before any completion is awaited.

    ``sign_message_sync`` is the serial emergency signer: if the batch
    path fails (engine dispatch exception), the reply is re-signed
    inline rather than silently dropped — a retransmitted REQUEST dedups
    at retire_seq and can only RE-SERVE a buffered reply, never re-sign
    a lost one.

    Returns True iff the request was actually delivered this call.  A
    re-proposed request re-drained after a view change early-returns False
    — callers counting executions (metrics, the checkpoint period, which
    must stay a deterministic global sequence number across replicas) must
    only count on True, or replicas that executed pre-transition would
    count a request twice while others count once.

    ``trace_execute`` / ``trace_reply_sign`` are the flight recorder's
    stage callbacks (obs/trace.py) — None when tracing is off, so the
    hot path pays one predicated check each."""
    # Strong refs for the in-flight sign-and-buffer tasks (discarded by
    # their done-callback) — a GC'd task would silently drop a REPLY.
    sign_tasks: set = set()
    # Per-client buffering-chain tails (see the docstring): execution
    # order in, add_reply order out.  O(known clients) — same growth as
    # the client_states map itself.
    chain_tails: Dict[int, object] = {}

    async def execute_request(request: Request) -> bool:
        if not retire_seq(request):
            return False  # already executed (reference request.go:214-218)
        pending_requests.remove(request)
        stop_timers(request)
        error = False
        if request.is_read:
            # An ORDERED read (read_mode=2, the fast read's fallback):
            # consensus fixes its place in the order — that is the
            # linearization point — but execution must not mutate state.
            # Deterministic across replicas: same slot -> same committed
            # state -> same query result (also under log replay).
            try:
                result = await consumer.query(request.operation)
            except Exception as e:
                # A SIGNED error reply on any query failure
                # (NotImplementedError = the deployment cannot serve
                # reads; anything else = a consumer bug on
                # CLIENT-CONTROLLED operation bytes, which must not
                # detonate in the execution chain behind committed
                # writes).  NO reply would park every replica-side
                # reply_for waiter on this seq forever — retransmissions
                # then pile parked tasks onto the stream's bounded
                # concurrency slots until the client's stream wedges.  A
                # fabricated plain b"" would be indistinguishable from a
                # real empty result; the error flag keeps it honest (the
                # client raises ReadOnlyQueryError on an error quorum).
                # State is untouched; checkpoint digests stay aligned
                # even if the failure is replica-local.
                error = True
                result = b""
                if log is not None:
                    log.warning(
                        "ordered read failed: %r (op %r...)",
                        e,
                        request.operation[:32],
                    )
                if metrics is not None:
                    metrics.inc("readonly_query_errors")
        else:
            result = await consumer.deliver(request.operation)
        if trace_execute is not None:
            trace_execute(request)
        reply = Reply(
            replica_id=replica_id,
            client_id=request.client_id,
            seq=request.seq,
            result=result,
            read_only=request.is_read,
            error=error,
        )

        prev = chain_tails.get(request.client_id)

        async def sign_and_buffer() -> None:
            signed = False
            try:
                await sign_message_async(reply)
                signed = True
                if trace_reply_sign is not None:
                    trace_reply_sign(reply)
            except Exception:
                if log is not None:
                    log.exception(
                        "batched REPLY signing failed for client %d seq %d"
                        "; re-signing serially",
                        reply.client_id,
                        reply.seq,
                    )
                if sign_message_sync is not None:
                    try:
                        sign_message_sync(reply)
                        signed = True
                        if trace_reply_sign is not None:
                            trace_reply_sign(reply)
                    except Exception:
                        # Both signers down: this reply is lost on this
                        # replica (the other replicas' quorum carries the
                        # client) — never the execution chain behind it.
                        if log is not None:
                            log.exception(
                                "serial REPLY signing also failed for "
                                "client %d seq %d",
                                reply.client_id,
                                reply.seq,
                            )
            if prev is not None:
                # Buffer in execution order (see the factory docstring);
                # a predecessor's failure or teardown-cancellation must
                # not unbuffer THIS reply.
                try:
                    await prev
                except (Exception, asyncio.CancelledError):
                    pass
            if signed:
                add_reply(reply)

        task = asyncio.get_running_loop().create_task(sign_and_buffer())
        chain_tails[request.client_id] = task
        sign_tasks.add(task)
        task.add_done_callback(sign_tasks.discard)
        return True

    return execute_request


def make_request_replier(
    client_states,
) -> Callable[[Request], Awaitable[Reply]]:
    """Await the REPLY for a REQUEST (reference makeRequestReplier,
    core/request.go:202-207 → clientstate reply subscription)."""

    async def reply_request(request: Request) -> Reply:
        return await client_states.client(request.client_id).reply_for(request.seq)

    return reply_request


def make_seq_capturer(client_states) -> Callable[[Request], Awaitable[bool]]:
    """Per-client seq capture (reference captureSeq, core/request.go:235-246)."""

    async def capture_seq(request: Request) -> bool:
        return await client_states.client(request.client_id).capture_request_seq(
            request.seq
        )

    return capture_seq


def make_seq_releaser(client_states) -> Callable[[Request], Awaitable[None]]:
    async def release_seq(request: Request) -> None:
        await client_states.client(request.client_id).release_request_seq(request.seq)

    return release_seq


def make_seq_preparer(client_states) -> Callable[[Request], None]:
    """Mark a request prepared (reference prepareSeq, core/request.go:248-259)."""

    def prepare_seq(request: Request) -> None:
        client_states.client(request.client_id).prepare_request_seq(request.seq)

    return prepare_seq


def make_seq_retirer(client_states) -> Callable[[Request], bool]:
    """Retire an executed request's seq (reference retireSeq,
    core/request.go:261-276)."""

    def retire_seq(request: Request) -> bool:
        return client_states.client(request.client_id).retire_request_seq(request.seq)

    return retire_seq
