"""Failure detection: request-timeout → REQ-VIEW-CHANGE emission.

Reference core/timeout.go:32-72 and core/request.go:280-340: when a pending
request's timer expires, the replica demands view v+1 and broadcasts a
signed REQ-VIEW-CHANGE.  The reference stops there (processing is "Not
implemented", core/message-handling.go:419); this build goes beyond it —
f+1 demands start the full view-change protocol (core/viewchange.py).  The
prepare-timer fallback forwards the starved REQUEST to the primary via its
unicast log (reference core/request.go:315-324).
"""

from __future__ import annotations

from typing import Awaitable, Callable

from ..messages import ReqViewChange


def make_view_change_requestor(
    replica_id: int,
    view_state,
    sign_message,
    broadcast,
) -> Callable[[int], Awaitable[None]]:
    """Demand a view change (reference makeViewChangeRequestor,
    core/timeout.go:45-72): dedup via expectedView, emit signed
    REQ-VIEW-CHANGE."""

    async def request_view_change(new_view: int) -> None:
        if not await view_state.advance_expected_view(new_view):
            return  # already demanded (reference timeout.go:56-63)
        msg = ReqViewChange(replica_id=replica_id, new_view=new_view)
        sign_message(msg)
        broadcast(msg)

    return request_view_change


def make_request_timeout_handler(
    request_view_change,
) -> Callable[[int], Awaitable[None]]:
    """Reference makeRequestTimeoutHandler (core/timeout.go:32-40)."""

    async def handle_request_timeout(view: int) -> None:
        await request_view_change(view + 1)

    return handle_request_timeout
