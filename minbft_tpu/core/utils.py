"""Core helpers (reference core/utils.go:43-97)."""

from __future__ import annotations

import logging

from .. import api
from ..messages import (
    Busy,
    Checkpoint,
    Commit,
    Hello,
    Message,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    SnapshotReq,
    SnapshotResp,
    StateChunk,
    StateDone,
    StateReq,
)


def is_primary(view: int, replica_id: int, n: int) -> bool:
    """The primary for view v is replica v mod n (reference core/utils.go:80-82)."""
    return replica_id == view % n


def signing_role(msg: Message) -> api.AuthenticationRole:
    """Map a signed message kind to the key family that signs it
    (reference core/utils.go:43-72 message-type → role mapping)."""
    if isinstance(msg, Request):
        return api.AuthenticationRole.CLIENT
    if isinstance(
        msg,
        (
            Reply, Busy, ReqViewChange, Checkpoint, SnapshotReq,
            SnapshotResp, StateReq, StateChunk, StateDone, Hello,
        ),
    ):
        return api.AuthenticationRole.REPLICA
    raise TypeError(f"{type(msg).__name__} is not a signed message")


def certifying_role(msg: Message) -> api.AuthenticationRole:
    if isinstance(msg, (Prepare, Commit)):
        return api.AuthenticationRole.USIG
    raise TypeError(f"{type(msg).__name__} is not a certified message")


def make_logger(replica_id: int, level: int = logging.INFO) -> logging.Logger:
    """Per-replica logger (reference core/utils.go:84-97, options.go:25-58)."""
    logger = logging.getLogger(f"minbft.replica{replica_id}")
    logger.setLevel(level)
    return logger
