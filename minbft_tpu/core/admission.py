"""Replica-side admission control for client streams (ISSUE 15).

The inbound client path already has two bounds: the bundle ingestor's rx
queue (transport backpressure) and the stream processor's concurrency
semaphore (in-flight task bound, PR 8's shed-on-saturated-group probe).
Before this module, hitting the second bound under an OPEN-LOOP offered
load had only bad outcomes: block the ingest tick (head-of-line blocks
the whole stream, rx queue wedges at its bound, the generator keeps
pushing) or drop silently (the client retransmits into the same
saturation and makes it worse).

:class:`AdmissionController` wraps the processor's non-blocking submit:
when the concurrency bound is exhausted the message is SHED — counted,
and (for REQUESTs) answered with a signed :class:`~minbft_tpu.messages.
Busy` carrying a retry-after hint scaled by the observed rx saturation.
The client's retransmit ladder honors the hold
(``client.Client._handle_busy``), so offered load beyond saturation
drains into backoff instead of queue growth — the replica keeps
committing at its capacity and the overload is visible on both ends
(``admission_shed`` / ``admission_busy_sent`` counters,
``minbft_admission_*`` Prometheus families, ``peer top`` SHED/S column).

BUSY signing itself costs a signature, so an attacker flooding garbage
must not be able to convert shed work into sign work: a token bucket
bounds BUSY emission; beyond the budget sheds stay silent (counted as
``admission_busy_suppressed``) and the client's plain retransmit ladder
carries the backoff.

``MINBFT_ADMISSION=0`` reverts to the pre-ISSUE-15 blocking submit (the
A/B lever: backpressure-only vs shed-and-signal).
"""

from __future__ import annotations

import os
import time

from ..messages import Busy, Message, Request, marshal

# BUSY emission budget: sustained signals/sec and burst size.  Sized so a
# saturated replica can tell every live client to back off within one
# retransmit interval, while a garbage flood cannot push sign load past
# a small constant rate.
_BUSY_RATE_PER_SEC = 400.0
_BUSY_BURST = 200

# retry-after hint bounds (milliseconds).  The low end covers a transient
# semaphore blip; the high end is one full saturation's worth of drain
# time at the committed ~1k req/s ceiling.
_RETRY_MIN_MS = 25
_RETRY_MAX_MS = 1000

_ADMISSION_ENV = "MINBFT_ADMISSION"


def admission_enabled() -> bool:
    return os.environ.get(_ADMISSION_ENV, "").lower() not in (
        "0", "false", "no",
    )


class AdmissionController:
    """Shed-and-signal submit wrapper for ONE client stream.

    Concurrency: confined to the owning stream's event-loop tasks (the
    ingest tick loop and the per-frame fallback path call submit; nothing
    else touches the instance) — same confinement contract as
    ``_BundleIngestor``.
    """

    def __init__(self, handlers, proc, out_queue, wrap=None):
        self._handlers = handlers
        self._proc = proc
        self._out_queue = out_queue
        # Optional frame envelope (the grouped runtime passes pack_group
        # so a BUSY demuxes to the right group client-side).
        self._wrap = wrap
        self._tokens = float(_BUSY_BURST)
        self._refill_at = time.monotonic()

    # -- submit paths (bundle ingest / per-frame fallback) ------------------

    async def submit_msg(self, msg: Message) -> None:
        if await self._proc.try_submit_msg(msg):
            return
        await self._shed(msg)

    async def submit(self, data: bytes) -> None:
        if await self._proc.try_submit(data):
            return
        # Decode only on the shed path (the happy path stays zero-copy):
        # a BUSY needs the request's client/seq attribution.
        from ..messages import unmarshal

        try:
            msg = unmarshal(data)
        except Exception:
            self._handlers.metrics.inc("admission_shed")
            return
        await self._shed(msg)

    # -- shed ---------------------------------------------------------------

    async def _shed(self, msg: Message) -> None:
        h = self._handlers
        h.metrics.inc("admission_shed")
        if not isinstance(msg, Request):
            return  # only REQUESTs have a client to signal
        if not self._take_token():
            h.metrics.inc("admission_busy_suppressed")
            return
        busy = Busy(
            replica_id=h.replica_id,
            client_id=msg.client_id,
            seq=msg.seq,
            retry_after_ms=self._retry_after_ms(),
        )
        try:
            # Batch-aware signing: concurrent sheds co-batch with reply
            # signatures on the engine's sign queue.
            await h.sign_message_async(busy)
        except Exception as e:
            h.metrics.inc("admission_busy_suppressed")
            h.log.warning("BUSY sign failed: %r", e)
            return
        h.metrics.inc("admission_busy_sent")
        frame = marshal(busy)
        if self._wrap is not None:
            frame = self._wrap(frame)
        await self._out_queue.put(frame)

    def _retry_after_ms(self) -> int:
        """Hold hint scaled by the last-stamped rx saturation: a blip
        earns a short hold, a wedged-full rx queue the max."""
        frac = self._handlers.metrics.admission_rx_saturation()
        return int(_RETRY_MIN_MS + frac * (_RETRY_MAX_MS - _RETRY_MIN_MS))

    def _take_token(self) -> bool:
        now = time.monotonic()
        self._tokens = min(
            float(_BUSY_BURST),
            self._tokens + (now - self._refill_at) * _BUSY_RATE_PER_SEC,
        )
        self._refill_at = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False
