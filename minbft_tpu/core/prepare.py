"""Prepare pipeline (reference core/prepare.go).

Only the primary of the PREPARE's view may have produced it (reference
prepare.go:51-53); validation re-checks the embedded REQUEST's client
signature and the primary's UI — with the TPU authenticator, both checks
join the same verification batch via ``asyncio.gather``.  Applying a
PREPARE on a backup marks the request prepared, collects the primary's
commitment, and responds with an own COMMIT (reference prepare.go:69-94).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

from .. import api
from ..messages import Commit, Prepare
from . import utils


def make_prepare_validator(
    n: int,
    validate_request,
    verify_ui,
) -> Callable[[Prepare], Awaitable[None]]:
    """Reference makePrepareValidator (core/prepare.go:46-65)."""

    async def validate_prepare(prepare: Prepare) -> None:
        if not utils.is_primary(prepare.view, prepare.replica_id, n):
            raise api.AuthenticationError(
                f"PREPARE from non-primary replica {prepare.replica_id} "
                f"in view {prepare.view}"
            )
        for r in prepare.requests:
            if r.is_fast_read:
                # The client signed this as UNORDERED: a primary batching
                # it would spend the client's seq on an ordering the
                # client never authorized.  (Ordered reads, read_mode=2,
                # batch fine — they execute via query at their slot.)
                raise api.AuthenticationError(
                    "PREPARE embeds a fast-read request"
                )
        # Client signatures on every embedded request + the primary's UI,
        # batched into one engine round (the reference does these serially,
        # prepare.go:55-61).
        results = await asyncio.gather(
            *[validate_request(r) for r in prepare.requests],
            verify_ui(prepare),
            return_exceptions=True,
        )
        ui_exc = results[-1]
        if isinstance(ui_exc, BaseException):
            raise ui_exc
        for exc in results[:-1]:
            if isinstance(exc, api.AuthenticationError):
                # UI valid, embedded request not: see
                # api.EmbeddedRequestAuthError — the handler demands a
                # view change rather than wedging on the counter gap.
                raise api.EmbeddedRequestAuthError(str(exc)) from exc
            if isinstance(exc, BaseException):
                raise exc

    return validate_prepare


def make_prepare_applier(
    replica_id: int,
    prepare_seq,
    collect_commitment,
    handle_generated,
    stop_prepare_timer,
    trace_prepare=None,
) -> Callable[[Prepare], Awaitable[None]]:
    """Reference makePrepareApplier (core/prepare.go:69-94).

    ``trace_prepare`` is the flight recorder's PREPARE capture point
    (obs/trace.py): noted when the batch is applied — on every replica,
    the primary included (its own PREPARE rides the own-message loop) —
    so the span is uniform cluster-wide.  None when tracing is off."""

    async def apply_prepare(prepare: Prepare) -> None:
        for req in prepare.requests:
            prepare_seq(req)
            stop_prepare_timer(req)
            if trace_prepare is not None:
                trace_prepare(req)
        await collect_commitment(prepare.replica_id, prepare)
        if prepare.replica_id != replica_id:
            # A backup commits to the accepted proposal
            # (reference prepare.go:90 NewCommit).
            await handle_generated(Commit(replica_id=replica_id, prepare=prepare))

    return apply_prepare
