"""View-change protocol — **beyond the reference**, which stops at emitting
REQ-VIEW-CHANGE and refuses to process it ("Not implemented",
reference core/message-handling.go:419; roadmap README.md:490-497).

The protocol follows the MinBFT paper (§IV-B of "Efficient Byzantine
Fault-Tolerance", Veronese et al. 2013), adapted to this build's asyncio
closure graph and USIG machinery:

1. A replica suspecting the primary broadcasts a *signed*
   REQ-VIEW-CHANGE(v+1) (reference-parity part, core/timeout.py).
2. On f+1 distinct demands for view v' > current, a replica enters the
   transition: it stops applying view-v messages (the read-lease check in
   ``message_handling``) and broadcasts a *certified* VIEW-CHANGE carrying
   its complete certified-message log.  Log completeness is enforced by
   USIG itself: the entries' counters must be exactly 1..k with the
   VIEW-CHANGE at k+1 — omitting a sent message leaves a visible gap, so
   even a faulty quorum member exposes the commit evidence it holds.
3. The new primary (v' mod n) collects n-f VIEW-CHANGEs (f+1 exactly
   when n = 2f+1 — see :attr:`ViewChangeState.vc_quorum`) and broadcasts
   a certified NEW-VIEW embedding them.  Every replica derives the same
   re-proposal set S from those f+1 logs (:func:`compute_new_view_set`),
   enters v', and expects the new primary's first PREPAREs to re-propose
   exactly S in order — a deviation is refused and answered with a demand
   for v'+1.  The NEW-VIEW's own UI counter is the base from which the
   new primary's PREPARE counters continue
   (:meth:`minbft_tpu.core.commit.CommitmentCollector.set_view_base`).
4. Re-proposed requests that were already executed are absorbed by the
   per-client retire watermark (execute-once), so state machines converge
   without double execution.

Safety sketch: a request executed anywhere needed f+1 commitments; any
n-f VIEW-CHANGE quorum intersects that commitment quorum in at least one
replica ((n-f) + (f+1) = n+1 > n), whose log — complete by the
counter-gap argument — contains its PREPARE/COMMIT for the request, so S
re-proposes it before any new request, in the original (view, counter)
order.

Checkpoint scoping (phase 2 — see :mod:`minbft_tpu.core.checkpoint`):
a VIEW-CHANGE may truncate its log to counters ``log_base+1..k``,
carrying an f+1 checkpoint certificate whose per-peer coverage bounds
prove the dropped prefix held no commit evidence beyond the certified
checkpoint; retained covered entries may be stubbed (payload replaced by
its digest under the same UI).  The re-proposal set is then **anchored**:
batches at or below the quorum's best certified position are covered by
certified state (a lagging replica fetches it — state transfer) and are
not re-proposed, so view-change work is O(window since the last stable
checkpoint), not O(history).
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from .. import api
from ..messages import Checkpoint, Commit, NewView, Prepare, ViewChange
from . import utils

# A batch key: the (client, seq) identity of each request a PREPARE orders.
BatchKey = Tuple[Tuple[int, int], ...]


def batch_key(prepare: Prepare) -> BatchKey:
    return tuple((r.client_id, r.seq) for r in prepare.requests)


def quorum_anchor(view_changes) -> Tuple[int, int, int, Tuple[Checkpoint, ...]]:
    """The best certified checkpoint position among a VIEW-CHANGE quorum:
    ``(count, view, cv, certificate)``.  Batches at or below it are
    covered by certified state; everything above must be re-proposed."""
    best = (0, 0, 0)
    cert: Tuple[Checkpoint, ...] = ()
    for vc in view_changes:
        if vc.checkpoint_cert:
            cp = vc.checkpoint_cert[0]
            if (cp.count, cp.view, cp.cv) > best:
                best = (cp.count, cp.view, cp.cv)
                cert = vc.checkpoint_cert
    return (*best, cert)


def compute_new_view_set(
    view_changes, new_view: int
) -> List[Prepare]:
    """Derive the deterministic re-proposal set S from a NEW-VIEW's n-f
    VIEW-CHANGEs: every full PREPARE of a view < new_view appearing in
    any log (directly, or embedded in a COMMIT) whose batch lies **above
    the quorum anchor**, ordered by (view, primary CV) and deduplicated —
    USIG uniqueness guarantees one PREPARE per (primary, counter), so the
    map cannot collide on conflicting proposals.

    Anchored batches are excluded: any request executed at or below the
    anchor is part of the f+1-certified state every replica entering the
    view holds (state transfer if behind), and execution order is
    lexicographic in (view, cv), so a request executed anywhere *above*
    the anchor has f+1 commitments at a batch above it — evidence the
    coverage-bound audit guarantees survives in the quorum logs.  Stubs
    are skipped for the same reason: a stub is validated as covered by
    its sender's certificate, which the anchor dominates.

    A batch appearing at several slots — its original PREPARE plus its
    re-proposals from intermediate failed views — is kept ONCE.  Without
    dedup, every unconcluded view change doubles S (the view-v originals
    plus the view-v' re-proposals of the same batches), so under
    sustained faults each transition carries exponentially more work
    than the last and the cluster livelocks in view-change thrash (the
    chaos soak found this at "768 re-proposals" of 6 requests).

    The surviving slot is the batch's LATEST (view, counter) appearance.
    Within any view, re-proposals are certified before fresh proposals
    IN S ORDER (enforced by check_reproposal at every backup), so the
    newest view's slots embed the full previously-committed order — the
    latest slot is always consistent with the execution order of every
    correct replica.  The earliest slot is NOT: a deposed primary that
    was stalled through its own view change (half-open link, partition)
    keeps certifying fresh PREPAREs for client retransmissions at its
    OLD view, and those uncommittable slots — present only in its own
    log, sorted before every later view — would steer S into an order
    that contradicts what the live quorum already executed (the chaos
    soak hit this as a real ledger fork: the healed ex-primary executed
    phase-D requests before the phase-C requests the cluster committed
    first).  A stale primary's late certifications always carry an old
    VIEW, so latest-slot ordering is immune to them by construction."""
    _, av, acv, _ = quorum_anchor(view_changes)
    prepares: Dict[Tuple[int, int], Prepare] = {}
    for vc in view_changes:
        for entry in vc.log:
            cand: Optional[Prepare] = None
            if isinstance(entry, Prepare):
                cand = entry
            elif isinstance(entry, Commit):
                cand = entry.prepare
            if cand is None or cand.ui is None or cand.view >= new_view:
                continue
            if cand.is_stub or (cand.view, cand.ui.counter) <= (av, acv):
                continue
            prepares[(cand.view, cand.ui.counter)] = cand
    # Latest slot per batch: ascending slot iteration, later overwrites.
    best: Dict[BatchKey, Tuple[Tuple[int, int], Prepare]] = {}
    for slot in sorted(prepares):
        best[batch_key(prepares[slot])] = (slot, prepares[slot])
    return [p for _, p in sorted(best.values(), key=lambda sp: sp[0])]


class ViewChangeState:
    """Per-replica bookkeeping for the view-change rounds.

    Memory is bounded: demands/collections are only accepted within
    ``MAX_VIEWS_AHEAD`` of the current view (honest escalation advances
    one view per timeout, so the window is generous), and concluded
    views' bookkeeping is pruned on view entry — a faulty replica cannot
    grow state by demanding views 10^9 apart."""

    MAX_VIEWS_AHEAD = 64

    def __init__(self, n: int, f: int, replica_id: int):
        self.n = n
        self.f = f
        self.replica_id = replica_id
        # REQ-VIEW-CHANGE demand votes: new_view -> demanding replica ids
        self.req_votes: Dict[int, Set[int]] = {}
        # collected VIEW-CHANGEs: new_view -> replica -> message
        self.view_changes: Dict[int, Dict[int, ViewChange]] = {}
        self.sent_view_change: Set[int] = set()  # new_views we voted for
        self.sent_new_view: Set[int] = set()
        # re-proposal enforcement, keyed per view: entering a view, the
        # new primary's first PREPAREs must match these batches in order.
        # Per-view (not a single slot): concurrent NEW-VIEW applications
        # during escalation must not overwrite the winning view's regime.
        self.reproposals: Dict[int, deque] = {}

    def in_window(self, new_view: int, current: int) -> bool:
        return current < new_view <= current + self.MAX_VIEWS_AHEAD

    def in_transition(self, current: int) -> bool:
        """True while this replica has VOTED (sent a VIEW-CHANGE) for a
        view beyond ``current`` — the window during which current-view
        messages are not applied.  Keyed on the actual vote, not on the
        expected-view watermark: a solo spurious demand advances the
        watermark without a quorum, and gating on it would wedge the
        replica until f+1 peers happened to demand too."""
        return any(v > current for v in self.sent_view_change)

    # -- demand votes -------------------------------------------------------

    def record_demand(self, replica_id: int, new_view: int) -> bool:
        """Record one REQ-VIEW-CHANGE; True when the f+1 quorum for
        ``new_view`` is (now) complete."""
        votes = self.req_votes.setdefault(new_view, set())
        votes.add(replica_id)
        return len(votes) >= self.f + 1

    # -- view-change collection --------------------------------------------

    @property
    def vc_quorum(self) -> int:
        """VIEW-CHANGE quorum size: **n - f**, not f+1.  The safety
        argument needs every view-change quorum to intersect every f+1
        commitment quorum: (n-f) + (f+1) = n+1 > n guarantees it for ALL
        n >= 2f+1, while f+1 only suffices at exactly n = 2f+1 (at n=4,
        f=1 two disjoint pairs could commit and recover separately,
        forking the ledger).  At n = 2f+1 this reduces to the paper's
        f+1.  Liveness holds: with <= f crashed, n-f replicas remain."""
        return self.n - self.f

    def record_view_change(self, vc: ViewChange) -> bool:
        """Record one validated VIEW-CHANGE; True when a quorum (n-f
        distinct replicas) for ``vc.new_view`` is available.  Only the
        first VIEW-CHANGE per (replica, view) counts — USIG counter order
        means every correct replica sees the same first one."""
        per_view = self.view_changes.setdefault(vc.new_view, {})
        per_view.setdefault(vc.replica_id, vc)
        return len(per_view) >= self.vc_quorum

    def quorum_for(self, new_view: int) -> List[ViewChange]:
        """The deterministic quorum subset used to build NEW-VIEW: lowest
        replica ids first."""
        per_view = self.view_changes.get(new_view, {})
        picked = sorted(per_view)[: self.vc_quorum]
        return [per_view[r] for r in picked]

    def prune_through(self, view: int) -> None:
        """Drop bookkeeping for concluded views (memory stays O(pending
        transitions), not O(views ever demanded))."""
        for d in (self.req_votes, self.view_changes):
            for v in [v for v in d if v <= view]:
                del d[v]
        self.sent_view_change = {v for v in self.sent_view_change if v > view}
        self.sent_new_view = {v for v in self.sent_new_view if v > view}
        for v in [v for v in self.reproposals if v < view]:
            del self.reproposals[v]

    # -- re-proposal enforcement -------------------------------------------

    def arm_reproposals(self, new_view: int, batches: List[BatchKey]) -> None:
        self.reproposals.setdefault(new_view, deque(batches))

    def check_reproposal(self, prepare: Prepare) -> bool:
        """True if ``prepare`` is acceptable under the re-proposal regime:
        either no regime is active for its view, or it matches the next
        expected batch (which it consumes)."""
        expected = self.reproposals.get(prepare.view)
        if not expected:
            return True  # no active regime for this prepare's view
        if batch_key(prepare) != expected[0]:
            return False
        expected.popleft()
        if not expected:
            del self.reproposals[prepare.view]
        return True


def trim_log_entry(entry):
    """The wire form of a prior VIEW-CHANGE/NEW-VIEW inside a log: payload
    emptied, its canonical digest carried instead — same authen bytes, so
    the original UI certificate verifies on the trimmed copy, and logs stay
    linear instead of nesting every earlier log (exponential growth)."""
    from ..messages.authen import collection_digest

    if isinstance(entry, ViewChange) and (entry.log or entry.checkpoint_cert):
        return ViewChange(
            replica_id=entry.replica_id,
            new_view=entry.new_view,
            log=(),
            ui=entry.ui,
            log_digest=collection_digest(entry.log, entry.log_digest),
            # log_base is part of the authen bytes (it scopes the claimed
            # history) and must survive trimming; the checkpoint cert is
            # transferable evidence outside the authen bytes and is
            # dropped with the log it vouched for.
            log_base=entry.log_base,
            checkpoint_cert=(),
        )
    if isinstance(entry, NewView) and entry.view_changes:
        return NewView(
            replica_id=entry.replica_id,
            new_view=entry.new_view,
            view_changes=(),
            ui=entry.ui,
            vcs_digest=collection_digest(entry.view_changes, entry.vcs_digest),
        )
    return entry


def make_view_change_validator(verify_ui, validate_cert=None):
    """Validate a VIEW-CHANGE: its own UI plus the USIG log-completeness
    invariant — entries are the sender's certified messages with counters
    exactly log_base+1..k and the VIEW-CHANGE itself at k+1.  Embedded
    foreign PREPAREs (inside the sender's COMMITs) are verified too, since
    the re-proposal set derives (view, counter) slots from them.

    Checkpoint scoping: a non-zero ``log_base`` requires an f+1
    checkpoint certificate whose coverage bounds for the sender reach the
    base (``validate_cert``, see core/checkpoint.py — at least one
    attester is correct, so the dropped prefix provably holds no evidence
    beyond the certificate).  Stubbed entries must be covered by the
    certificate's position — their (view, cv) claims are themselves
    USIG-authenticated (the digest substitution preserves authen bytes),
    so a sender cannot stub away live evidence."""

    from . import checkpoint as checkpoint_mod

    async def validate_view_change(vc: ViewChange) -> None:
        cp = None
        if vc.checkpoint_cert:
            if validate_cert is None:
                raise api.AuthenticationError(
                    "VIEW-CHANGE carries a checkpoint certificate but "
                    "this validator cannot check one"
                )
            cp = await validate_cert(vc.checkpoint_cert)
        if vc.log_base > 0:
            if cp is None:
                raise api.AuthenticationError(
                    "truncated VIEW-CHANGE without a checkpoint certificate"
                )
            bounds = [c.bound_for(vc.replica_id) for c in vc.checkpoint_cert]
            if min(bounds) < vc.log_base:
                raise api.AuthenticationError(
                    "VIEW-CHANGE log_base exceeds the certified coverage "
                    "bounds: the dropped prefix is not provably covered"
                )
        to_verify = []
        base = vc.log_base
        for i, entry in enumerate(vc.log):
            if entry.replica_id != vc.replica_id:
                raise api.AuthenticationError(
                    "VIEW-CHANGE log entry from another replica"
                )
            if entry.ui is None or entry.ui.counter != base + i + 1:
                raise api.AuthenticationError(
                    "VIEW-CHANGE log has a counter gap: omitted messages"
                )
            if isinstance(entry, ViewChange) and entry.log:
                # nested logs must arrive trimmed (see trim_log_entry) —
                # otherwise one message re-nests the whole history
                raise api.AuthenticationError(
                    "VIEW-CHANGE log entry must be trimmed"
                )
            if isinstance(entry, NewView) and entry.view_changes:
                raise api.AuthenticationError(
                    "NEW-VIEW log entry must be trimmed"
                )
            stub = (
                entry if isinstance(entry, Prepare) else entry.prepare
            ) if isinstance(entry, (Prepare, Commit)) else None
            if stub is not None and stub.is_stub:
                cov = checkpoint_mod.entry_coverage(entry)
                if cp is None or not checkpoint_mod.is_covered(
                    cov, cp.view, cp.cv
                ):
                    raise api.AuthenticationError(
                        "VIEW-CHANGE stubs an entry the certificate does "
                        "not cover"
                    )
            to_verify.append(entry)
            if isinstance(entry, Commit):
                to_verify.append(entry.prepare)
        # Entry checks are stateless: gather them so they co-batch on the
        # verification engine (the log grows with the checkpoint window —
        # one serial engine round-trip per entry would stall recovery; the
        # gather collapses them to ~one batch, prepare.py's house pattern).
        # Coroutines are created HERE, not in the loop: a raise mid-loop
        # would leak the already-created, never-awaited calls.
        results = await asyncio.gather(
            *(verify_ui(e) for e in to_verify), return_exceptions=True
        )
        for res in results:
            if isinstance(res, BaseException):
                raise res
        ui = await verify_ui(vc)
        if ui.counter != base + len(vc.log) + 1:
            raise api.AuthenticationError(
                "VIEW-CHANGE counter does not extend its log"
            )

    return validate_view_change


def make_new_view_validator(n: int, f: int, verify_ui, validate_view_change):
    """Validate a NEW-VIEW: sent by the view's primary, carrying n-f
    distinct valid VIEW-CHANGEs for the same view (see
    :attr:`ViewChangeState.vc_quorum` for why n-f, not f+1)."""

    quorum = n - f

    async def validate_new_view(nv: NewView) -> None:
        if not utils.is_primary(nv.new_view, nv.replica_id, n):
            raise api.AuthenticationError(
                "NEW-VIEW from a replica that is not the view's primary"
            )
        senders = {vc.replica_id for vc in nv.view_changes}
        if len(nv.view_changes) != quorum or len(senders) != quorum:
            raise api.AuthenticationError(
                "NEW-VIEW must carry n-f distinct VIEW-CHANGEs"
            )
        for vc in nv.view_changes:
            if vc.new_view != nv.new_view:
                raise api.AuthenticationError(
                    "NEW-VIEW embeds a VIEW-CHANGE for another view"
                )
        # The per-VC validations are stateless — gather them so the whole
        # quorum's UI checks co-batch on the verification engine instead
        # of paying n-f serial engine round-trips during recovery.
        results = await asyncio.gather(
            *[validate_view_change(vc) for vc in nv.view_changes],
            return_exceptions=True,
        )
        for res in results:
            if isinstance(res, BaseException):
                raise res
        await verify_ui(nv)

    return validate_new_view
