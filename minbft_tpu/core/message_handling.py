"""Message handling: the processing graph and stream pumps.

Reference core/message-handling.go — ``defaultMessageHandlers`` builds a
~30-closure processing graph; here :func:`build_handlers` wires the same
pipeline stages (validate → process → apply, with the generated-message
path assigning UIs under a lock and fanning out through the message log).

Asyncio re-design notes:

- Each connection is a pair of async streams instead of goroutine pairs
  (reference makeMessageStreamHandler, startPeerConnection).
- **Validation awaits batched TPU verification** (the reference's serial
  validate-then-process at message-handling.go:363-377 becomes
  submit-batch-then-resolve): concurrent validations of different messages
  coalesce in the :class:`minbft_tpu.parallel.BatchVerifier`.
- Stateful processing (UI capture, seq capture, quorum accounting) stays
  sequential per peer/client exactly as the reference's condvar-guarded
  state packages require — batching never reorders *effects*.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import OrderedDict
from typing import AsyncIterator, Dict, Optional

from .. import api
from ..messages import (
    CERTIFIED_MESSAGES,
    Busy,
    Checkpoint,
    Commit,
    Hello,
    LogBase,
    Message,
    NewView,
    Prepare,
    ReqViewChange,
    Reply,
    Request,
    SnapshotReq,
    SnapshotResp,
    StateChunk,
    StateDone,
    StateReq,
    UNICAST_LOG_MESSAGES,
    ViewChange,
    authen_bytes,
    drain_multi,
    marshal,
    split_multi,
    stringify,
    unmarshal,
    unmarshal_batch,
)
from ..messages.codec import CodecError
from ..messages.authen import collection_digest as authen_collection_digest
from . import admission as admission_mod
from . import commit as commit_mod
from . import prepare as prepare_mod
from . import request as request_mod
from . import timeout as timeout_mod
from . import checkpoint as checkpoint_mod
from . import usig_ui, utils
from . import viewchange as viewchange_mod
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from ..recovery import manager as recovery_mod
from ..recovery import store as recovery_store
from ..recovery import transfer as recovery_transfer
from ..utils.backoff import ReconnectBackoff
from ..utils.metrics import ReplicaMetrics
from .internal.clientstate import ClientStates
from .internal.messagelog import MessageLog
from .internal.peerstate import PeerStates
from .internal.requestlist import RequestList
from .internal.viewstate import ViewState


class _PrepareBatcher:
    """Groups the primary's captured requests into batched PREPAREs.

    Request batching is an unimplemented roadmap item in the reference
    (reference README.md:505, one request per PREPARE); here the primary
    coalesces requests that arrive within the same event-loop turn (up to
    ``max_batch``) into one PREPARE — one USIG counter value, one
    PREPARE/COMMIT round, and one set of UI verifications for the whole
    batch.  Ship-when-idle: a lone request flushes on the next loop turn,
    so low-load latency is unchanged."""

    def __init__(
        self, replica_id: int, handle_generated, spawn, max_batch: int = 64
    ):
        self.replica_id = replica_id
        self.max_batch = max(1, max_batch)
        self._handle_generated = handle_generated
        # Task factory honoring the _bg_tasks retention contract
        # (Handlers._spawn_bg): a flush task nobody holds is GC-able
        # mid-PREPARE and its failure vanishes.
        self._spawn = spawn
        self._buffers: Dict[int, list] = {}  # view -> pending requests
        self._suspended = 0

    async def propose(self, request: Request, view: int) -> None:
        buf = self._buffers.setdefault(view, [])
        buf.append(request)
        if self._suspended:
            return  # resume() flushes
        if len(buf) >= self.max_batch:
            self._flush(view)
        elif len(buf) == 1:
            asyncio.get_running_loop().call_soon(self._flush, view)

    def suspend(self) -> None:
        """Hold flushes — the view-change applier suspends proposals so the
        new view's re-proposals (S) are certified *before* any fresh
        request, then resumes.  Counted: concurrent transitions nest."""
        self._suspended += 1

    def resume(self, active_view: int) -> None:
        self._suspended -= 1
        if self._suspended:
            return
        for view in list(self._buffers):
            if view < active_view:
                # Abandoned-view proposals must not waste USIG counters
                # (a stale flush when this replica is primary again in
                # view v+n would even split its new view's CV sequence);
                # the buffered requests stay in the pending list, which
                # the view-change applier re-applies in the new view.
                del self._buffers[view]
            else:
                self._flush(view)

    def _flush(self, view: int) -> None:
        if self._suspended:
            return
        buf = self._buffers.get(view)
        if not buf:
            return
        self._buffers[view] = []
        prepare = Prepare(
            replica_id=self.replica_id, view=view, requests=tuple(buf)
        )
        # UI assignment order = task creation order (handle_generated's UI
        # lock wakes waiters FIFO), so batches hit the log in flush order.
        self._spawn(self._handle_generated(prepare))


class Handlers:
    """The wired processing graph (what ``defaultMessageHandlers`` returns,
    reference core/message-handling.go:128-200)."""

    def __init__(
        self,
        replica_id: int,
        n: int,
        f: int,
        configer: api.Configer,
        authenticator: api.Authenticator,
        consumer: api.RequestConsumer,
        message_log: MessageLog,
        unicast_logs: Dict[int, MessageLog],
        client_states: ClientStates,
        logger: Optional[logging.Logger] = None,
        group: Optional[int] = None,
        recovery: Optional["recovery_mod.RecoveryManager"] = None,
    ):
        self.replica_id = replica_id
        self.n = n
        self.f = f
        # Consensus-group id when this core is one of a GroupRuntime's G
        # instances (minbft_tpu/groups): pure observability — it labels
        # the metrics and the flight recorder so per-group cost tables
        # and Prometheus series stay separable on shared transport.
        self.group = group
        self.configer = configer
        self.authenticator = authenticator
        self.consumer = consumer
        self.log = logger or utils.make_logger(replica_id)
        self.message_log = message_log
        self.unicast_logs = unicast_logs
        self.client_states = client_states
        self.peer_states = PeerStates()
        self.view_state = ViewState()
        self.pending = RequestList()
        # Per-peer view-change bar: highest new_view of a VIEW-CHANGE (or
        # NEW-VIEW) processed from each peer.  A peer that voted for view
        # v' froze its log evidence in that VIEW-CHANGE; anything it
        # certifies *afterwards* for a view < v' is outside every
        # NEW-VIEW quorum log, so counting it toward a commit quorum
        # could execute a request the re-proposal set S omits (ledger
        # fork, reachable at f >= 2 with adversarial delivery).  This is
        # the receive-side analogue of "stop sending after voting"
        # (in_transition gates our own sends).  O(n) ints, never pruned.
        self._peer_vc_bar: Dict[int, int] = {}
        self._ui_lock = asyncio.Lock()
        self.metrics = ReplicaMetrics(group=group)
        # Flight recorder (obs/trace.py): per-request stage spans into a
        # preallocated ring + per-stage histograms.  None unless the
        # operator opted in (configer.trace, or the MINBFT_TRACE /
        # MINBFT_TRACE_DUMP env knobs) — every hook below is then ONE
        # predicated attribute check (`if tr is not None`), the ISSUE's
        # disabled-cost contract.
        self.trace = (
            obs_trace.FlightRecorder.for_replica(replica_id, group=group)
            if (getattr(configer, "trace", False) or obs_trace.tracing_enabled())
            else None
        )
        # Latency-SLO budget ledger (obs/slo.py): recv-origin
        # good/breached classification at commit-quorum time against the
        # per-group finality budget.  None unless the operator opted in
        # (configer slo fields from consensus.yaml, or the MINBFT_SLO_*
        # env knobs) — every hook below is then ONE predicated attribute
        # check (`if sl is not None`), the flight recorder's
        # disabled-cost contract.
        self.slo = (
            obs_slo.BudgetLedger(
                obs_slo.SLOPolicy.from_env(group=group, configer=configer),
                group=group,
            )
            if obs_slo.slo_enabled(configer)
            else None
        )

        # Verified-check memo: a COMMIT re-validates its embedded PREPARE
        # (which re-validates the embedded REQUEST), so the same
        # (authen-bytes, tag) pair is verified up to n times per request.
        # Verification is a pure function of those bytes — a passed check is
        # cached (LRU), turning O(n²) verifies per request into O(n).
        # Failures are never cached.  (The reference re-verifies every time,
        # core/commit.go:74-92; this memo preserves its exact semantics.)
        self._verified: "OrderedDict[tuple, None]" = OrderedDict()
        self._verified_cap = 4 * 4096
        # dedup_verify=False (measurement mode, set via the configer)
        # disables this memo so every embedded re-validation actually
        # reaches the authenticator/engine — the reference's O(n²)
        # re-verification behavior, used by the bench's no-dedup phase to
        # report honest protocol-driven device verification rates.
        self._dedup_verify = getattr(configer, "dedup_verify", True)

        def _verified_hit(key: tuple) -> bool:
            if not self._dedup_verify:
                return False
            cache = self._verified
            if key in cache:
                cache.move_to_end(key)
                return True
            return False

        def _verified_put(key: tuple) -> None:
            if not self._dedup_verify:
                return
            cache = self._verified
            cache[key] = None
            if len(cache) > self._verified_cap:
                cache.popitem(last=False)

        # --- signing / verification primitives
        def sign_message(msg) -> None:
            # A REPLY (or BUSY shed signal) is addressed to one client:
            # recipient-specific schemes (MAC) key the tag to it;
            # signature schemes ignore it.
            audience = msg.client_id if isinstance(msg, (Reply, Busy)) else -1
            msg.signature = authenticator.generate_message_authen_tag(
                utils.signing_role(msg), authen_bytes(msg), audience
            )

        async def sign_message_async(msg) -> None:
            # The awaitable sibling for hot-path emission (REPLY signing):
            # concurrent executors co-batch their signatures on the
            # engine's sign queue instead of each paying a serial host
            # sign inline.  Control-plane messages (checkpoints,
            # view-change votes, HELLO) keep the synchronous path — their
            # rate never justifies a batch lane.  USIG certification is
            # untouched either way: the authenticator routes the USIG
            # role serially by design (counter-after-sign).
            audience = msg.client_id if isinstance(msg, (Reply, Busy)) else -1
            msg.signature = await authenticator.generate_message_authen_tag_async(
                utils.signing_role(msg), authen_bytes(msg), audience
            )

        async def verify_signature(msg) -> None:
            peer = msg.client_id if isinstance(msg, Request) else msg.replica_id
            role = utils.signing_role(msg)
            ab = authen_bytes(msg)
            key = (role, peer, ab, msg.signature)
            if _verified_hit(key):
                return
            await authenticator.verify_message_authen_tag(
                role, peer, ab, msg.signature
            )
            _verified_put(key)

        base_verify_ui = usig_ui.make_ui_verifier(authenticator)

        async def verify_ui(msg):
            ui = msg.ui
            if ui is None:
                raise api.AuthenticationError("missing UI")
            key = ("ui", msg.replica_id, authen_bytes(msg), ui.counter, ui.cert)
            if _verified_hit(key):
                return ui
            ui = await base_verify_ui(msg)
            _verified_put(key)
            return ui

        self.sign_message = sign_message
        self.sign_message_async = sign_message_async
        self.verify_signature = verify_signature
        self.verify_ui = verify_ui
        # Exposed for the bundle-ingest seed path (preverify_requests):
        # the seed must HIT the same verified-check memo as the
        # per-message path (already-verified requests are skipped from
        # the seed); feeding the memo stays the per-message path's job.
        self._verified_hit = _verified_hit
        self.assign_ui = usig_ui.make_ui_assigner(authenticator)
        self.capture_ui = usig_ui.make_ui_capturer(self.peer_states)

        # --- timers & view change
        self.request_view_change = timeout_mod.make_view_change_requestor(
            replica_id, self.view_state, sign_message, self._broadcast_signed
        )
        self.handle_request_timeout = timeout_mod.make_request_timeout_handler(
            self.request_view_change
        )

        # --- view-change protocol (beyond reference; core/viewchange.py)
        self.view_change_state = viewchange_mod.ViewChangeState(n, f, replica_id)
        self._viewchange_timeout = getattr(configer, "timeout_viewchange", 8.0)
        self._viewchange_timer = None
        self._viewchange_timer_view = 0  # the view the armed timer escalates
        self._timer_provider = client_states.timers

        def start_request_timer(req: Request, view: int) -> None:
            timeout = configer.timeout_request

            def on_expiry() -> None:
                self.metrics.inc("timeouts_request")
                self.log.warning(
                    "request timeout for client %d seq %d", req.client_id, req.seq
                )
                self._spawn_bg(self.handle_request_timeout(view))

            self.client_states.client(req.client_id).start_request_timer(
                req.seq, timeout, on_expiry
            )

        def start_prepare_timer(req: Request, view: int) -> None:
            timeout = configer.timeout_prepare

            def on_expiry() -> None:
                self.metrics.inc("timeouts_prepare")
                # Forward the starved request to the primary
                # (reference core/request.go:315-324).
                primary = view % n
                self.log.info(
                    "prepare timeout: forwarding request to primary %d", primary
                )
                self._unicast_append(primary, req)

            self.client_states.client(req.client_id).start_prepare_timer(
                req.seq, timeout, on_expiry
            )

        def stop_timers(req: Request) -> None:
            st = self.client_states.client(req.client_id)
            st.stop_request_timer(req.seq)
            st.stop_prepare_timer(req.seq)

        def stop_prepare_timer(req: Request) -> None:
            self.client_states.client(req.client_id).stop_prepare_timer(req.seq)

        # --- request pipeline
        raw_validate_request = request_mod.make_request_validator(verify_signature)

        if self.trace is not None:
            _vtr = self.trace

            async def base_validate_request(req: Request) -> None:
                # Flight-recorder capture point: the REQUEST is about to
                # be submitted for signature verification (recv→here =
                # dispatch and bookkeeping; here→verify_done = the
                # engine round trip including queue wait).
                _vtr.note(obs_trace.R_VERIFY_ENQUEUE, req.client_id, req.seq)
                await raw_validate_request(req)

        else:
            # Tracing off: the raw validator IS the validator — wrapping
            # unconditionally would put an extra coroutine frame on
            # every REQUEST's hot path just to test a None.
            base_validate_request = raw_validate_request

        # Object-level validation marker: the interned message objects (see
        # messages/codec.py) arrive repeatedly — a REQUEST via the client
        # stream, again inside the PREPARE, again inside every COMMIT; the
        # PREPARE again inside every COMMIT.  A *successful* validation is a
        # pure function of the message content AND this replica's trusted
        # keys/config, so the mark is keyed by a token unique to this
        # Handlers instance — never by replica id, which a restarted or
        # co-resident cluster would reuse with different keys (the interned
        # objects are process-global and outlive any one replica).
        # Failures are never recorded.
        vtoken = self._validation_token = object()

        # One marking idiom for every per-Handlers memo on interned message
        # objects (validation below, embedded processing in
        # _process_peer_message): the attribute holds a set of Handlers
        # tokens, never replica ids — see the keying rationale above.
        def _marked(msg, attr: str) -> bool:
            done = msg.__dict__.get(attr)
            return done is not None and vtoken in done

        def _set_mark(msg, attr: str) -> None:
            msg.__dict__.setdefault(attr, set()).add(vtoken)

        self._marked = _marked
        self._set_mark = _set_mark

        def _mark(msg) -> bool:
            """True if this Handlers already validated ``msg``."""
            return _marked(msg, "_validated_by")

        def _record(msg) -> None:
            _set_mark(msg, "_validated_by")

        def _cached_validator(base):
            async def validate_cached(msg) -> None:
                if _mark(msg):
                    return
                await base(msg)
                _record(msg)

            return validate_cached

        self.validate_request = _cached_validator(base_validate_request)
        capture_seq = request_mod.make_seq_capturer(self.client_states)
        self.release_seq = request_mod.make_seq_releaser(self.client_states)
        prepare_seq = request_mod.make_seq_preparer(self.client_states)
        retire_seq = request_mod.make_seq_retirer(self.client_states)

        def add_reply(reply: Reply) -> None:
            self.client_states.client(reply.client_id).add_reply(reply.seq, reply)

        # Flight-recorder stage callbacks for the pipeline factories:
        # plain callables (None when tracing is off) so the factories
        # stay recorder-agnostic and their hot paths pay one predicated
        # check each.
        if self.trace is not None:
            _tr = self.trace

            def trace_prepare(req: Request) -> None:
                _tr.note(obs_trace.R_PREPARE, req.client_id, req.seq)

            def trace_quorum(req: Request) -> None:
                _tr.note(obs_trace.R_COMMIT_QUORUM, req.client_id, req.seq)

            def trace_execute(req: Request) -> None:
                _tr.note(obs_trace.R_EXECUTE, req.client_id, req.seq)

            def trace_reply_sign(reply: Reply) -> None:
                _tr.note(obs_trace.R_REPLY_SIGN, reply.client_id, reply.seq)

        else:
            trace_prepare = trace_quorum = None
            trace_execute = trace_reply_sign = None

        if self.slo is not None:
            # Chain the budget classifier onto the commit-quorum capture
            # point: the pipeline factories still see ONE callable (and
            # pay one predicated check when both recorder and SLO are
            # off — the callable stays None).
            _sl = self.slo
            _tq = trace_quorum

            def trace_quorum(req: Request) -> None:  # noqa: F811
                if _tq is not None:
                    _tq(req)
                _sl.commit(req.client_id, req.seq)

        base_execute = request_mod.make_request_executor(
            replica_id,
            retire_seq,
            self.pending,
            stop_timers,
            consumer,
            sign_message_async,
            add_reply,
            log=self.log,
            metrics=self.metrics,
            sign_message_sync=sign_message,
            trace_execute=trace_execute,
            trace_reply_sign=trace_reply_sign,
        )

        # Checkpointing (phase 1 + 2 — core/checkpoint.py): every
        # checkpoint_period delivered requests, at a batch boundary, sign
        # and broadcast a CHECKPOINT of the composite state digest with
        # per-peer coverage bounds; f+1 matching claims make it stable,
        # stability licenses log truncation, and the retained snapshot
        # serves state transfer.  All replicas emit — checkpoints are
        # signed, not USIG-certified, so the primary's prepare-CV sequence
        # is untouched.
        self.checkpoint_collector = checkpoint_mod.CheckpointCollector(
            f, logger=self.log
        )
        self.coverage = checkpoint_mod.CoverageTracker()
        self.validate_checkpoint_cert = checkpoint_mod.make_cert_validator(
            f, verify_signature
        )
        # Own-log truncation state: counters 1..base are dropped from the
        # broadcast log, vouched by cert (f+1 claims with our coverage
        # bound >= base).  Mirrored into every VIEW-CHANGE we emit.
        self._own_log_base: tuple = (0, ())
        # Execution position (view, cv) at the last batch boundary, and
        # the pending state-transfer bookkeeping.
        self._exec_pos = (0, 0)
        self._snapshot_expect: Optional[Checkpoint] = None
        self._snapshot_sources: list = []  # claimants left to try
        self._snapshot_timer = None
        # Chunked resumable state transfer (recovery subsystem): the
        # assembler for the in-flight STATE-CHUNK stream, the peer it was
        # requested from, and the verified offset at the last retry-timer
        # fire (progress since then means resume-from-offset on the SAME
        # source; no progress means fail over to the next one).
        self._state_asm: Optional[recovery_transfer.ChunkAssembler] = None
        self._state_source: Optional[int] = None
        self._state_progress = 0
        # Recovery telemetry + durable store handle (None = durability and
        # recovery SLOs off; every hook below is one predicated check).
        self.recovery = recovery
        self._pending_new_view: Optional[NewView] = None
        # Strong refs to fire-and-forget background tasks (the deferred
        # NEW-VIEW re-check): discarded by their done-callback.
        self._bg_tasks: set = set()
        self._logsize = getattr(configer, "logsize", 0)
        # Truncation requires state transfer to exist: dropping/stubbing
        # covered history strands any replica that later needs it unless
        # a certified snapshot can replace it.  Consumers without
        # snapshot support still checkpoint (stability, covered-gap
        # acceptance) but never GC.
        self._can_snapshot = (
            type(consumer).snapshot is not api.RequestConsumer.snapshot
        )
        # Swapped + fired whenever the local stable checkpoint advances
        # (stabilization, LOG-BASE / NEW-VIEW certificate adoption) —
        # lets stub acceptance wait out the tiny race where a stub task
        # overtakes the LOG-BASE task on the same stream.
        self._stable_event = asyncio.Event()

        async def emit_signed_checkpoint(cp: Checkpoint) -> None:
            sign_message(cp)
            self.metrics.inc("checkpoints_sent")
            # Record our own claim directly (it also rides the broadcast
            # log to peers; the own-message loop dedups via the
            # collector's newest-claim rule).
            if self.checkpoint_collector.record(cp):
                self._on_checkpoint_stable()
            self.message_log.append(cp)

        self.checkpoint_emitter = checkpoint_mod.CheckpointEmitter(
            replica_id,
            getattr(configer, "checkpoint_period", 0),
            consumer,
            client_states.retire_watermarks,
            self.coverage.bounds_at,
            emit_signed_checkpoint,
        )

        async def execute_counted(req: Request) -> None:
            t0 = time.monotonic()
            delivered = await base_execute(req)
            if not delivered:
                # Already retired (a re-proposed request re-drained after a
                # view change): counting it would diverge the execution
                # count — and so the checkpoint sequence — across replicas
                # that did/didn't execute it pre-transition.
                self.log.info(
                    "skipping already-retired request client %d seq %d",
                    req.client_id,
                    req.seq,
                )
                return
            self.metrics.observe_execute(time.monotonic() - t0)
            self.metrics.inc("requests_executed")
            if self.recovery is not None:
                # Stops the restart-to-first-executed-request clock; cheap
                # no-op on every execution after the first.
                self.recovery.note_executed()
            self.checkpoint_emitter.on_delivered()

        self.execute_request = execute_counted

        async def on_batch_end(view: int, cv: int) -> None:
            self._exec_pos = (view, cv)
            await self.checkpoint_emitter.on_batch_end(view, cv)
            if self._pending_new_view is not None:
                # Ordinary log replay can carry the checkpoint count past
                # a deferred NEW-VIEW's anchor without any snapshot ever
                # installing.  Applying advances the view, which drains
                # the read lease this execution path runs under — so the
                # re-check must run as its own task, outside the lease.
                # The event loop holds only a WEAK reference to running
                # tasks (ADVICE r5): keep a strong one until done, and
                # route the deliberately re-raised apply failure to the
                # log instead of the unretrieved-exception void.
                task = asyncio.get_running_loop().create_task(
                    self._maybe_apply_pending_new_view()
                )
                self._bg_tasks.add(task)
                task.add_done_callback(self._on_bg_task_done)

        self._prepare_batcher = _PrepareBatcher(
            replica_id,
            self.handle_generated,
            self._spawn_bg,
            max_batch=getattr(configer, "batchsize_prepare", 64),
        )

        self.apply_request = request_mod.make_request_applier(
            replica_id,
            n,
            self._prepare_batcher.propose,
            start_prepare_timer,
            start_request_timer,
        )

        async def _process_request_apply(req: Request, view: int) -> None:
            try:
                await self.apply_request(req, view)
            finally:
                await self.release_seq(req)

        self.process_request = request_mod.make_request_processor(
            capture_seq, self.pending, self.view_state, _process_request_apply
        )

        # --- commit pipeline / quorum (instance kept visible so tests can
        # assert its containers stay bounded)
        self.commitment_collector = commit_mod.CommitmentCollector(
            f, self.execute_request, on_batch_end=on_batch_end,
            trace_quorum=trace_quorum,
        )

        async def collect_counted(peer_id: int, prepare: Prepare) -> None:
            self.metrics.inc("commitments_counted")
            await self.commitment_collector.collect(peer_id, prepare)

        self.collect_commitment = collect_counted
        self.apply_commit = commit_mod.make_commit_applier(self.collect_commitment)

        # --- prepare pipeline
        base_apply_prepare = prepare_mod.make_prepare_applier(
            replica_id,
            prepare_seq,
            self.collect_commitment,
            self.handle_generated,
            stop_prepare_timer,
            trace_prepare=trace_prepare,
        )

        async def apply_prepare_counted(prepare: Prepare) -> None:
            await base_apply_prepare(prepare)
            self.metrics.inc("prepares_accepted")

        self.apply_prepare = apply_prepare_counted
        self.validate_prepare = _cached_validator(
            prepare_mod.make_prepare_validator(
                n, self.validate_request, self.verify_ui
            )
        )
        self.validate_commit = commit_mod.make_commit_validator(
            n, self.validate_prepare, self.verify_ui
        )
        self.validate_view_change = _cached_validator(
            viewchange_mod.make_view_change_validator(
                verify_ui, self.validate_checkpoint_cert
            )
        )
        self.validate_new_view = _cached_validator(
            viewchange_mod.make_new_view_validator(
                n, f, verify_ui, self.validate_view_change
            )
        )

        self.reply_request = request_mod.make_request_replier(self.client_states)

    # ------------------------------------------------------------------
    # Generated own messages (reference makeGeneratedMessageHandler /
    # makeGeneratedMessageConsumer, core/message-handling.go:552-587).

    async def handle_generated(self, msg: Message) -> None:
        """Assign a UI under the global UI lock (serialized — USIG counters
        must match log order) and append to the broadcast log."""
        async with self._ui_lock:
            if isinstance(msg, CERTIFIED_MESSAGES):
                if msg.ui is None:  # emit_view_change/emit_checkpoint
                    self.assign_ui(msg)  # pre-assign under this same lock
                if isinstance(msg, (Prepare, Commit)):
                    self.metrics.inc(
                        "prepares_sent"
                        if isinstance(msg, Prepare)
                        else "commits_sent"
                    )
            self.message_log.append(msg)

    def _broadcast_signed(self, msg: Message) -> None:
        """Broadcast a signed (non-certified) own message."""
        self.message_log.append(msg)

    # ------------------------------------------------------------------
    # Validation dispatch (reference validateMessage,
    # core/message-handling.go:409-424).

    async def validate_message(self, msg: Message) -> None:
        if isinstance(msg, Request):
            await self.validate_request(msg)
        elif isinstance(msg, Prepare):
            await self.validate_prepare(msg)
        elif isinstance(msg, Commit):
            await self.validate_commit(msg)
        elif isinstance(msg, ReqViewChange):
            await self.verify_signature(msg)
        elif isinstance(msg, ViewChange):
            await self.validate_view_change(msg)
        elif isinstance(msg, NewView):
            await self.validate_new_view(msg)
        elif isinstance(
            msg,
            (Checkpoint, SnapshotReq, SnapshotResp, StateReq, StateChunk, StateDone),
        ):
            await self.verify_signature(msg)
        elif isinstance(msg, LogBase):
            await self._validate_log_base(msg)
        else:
            raise api.AuthenticationError(f"unexpected message {stringify(msg)}")

    def preverify_requests(self, msgs) -> int:
        """Seed the engine verify queue with a decoded ingest bundle's
        outstanding client-signature checks in ONE batch call; returns
        the number of checks seeded.

        This is deliberately fire-and-forget, NOT a barrier: the caller
        fans the bundle out immediately, and each message's ordinary
        ``validate_request`` submits the same engine item moments later —
        which COALESCES onto the in-flight lane the seed opened
        (``_SchemeQueue._inflight_futs``), so the whole bundle dispatches
        as one engine batch while per-message validation keeps its exact
        semantics (failures raise item-wise on the per-message path, the
        verified-check memo is fed there, nothing double-verifies).
        Awaiting the batch here instead was measured to CHOP the
        pipeline's natural processing waves: ingest ticks serialized on
        engine round trips, requests reached the primary's proposer in
        bundle-sized groups, and PREPAREs shrank — more USIG signing
        (serial by design) and thinner UI-verify batches.

        Skipped entirely (returns 0) in the no-dedup measurement mode:
        with the engine's in-flight coalescing off, every seeded check
        would occupy a SECOND device lane and the reported device rate
        would no longer equal protocol demand.
        """
        if not self._dedup_verify:
            return 0
        if not getattr(self.authenticator, "supports_batch_verify", False):
            # No engine behind the batch surface: a seed would verify
            # everything twice on the serial loop for no coalescing win.
            return 0
        verify_many = self.authenticator.verify_message_authen_tags
        # No trace notes and no validation marks here: the per-message
        # path still walks its full recv -> verify_enqueue -> verify_done
        # span sequence AND its own memo checks (a memo-hit request is
        # merely skipped from the seed — marking it validated here would
        # short-circuit the per-message verify_enqueue note and skew the
        # stage table on exactly the path this runtime exists to measure).
        role = None
        items: list = []
        for m in msgs:
            if not isinstance(m, Request):
                continue
            if self._marked(m, "_validated_by"):
                continue
            ab = authen_bytes(m)
            role = utils.signing_role(m)
            if self._verified_hit((role, m.client_id, ab, m.signature)):
                continue
            items.append((m.client_id, ab, m.signature))
        if not items:
            return 0

        async def seed() -> None:
            # Verdicts are consumed by the per-message validations that
            # coalesced onto these lanes; engine errors surface THERE
            # with full per-message handling, so the seed itself only
            # has to avoid dying loudly.
            try:
                await verify_many(role, items)
            except Exception:  # pragma: no cover - engine failure path
                pass

        task = asyncio.get_running_loop().create_task(seed())
        self._bg_tasks.add(task)
        task.add_done_callback(self._on_bg_task_done)
        return len(items)

    async def _validate_log_base(self, lb: LogBase) -> None:
        """A LOG-BASE claim is exactly its certificate: f+1 matching
        signed checkpoints, each attesting a coverage bound for the
        sender at or above the announced base.  base == 0 is a pure
        certificate announcement (nothing dropped yet, but the stream
        carries stubs the certificate covers)."""
        await self.validate_checkpoint_cert(lb.cert)
        if lb.base > 0 and min(
            c.bound_for(lb.replica_id) for c in lb.cert
        ) < lb.base:
            raise api.AuthenticationError(
                "LOG-BASE base exceeds the certified coverage bounds"
            )

    # ------------------------------------------------------------------
    # Processing dispatch (reference processMessage / processPeerMessage /
    # processViewMessage, core/message-handling.go:426-533).

    async def process_message(self, msg: Message) -> bool:
        if isinstance(msg, Request):
            return await self.process_request(msg)
        if isinstance(msg, CERTIFIED_MESSAGES):
            return await self._process_peer_message(msg)
        if isinstance(msg, ReqViewChange):
            # Beyond the reference (which refuses here, "Not implemented",
            # core/message-handling.go:419): demands are tallied and f+1
            # of them start the view-change transition.
            return await self._process_req_view_change(msg)
        if isinstance(msg, Checkpoint):
            return self._process_checkpoint(msg)
        if isinstance(msg, LogBase):
            return await self._process_log_base(msg)
        if isinstance(msg, SnapshotReq):
            return await self._process_snapshot_req(msg)
        if isinstance(msg, SnapshotResp):
            return await self._process_snapshot_resp(msg)
        if isinstance(msg, StateReq):
            return await self._process_state_req(msg)
        if isinstance(msg, StateChunk):
            return await self._process_state_chunk(msg)
        if isinstance(msg, StateDone):
            return await self._process_state_done(msg)
        raise ValueError(f"unexpected message {stringify(msg)}")

    async def _process_peer_message(self, msg) -> bool:
        if isinstance(msg, (ViewChange, NewView)):
            # Certified view-change messages ride the same per-peer
            # counter-ordered capture, but apply outside the view lease:
            # NEW-VIEW application *advances* the view, which drains the
            # lease it would otherwise hold.
            if not await self.capture_ui(msg):
                return False
            if self.checkpoint_emitter.period > 0:
                self.coverage.track(msg.replica_id, msg.ui.counter, msg)
            # Raise the sender's bar unconditionally (even for votes
            # outside the demand window): per-peer capture order means
            # every later message from this peer was certified after
            # this vote.
            if msg.new_view > self._peer_vc_bar.get(msg.replica_id, 0):
                self._peer_vc_bar[msg.replica_id] = msg.new_view
            if isinstance(msg, ViewChange):
                return await self._apply_view_change(msg)
            return await self._apply_new_view(msg)

        msg_view = msg.view if isinstance(msg, Prepare) else msg.prepare.view

        p = msg if isinstance(msg, Prepare) else msg.prepare
        if p.is_stub:
            # Checkpoint-covered stub from a truncated log replay: its
            # counter slot must be captured (gap-free per-peer
            # sequencing), but it is NEVER applied — executing a stub
            # would let full-vs-stub encodings of one UI (they share
            # authen bytes by construction) diverge replicas, and an
            # up-to-date replica needs nothing from covered history.
            #
            # Capture is gated on the LOCAL stable checkpoint actually
            # covering the stub's batch: an honest sender's stream
            # carries its LOG-BASE certificate ahead of its stubs (the
            # short wait absorbs task-ordering races), while a Byzantine
            # peer stubbing LIVE batches — trying to blind this replica
            # to a batch by consuming its capture slot with the stub
            # encoding — is refused without capture, wedging only the
            # liar's own stream (its un-applied proposals then time out
            # into a view change).
            if not await self._wait_covered(p.view, p.ui.counter):
                raise api.AuthenticationError(
                    f"stub for uncovered batch (view {p.view} cv "
                    f"{p.ui.counter}) refused"
                )
            if isinstance(msg, Commit):
                await self._process_peer_message(msg.prepare)
            if not await self.capture_ui(msg):
                return False
            if self.checkpoint_emitter.period > 0:
                self.coverage.track(msg.replica_id, msg.ui.counter, msg)
            return False

        cur, _ = await self.view_state.hold_view()
        if msg_view > cur:
            # A message from a view this replica hasn't entered yet (its
            # NEW-VIEW is still in flight): park until the transition
            # catches up instead of consuming the peer's counter and
            # losing the message.  Bounded: a claimed view that never
            # materializes drops out after the view-change timeout —
            # EXCEPT while a state transfer is pending, which will
            # advance the view (or keep retrying claimants): letting the
            # park expire mid-transfer would capture-and-refuse commits
            # for batches just above the incoming checkpoint, and the
            # acceptor would then see an uncovered per-peer CV gap for
            # the rest of the view.
            while True:
                try:
                    await asyncio.wait_for(
                        self.view_state.wait_current_at_least(msg_view),
                        max(self._viewchange_timeout, 1.0) * 2,
                    )
                    break
                except asyncio.TimeoutError:
                    if self._snapshot_expect is not None:
                        continue  # transfer in flight: keep parking
                    # The claimed view never materialized: fall through
                    # to the normal capture-then-refuse path rather than
                    # returning here — dropping WITHOUT capturing would
                    # leave a counter gap that wedges every later
                    # message from this peer.
                    self.metrics.inc("messages_dropped_future_view")
                    break

        # Process embedded messages first (reference processEmbedded,
        # core/message-handling.go:454-473).  A batched PREPARE embeds up
        # to batchsize requests and is itself embedded in every COMMIT —
        # naively that re-processes each request ~n+1 times per replica
        # (measured 8 process_request calls per request at n=7).  The
        # re-runs are pure no-ops (seq capture dedups), so the first
        # completed pass is recorded per Handlers (token-keyed like the
        # validation marker — interned objects are process-global) and
        # later carriers of the same PREPARE skip straight to UI capture.
        if isinstance(msg, Prepare):
            if not self._marked(msg, "_embedded_processed"):
                for req in msg.requests:
                    await self.process_request(req)
                self._set_mark(msg, "_embedded_processed")
        elif isinstance(msg, Commit):
            await self._process_peer_message(msg.prepare)

        if not await self.capture_ui(msg):
            return False  # already processed (replay)
        if self.checkpoint_emitter.period > 0:
            # Coverage bookkeeping feeds checkpoint bounds; with
            # checkpointing disabled nothing ever prunes it, so don't
            # let it grow with history.
            self.coverage.track(msg.replica_id, msg.ui.counter, msg)

        # View check + apply under one read lease (reference
        # processViewMessage holds the view, core/message-handling.go:
        # 492-533): apply suspends at awaits, and without the lease a view
        # advancement could interleave — a message checked in view v must
        # not apply in view v+1.
        async with self.view_state.hold_view_lease() as (view, _):
            if msg_view != view or self.view_change_state.in_transition(view):
                # stale view, or this replica voted for a view change (the
                # reference's !active state): captured but not applied —
                # the transition's VIEW-CHANGE logs carry the evidence.
                return False
            if msg_view < self._peer_vc_bar.get(msg.replica_id, 0):
                # The sender already voted for a higher view: this message
                # was certified after its VIEW-CHANGE, so no NEW-VIEW
                # quorum log can contain it — applying it here could
                # commit a request the re-proposal set S omits.
                return False

            if isinstance(msg, Prepare):
                if not self.view_change_state.check_reproposal(msg):
                    # The new primary deviated from the agreed re-proposal
                    # set S — refuse and demand its removal.
                    self.log.warning(
                        "new-view primary deviated from S: %s", stringify(msg)
                    )
                    await self.request_view_change(view + 1)
                    return False
                await self.apply_prepare(msg)
            else:
                await self.apply_commit(msg)
            return True

    # ------------------------------------------------------------------
    # Checkpointing: claim accounting, log truncation, state transfer
    # (phase 2 — core/checkpoint.py).

    def _process_checkpoint(self, cp: Checkpoint) -> bool:
        coll = self.checkpoint_collector
        before = coll.cert_version
        if coll.record(cp):
            self._on_checkpoint_stable()
        elif coll.cert_version != before:
            # A late claim genuinely grew the stable certificate — its
            # bounds may license a deeper truncation.  (No-op replays and
            # divergent claims change nothing and cost nothing.)
            self._maybe_truncate()
        return True

    def _on_checkpoint_stable(self) -> None:
        coll = self.checkpoint_collector
        self.metrics.inc("checkpoints_stable")
        self._note_stable_locally()
        self.log.info(
            "stable checkpoint at %d executions (view %d cv %d, digest %s)",
            coll.stable_count,
            coll.stable_view,
            coll.stable_cv,
            coll.stable_digest.hex()[:12],
        )
        self._maybe_truncate()
        self._spawn_durable_save()

    def _note_stable_locally(self) -> None:
        """Propagate a stable-watermark change: the commitment collector
        learns the covered-gap position and coverage waiters wake."""
        coll = self.checkpoint_collector
        self.commitment_collector.note_stable(
            coll.stable_view, coll.stable_cv
        )
        ev, self._stable_event = self._stable_event, asyncio.Event()
        ev.set()

    def _adopt_cert(self, cert) -> None:
        """Adopt an externally received (validated) stable certificate."""
        coll = self.checkpoint_collector
        before = coll.stable_count
        coll.install(cert)
        if coll.stable_count != before:
            self._note_stable_locally()

    async def _wait_covered(self, view: int, cv: int) -> bool:
        """True once the local stable checkpoint covers batch (view, cv);
        bounded wait — the honest case resolves as soon as the sender's
        LOG-BASE certificate (earlier on the same stream) is adopted, but
        certificate adoption can itself be slow (a cold verification
        engine's first kernel compile takes tens of seconds), so the
        bound matches the future-view park (2x the view-change timeout)
        rather than being aggressively short — a refused honest stub
        wedges its sender's whole capture stream.  Byzantine uncovered
        stubs pin at most the bounded per-stream concurrency slots for
        this long.  Honors a 0 view-change timeout (no wait, tests)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 2.0 * max(self._viewchange_timeout, 0.0)
        while True:
            coll = self.checkpoint_collector
            if (view, cv) <= (coll.stable_view, coll.stable_cv):
                return True
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False
            ev = self._stable_event
            try:
                # No shield: on timeout the inner wait() task must be
                # cancelled so its waiter leaves the long-lived Event
                # (a stub flood would otherwise accumulate one leaked
                # waiter per refusal).
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return False

    def _maybe_truncate(self) -> None:
        """Garbage-collect the broadcast log against the stable
        checkpoint: drop the provably-covered prefix (up to the coverage
        bound β the stable certificate attests for us), stub covered
        retained entries down to their digests, and install a LOG-BASE
        head so lagging subscribers fast-forward instead of wedging.
        Synchronous — atomic with respect to the event loop, so it can
        never interleave with the UI-locked log snapshot in
        emit_view_change."""
        coll = self.checkpoint_collector
        if coll.stable_count == 0 or not self._can_snapshot:
            # Without snapshot support there is no state transfer, and
            # truncated/stubbed history could strand a lagging replica
            # forever — keep the full log (see api.RequestConsumer).
            return
        beta, cert = coll.certificate_for_bound(self.replica_id, self.f + 1)
        if not cert:
            return
        v, cv = coll.stable_view, coll.stable_cv
        old_base, old_cert = self._own_log_base
        if beta < old_base:
            # The fresh certificate's bounds for us lag the base we have
            # ALREADY committed to (e.g. a new position stabilized first
            # through replicas that trail our stream): pairing the old
            # base with a cert that cannot prove it would get our honest
            # VIEW-CHANGE and LOG-BASE rejected everywhere.  Keep the old
            # certificate — and cap stubbing at ITS position, since the
            # head cert must cover every stub a fresh subscriber meets.
            cert = list(old_cert)
            beta = old_base
            v, cv = old_cert[0].view, old_cert[0].cv
        entries = self.message_log.snapshot()
        if self._logsize > 0 and len(entries) <= self._logsize:
            return  # operator asked to retain at least this much history
        # The droppable prefix: certified entries up to counter β that are
        # genuinely covered (belt and braces — β is already provably
        # covered by an honest attester), plus concluded signed messages.
        n_drop = 0
        base = self._own_log_base[0]
        for m in entries:
            if isinstance(m, CERTIFIED_MESSAGES) and m.ui is not None:
                cov = checkpoint_mod.entry_coverage(m)
                if m.ui.counter <= beta and checkpoint_mod.is_covered(
                    cov, v, cv
                ):
                    base = m.ui.counter
                    n_drop += 1
                    continue
                break
            if isinstance(m, LogBase):
                n_drop += 1
                continue
            if isinstance(m, Checkpoint) and m.count < coll.stable_count:
                n_drop += 1
                continue
            if isinstance(m, ReqViewChange) and m.new_view <= v:
                n_drop += 1
                continue
            break
        # Stub covered certified entries in the retained suffix (payload
        # -> digest under the same UI; O(1) per counter slot).
        stubbed = 0
        for i, m in enumerate(entries[n_drop:], start=n_drop):
            if not (isinstance(m, (Prepare, Commit)) and m.ui is not None):
                continue
            p = m if isinstance(m, Prepare) else m.prepare
            if p.is_stub:
                continue
            if not checkpoint_mod.is_covered(
                checkpoint_mod.entry_coverage(m), v, cv
            ):
                continue
            stub_p = Prepare(
                replica_id=p.replica_id,
                view=p.view,
                requests=(),
                ui=p.ui,
                requests_digest=authen_collection_digest(p.requests, p.requests_digest),
            )
            stub = (
                stub_p
                if isinstance(m, Prepare)
                else Commit(replica_id=m.replica_id, prepare=stub_p, ui=m.ui)
            )
            self.message_log.replace(i, stub)
            stubbed += 1
        # Always store the freshest certificate THAT PROVES THE BASE
        # alongside it: our next VIEW-CHANGE must carry a certificate at
        # the position the retained stubs were covered against, with
        # coverage bounds for us >= the base (both enforced by every
        # receiver).  The bound-maximizing cert proves any base <= beta.
        base = max(base, old_base)
        self._own_log_base = (base, tuple(cert))
        head = LogBase(replica_id=self.replica_id, base=base, cert=tuple(cert))
        if base > old_base:
            self.metrics.inc("log_truncations")
            self.message_log.truncate(n_drop, head=head)
            self.log.info(
                "log truncated to counter base %d (%d entries dropped, "
                "%d stubbed) at stable count %d",
                base,
                n_drop,
                stubbed,
                coll.stable_count,
            )
            return
        # No prefix advance, but the log carries (or just gained) stubs:
        # the replayed stream's head certificate must cover every stub a
        # fresh subscriber will meet, or — with f other replicas crashed —
        # it could never assemble f+1 claims for the stubs' position and
        # would wedge on the refused stub.  Refresh (or install) the head
        # in place.
        cert_pos = cert[0].count if cert else 0
        old_pos = old_cert[0].count if old_cert else -1
        # entries still mirrors the live log here (nothing was dropped on
        # this path, and stubbing never swaps in a LogBase).
        head_exists = bool(entries) and isinstance(entries[0], LogBase)
        if stubbed or (head_exists and cert_pos > old_pos):
            if head_exists:
                self.message_log.replace(0, head)
            elif base > 0 or stubbed:
                self.message_log.truncate(0, head=head)

    async def _process_log_base(self, lb: LogBase) -> bool:
        """A peer announced its log now starts above ``lb.base``
        (validated: f+1 certificate with coverage bounds >= base).  Adopt
        the certificate if it is ahead, fetch certified state if *we* are
        behind it, and fast-forward the peer's capture sequence so its
        retained suffix doesn't park on the intentional gap."""
        if lb.replica_id == self.replica_id:
            return True  # own announcement replayed by the own-message loop
        cp = lb.cert[0]
        self._adopt_cert(lb.cert)
        if self.checkpoint_emitter.count < cp.count:
            await self._request_state(lb.cert, first_source=lb.replica_id)
        # State-transfer TOFU: a late joiner never sees this peer's
        # counter-1 UI (the certificate proves that history is covered
        # and it was truncated) — permit first-contact epoch capture from
        # the first valid UI above the certified base, or the joiner
        # installs the snapshot and then rejects every live message.
        # Only while actually BEHIND the certificate: a caught-up replica
        # saw the history (or holds captured epochs), and a standing
        # floor would widen the stale-epoch re-pin window the counter-1
        # rule narrows (see reset_usig_epoch).
        if self.checkpoint_emitter.count < cp.count:
            allow = getattr(
                self.authenticator, "allow_epoch_capture_from", None
            )
            if allow is not None:
                allow(lb.replica_id, lb.base + 1)
        await self.peer_states.peer(lb.replica_id).fast_forward(lb.base + 1)
        return True

    async def _request_state(self, cert, first_source: Optional[int] = None) -> None:
        """Fetch the snapshot at the certificate's checkpoint.  One
        outstanding target at a time (a newer certificate re-targets);
        requests rotate on a retry timer through the certificate's
        claimants FIRST (they provably attested the state) and then every
        other peer — the certificate guarantees a correct attester, not a
        live one, and any replica at or past the checkpoint can serve the
        snapshot (a snapshot-less peer simply doesn't answer and the
        rotation moves on).  So no set of claimant crashes wedges the
        transfer (ADVICE r4)."""
        cp = cert[0]
        prev = self._snapshot_expect
        if prev is not None and prev.count >= cp.count:
            return
        self._snapshot_expect = cp
        sources = [] if first_source in (None, self.replica_id) else [first_source]
        for c in cert:
            if c.replica_id != self.replica_id and c.replica_id not in sources:
                sources.append(c.replica_id)
        for p in self.unicast_logs:
            if p != self.replica_id and p not in sources:
                sources.append(p)
        self._snapshot_sources = sources
        # Re-targeting to a newer certificate abandons any partial stream
        # for the old one (the chunks verified so far belong to a snapshot
        # nobody needs anymore).
        self._state_asm = None
        self._state_source = None
        self._state_progress = 0
        if self.recovery is not None:
            self.recovery.set_phase(recovery_mod.PHASE_FETCHING)
        self._send_state_req()

    def _unicast_append(self, peer_id: int, msg) -> None:
        """THE unicast-log append point.  Only kinds in
        messages.UNICAST_LOG_MESSAGES may ride a unicast log — the
        signed-HELLO replay-harmlessness invariant is defined next to
        that tuple and holds only while every unicast kind is public,
        individually authenticated content.  Route new unicast traffic
        through here so the contract trips loudly, not silently."""
        if not isinstance(msg, UNICAST_LOG_MESSAGES):
            raise TypeError(
                f"{type(msg).__name__} is not a unicast-log kind — see "
                "messages.UNICAST_LOG_MESSAGES (HELLO replay invariant)"
            )
        ulog = self.unicast_logs.get(peer_id)
        if ulog is not None:
            ulog.append(msg)

    def _send_state_req(self, resume: bool = False) -> None:
        """Issue (or re-issue) the chunked STATE-REQ for the pending
        target.  ``resume=True`` keeps the CURRENT source and asks it to
        continue from the verified offset — the mid-transfer-reset path:
        every chunk already assembled was chain-verified, so nothing needs
        re-downloading.  ``resume=False`` rotates to the next source and
        restarts from offset 0 (fresh fetch, or failover after a stalled /
        corrupt stream)."""
        expect = self._snapshot_expect
        if expect is None or not self._snapshot_sources:
            return
        asm = self._state_asm
        if resume and self._state_source is not None and asm is not None:
            via = self._state_source
            # Resume the stream the assembler verified so far — which may
            # be an upgraded (newer) snapshot than the original target.
            count, offset = asm.count, asm.offset
            self.metrics.inc("state_transfer_resumes")
            if self.recovery is not None:
                self.recovery.note_resume()
        else:
            via = self._snapshot_sources.pop(0)
            self._snapshot_sources.append(via)  # retries cycle the claimants
            if self._state_source is not None and via != self._state_source:
                self.metrics.inc("state_transfer_failovers")
                if self.recovery is not None:
                    self.recovery.note_failover()
            self._state_asm = None
            count, offset = expect.count, 0
        self._state_source = via
        self._state_progress = offset
        self.metrics.inc("state_transfer_requests")
        req = StateReq(replica_id=self.replica_id, count=count, offset=offset)
        self.sign_message(req)
        self._unicast_append(via, req)

        def on_expiry() -> None:
            if self._snapshot_expect is None:
                return
            self.metrics.inc("state_transfer_retries")
            cur = self._state_asm
            progressed = cur is not None and cur.offset > self._state_progress
            self._send_state_req(resume=progressed)

        if self._snapshot_timer is not None:
            self._snapshot_timer.cancel()
        self._snapshot_timer = self._timer_provider.after(
            max(self._viewchange_timeout, 1.0), on_expiry
        )

    async def _process_snapshot_req(self, req: SnapshotReq) -> bool:
        snap = self.checkpoint_emitter.snapshot_for(req.count)
        count, cert = req.count, ()
        if snap is None:
            # The exact snapshot aged out of the retention window: offer
            # our newest certified one instead, certificate attached so
            # the requester can verify and upgrade its target.
            coll = self.checkpoint_collector
            if coll.stable_count > req.count:
                snap = self.checkpoint_emitter.snapshot_for(coll.stable_count)
                count = coll.stable_count
                cert = tuple(coll.stable_certificate[: self.f + 1])
        if snap is None:
            self.log.info(
                "no retained snapshot at count %d for replica %d",
                req.count,
                req.replica_id,
            )
            return False
        view, cv, app, marks = snap
        resp = SnapshotResp(
            replica_id=self.replica_id,
            count=count,
            view=view,
            cv=cv,
            app_state=app,
            watermarks=tuple(marks),
            cert=cert,
        )
        self.sign_message(resp)
        self._unicast_append(req.replica_id, resp)
        return True

    def _prune_state_unicast(self, peer_id: int) -> None:
        """Drop the prefix of ``peer_id``'s unicast log consisting of
        state-transfer payload frames — a fresh STATE-REQ supersedes every
        stream we queued for this peer before (its signed offset tells us
        exactly what it still needs, and the new stream re-sends that), so
        retaining them only bloats the log and the reconnect replay.
        Prefix-only: anything behind a non-state frame (e.g. a forwarded
        REQUEST or our own outgoing STATE-REQ) is left alone."""
        ulog = self.unicast_logs.get(peer_id)
        if ulog is None:
            return
        n_drop = 0
        for m in ulog.snapshot():
            if isinstance(m, (SnapshotResp, StateChunk, StateDone)):
                n_drop += 1
            else:
                break
        if n_drop:
            ulog.truncate(n_drop)

    async def _process_state_req(self, req: StateReq) -> bool:
        """Serve a chunked snapshot stream (the resumable counterpart of
        ``_process_snapshot_req``): deterministic fixed-size chunks, each
        signed and carrying the running chain digest recomputed from byte
        zero — so a requester resuming at ``req.offset`` receives chunks
        whose chain commits to the entire prefix it already verified."""
        snap = self.checkpoint_emitter.snapshot_for(req.count)
        count, cert = req.count, ()
        if snap is None:
            # The exact snapshot aged out of the retention window: offer
            # our newest certified one instead (certificate attached on
            # the DONE frame so the requester can verify and upgrade).
            coll = self.checkpoint_collector
            if coll.stable_count > req.count:
                snap = self.checkpoint_emitter.snapshot_for(coll.stable_count)
                count = coll.stable_count
                cert = tuple(coll.stable_certificate[: self.f + 1])
        if snap is None:
            self.log.info(
                "no retained snapshot at count %d for replica %d",
                req.count,
                req.replica_id,
            )
            return False
        view, cv, app, marks = snap
        self._prune_state_unicast(req.replica_id)
        total = len(app)
        # A resume offset only applies to the stream it measured; an
        # upgraded (newer) snapshot restarts from zero.  Offsets are
        # chunk-aligned by construction — a stale/misaligned one degrades
        # into the requester's failover path, never into bad bytes.
        offset = min(req.offset, total) if count == req.count else 0
        rec = self.recovery
        chain = b""
        for off, piece in recovery_transfer.iter_chunks(
            app, recovery_transfer.chunk_bytes()
        ):
            chain = recovery_transfer.chain_extend(chain, piece)
            if off < offset:
                continue  # the requester already verified this prefix
            ck = StateChunk(
                replica_id=self.replica_id,
                count=count,
                offset=off,
                total=total,
                data=piece,
                chain=chain,
            )
            self.sign_message(ck)
            self._unicast_append(req.replica_id, ck)
            self.metrics.inc("state_chunks_sent")
            if rec is not None:
                rec.note_chunk_tx(len(piece))
        done = StateDone(
            replica_id=self.replica_id,
            count=count,
            view=view,
            cv=cv,
            total=total,
            watermarks=tuple(marks),
            cert=cert,
        )
        self.sign_message(done)
        self._unicast_append(req.replica_id, done)
        return True

    async def _process_state_chunk(self, ck: StateChunk) -> bool:
        """Assemble one verified chunk of the in-flight stream.  Chunks
        from peers we did not ask, for streams we are not assembling, or
        below the verified offset (reconnect replays) are ignored
        idempotently; a chain mismatch is Byzantine evidence and fails the
        fetch over to the next source immediately."""
        if self._snapshot_expect is None or ck.replica_id != self._state_source:
            return False
        asm = self._state_asm
        if asm is None:
            # First chunk of a fresh stream: must start at zero, and may
            # carry a NEWER count than requested (the responder upgraded;
            # certified at the DONE frame before anything installs).
            if ck.offset != 0 or ck.count < self._snapshot_expect.count:
                return False
            asm = self._state_asm = recovery_transfer.ChunkAssembler(ck.count)
        if ck.count != asm.count:
            return False  # stale replay from a superseded stream
        try:
            fresh = asm.add(ck.offset, ck.total, ck.data, ck.chain)
        except recovery_transfer.ChainMismatch as e:
            self.log.warning(
                "corrupt state chunk from replica %d at offset %d: %s — "
                "failing over",
                ck.replica_id,
                ck.offset,
                e,
            )
            self.metrics.inc("state_transfer_corrupt")
            self._state_asm = None
            self._send_state_req()
            return False
        if fresh:
            self.metrics.inc("state_chunks_received")
            if self.recovery is not None:
                self.recovery.note_chunk_rx(len(ck.data))
        return fresh

    async def _process_state_done(self, done: StateDone) -> bool:
        """Terminal frame of a chunk stream: resolve the certified target
        (expected or upgraded), check the assembled length, and install
        through the same verified sequence as a monolithic SNAPSHOT-RESP.
        A stream that assembled cleanly but fails the f+1-certified
        composite digest is Byzantine (self-consistent garbage) — fail
        over to the next source."""
        if self._snapshot_expect is None or done.replica_id != self._state_source:
            return False
        asm = self._state_asm
        if asm is not None:
            if done.count != asm.count:
                return False
            if asm.offset != done.total:
                # Incomplete (a DONE replayed ahead of its chunks after a
                # reset): the retry timer resumes from the verified
                # offset; nothing to do now.
                return False
            app = asm.bytes()
        else:
            # Empty-snapshot stream: no chunks at all, just the DONE.
            if done.total != 0 or done.count < self._snapshot_expect.count:
                return False
            app = b""
        target = await self._resolve_transfer_target(
            done.count, done.view, done.cv, done.cert
        )
        if target is None:
            ok = False
        else:
            ok = await self._finish_state_transfer(
                target,
                done.count,
                done.view,
                done.cv,
                app,
                tuple(done.watermarks),
                done.replica_id,
            )
        if not ok and self._snapshot_expect is not None:
            self.metrics.inc("state_transfer_corrupt")
            self._state_asm = None
            self._send_state_req()
        return ok

    async def _resolve_transfer_target(self, count, view, cv, cert):
        """Map a transfer payload's claimed position to the certified
        target checkpoint: the expected one, or — when the responder's
        retention window moved past it — a NEWER one vouched by the
        attached certificate (verified independently, then adopted).
        Returns None when the payload matches neither."""
        expect = self._snapshot_expect
        if count == expect.count:
            return expect
        if count > expect.count and cert:
            try:
                target = await self.validate_checkpoint_cert(cert)
            except api.AuthenticationError as e:
                self.log.warning("bad snapshot-upgrade cert: %s", e)
                return None
            if (target.count, target.view, target.cv) != (count, view, cv):
                return None
            self._adopt_cert(cert)
            return target
        return None

    def _clear_state_transfer(self) -> None:
        self._snapshot_expect = None
        self._snapshot_sources = []
        self._state_asm = None
        self._state_source = None
        self._state_progress = 0
        if self._snapshot_timer is not None:
            self._snapshot_timer.cancel()
            self._snapshot_timer = None

    async def _finish_state_transfer(
        self, target, count, view, cv, app, watermarks, source
    ) -> bool:
        """Verify a fully-transferred snapshot against the f+1-certified
        composite digest and install it — the shared tail of the
        monolithic (SNAPSHOT-RESP) and chunked (STATE-DONE) paths."""
        if self.checkpoint_emitter.count >= count:
            # We caught up past the snapshot while it was in flight (e.g.
            # replaying full history from an untruncated peer): installing
            # now would REWIND the application state below the retire
            # watermarks and diverge this replica forever.
            self._clear_state_transfer()
            # A NEW-VIEW deferred behind this transfer must not die with
            # it: the catch-up that made the snapshot stale may equally
            # have carried us past the NEW-VIEW's anchor (and if it did
            # not, the re-check restarts the transfer) — otherwise the
            # replica stays wedged in the old view, silently consuming
            # the fault budget.
            await self._maybe_apply_pending_new_view()
            return False
        try:
            app_digest = self.consumer.snapshot_digest(app)
        except (ValueError, NotImplementedError) as e:
            self.log.warning("rejected snapshot at %d: %r", count, e)
            return False
        composite = checkpoint_mod.checkpoint_digest(
            app_digest, count, view, cv, watermarks
        )
        if composite != target.digest or (view, cv) != (target.view, target.cv):
            self.log.warning(
                "snapshot at %d does not match the certified digest "
                "(from replica %d)",
                count,
                source,
            )
            return False
        rec = self.recovery
        if rec is not None:
            rec.set_phase(recovery_mod.PHASE_INSTALLING)
        self.consumer.install_snapshot(app)
        self.client_states.install_retire_watermarks(watermarks)
        self.commitment_collector.install_checkpoint(view, cv)
        self.checkpoint_emitter.install(count)
        self._exec_pos = (view, cv)
        self._clear_state_transfer()
        self.metrics.inc("state_transfers")
        self.log.info(
            "state transfer complete: installed certified state at "
            "count %d (view %d cv %d) from replica %d",
            count,
            view,
            cv,
            source,
        )
        if rec is not None:
            # The broadcast-log replay delta-catches-up the tail from here.
            rec.set_phase(recovery_mod.PHASE_CATCHUP)
        cur, _ = await self.view_state.hold_view()
        if view > cur:
            await self.view_state.advance_expected_view(view)
            await self.view_state.advance_current_view(view)
        await self._maybe_apply_pending_new_view()
        return True

    async def _process_snapshot_resp(self, resp: SnapshotResp) -> bool:
        """Install a transferred snapshot once it checks out against the
        f+1-certified composite digest — then jump execution, watermarks,
        and the view to the certified position and retry any view entry
        that was waiting on the state."""
        if self._snapshot_expect is None:
            return False
        target = await self._resolve_transfer_target(
            resp.count, resp.view, resp.cv, resp.cert
        )
        if target is None:
            return False
        return await self._finish_state_transfer(
            target,
            resp.count,
            resp.view,
            resp.cv,
            resp.app_state,
            tuple(resp.watermarks),
            resp.replica_id,
        )

    # ------------------------------------------------------------------
    # Durable checkpoint store (recovery subsystem): persist every new
    # stable position, restore it crash-consistently at startup.

    def _own_ui_counter(self) -> int:
        """Highest own USIG counter this replica has certified — the
        watermark persisted alongside the stable state.  The broadcast log
        holds every certified entry above the truncation base, so the
        newest one (scanned from the tail) plus the base bounds it."""
        hi = self._own_log_base[0]
        for m in reversed(self.message_log.snapshot()):
            ui = getattr(m, "ui", None)
            if ui is not None:
                return max(hi, ui.counter)
        return hi

    def _spawn_durable_save(self) -> None:
        """Persist the freshly-stabilized position off-loop.  Never
        persists unverified bytes: the snapshot is recomputed against the
        stable composite digest first, so the store only ever holds state
        the f+1 certificate actually vouches for."""
        rec = self.recovery
        if rec is None or rec.store is None:
            return
        coll = self.checkpoint_collector
        count = coll.stable_count
        snap = self.checkpoint_emitter.snapshot_for(count)
        if snap is None:
            return  # no retained snapshot at the stable position
        view, cv, app, marks = snap
        try:
            app_digest = self.consumer.snapshot_digest(app)
        except (ValueError, NotImplementedError):
            return
        if (
            checkpoint_mod.checkpoint_digest(app_digest, count, view, cv, marks)
            != coll.stable_digest
        ):
            self.log.error(
                "local snapshot at %d diverges from the stable digest — "
                "not persisting",
                count,
            )
            return
        state = recovery_store.StableState(
            count=count,
            view=view,
            cv=cv,
            usig_counter=self._own_ui_counter(),
            app_state=app,
            watermarks=tuple(marks),
            cert=tuple(coll.stable_certificate[: self.f + 1]),
        )
        self._spawn_bg(self._durable_save(state))

    async def _durable_save(self, state) -> None:
        rec = self.recovery
        try:
            wrote = await asyncio.to_thread(rec.store.save, state)
        except OSError as e:
            rec.note_save_error()
            self.metrics.inc("recovery_save_errors")
            self.log.error("durable checkpoint save failed: %r", e)
            return
        if wrote:
            rec.note_saved(state.count)
            self.metrics.inc("recovery_saves")

    async def restore_from_store(self) -> None:
        """Crash-consistent startup restore (called by ``_Replica.start``
        BEFORE any peer connection): load the durable stable state,
        re-validate its f+1 certificate and recompute the composite digest
        — the file is a cache of certified state, never an authority —
        then install exactly like a completed state transfer.  The normal
        broadcast-log replay delta-catches-up the tail from here, and a
        LOG-BASE above our restored count triggers an ordinary chunked
        fetch.  A corrupted committed file raises
        :class:`minbft_tpu.recovery.store.CorruptStoreError` — deliberately
        fatal (``peer run`` exits non-zero) rather than a silent fresh
        start."""
        rec = self.recovery
        if rec is None or rec.store is None:
            return
        rec.set_phase(recovery_mod.PHASE_LOADING)
        state = await asyncio.to_thread(rec.store.load)
        if state is None:
            rec.set_phase(recovery_mod.PHASE_IDLE)
            return
        rec.arm()
        try:
            target = await self.validate_checkpoint_cert(state.cert)
        except api.AuthenticationError as e:
            raise recovery_store.CorruptStoreError(
                f"durable store certificate invalid: {e}"
            )
        if (target.count, target.view, target.cv) != (
            state.count,
            state.view,
            state.cv,
        ):
            raise recovery_store.CorruptStoreError(
                "durable store position does not match its certificate"
            )
        try:
            app_digest = self.consumer.snapshot_digest(state.app_state)
        except (ValueError, NotImplementedError) as e:
            raise recovery_store.CorruptStoreError(
                f"durable store snapshot rejected by the consumer: {e!r}"
            )
        composite = checkpoint_mod.checkpoint_digest(
            app_digest, state.count, state.view, state.cv, state.watermarks
        )
        if composite != target.digest:
            raise recovery_store.CorruptStoreError(
                "durable store snapshot does not match its f+1 certificate"
            )
        self._adopt_cert(state.cert)
        self.consumer.install_snapshot(state.app_state)
        self.client_states.install_retire_watermarks(state.watermarks)
        self.commitment_collector.install_checkpoint(state.view, state.cv)
        self.checkpoint_emitter.install(state.count)
        self._exec_pos = (state.view, state.cv)
        rec.restored_count = state.count
        rec.set_phase(recovery_mod.PHASE_CATCHUP)
        self.metrics.inc("recovery_restores")
        self.log.info(
            "recovered durable state at count %d (view %d cv %d, usig "
            "watermark %d)",
            state.count,
            state.view,
            state.cv,
            state.usig_counter,
        )
        cur, _ = await self.view_state.hold_view()
        if state.view > cur:
            await self.view_state.advance_expected_view(state.view)
            await self.view_state.advance_current_view(state.view)

    def _spawn_bg(self, coro) -> "asyncio.Task":
        """``create_task`` under the ``_bg_tasks`` retention contract
        (TL601): the loop holds only a weak reference to running tasks,
        so the set keeps the strong one and the done-callback routes any
        failure to the replica log instead of the unretrieved void."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._on_bg_task_done)
        return task

    def _on_bg_task_done(self, task) -> None:
        """Done-callback for fire-and-forget background tasks: drop the
        strong reference and surface any failure in the replica log (the
        task has no awaiter — without this its exception only appears as
        an unretrieved-task warning at interpreter teardown, if ever)."""
        self._bg_tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self.log.error("background task failed: %r", exc)

    async def _maybe_apply_pending_new_view(self) -> None:
        """Retry a NEW-VIEW that was deferred behind a state transfer.

        Re-applies once the local checkpoint count reaches the NEW-VIEW's
        quorum anchor, OR when no transfer is in flight anymore (the
        deferred entry's transfer was dropped): in the latter case
        ``_apply_new_view`` re-defers and re-requests the anchor state
        itself, so calling it is always safe.  Must be invoked outside the
        view read lease — applying advances the view, which drains leases.
        """
        nv = self._pending_new_view
        if nv is None:
            return
        anchor_count = viewchange_mod.quorum_anchor(nv.view_changes)[0]
        if (
            self.checkpoint_emitter.count < anchor_count
            and self._snapshot_expect is not None
        ):
            return  # still legitimately waiting on the in-flight transfer
        self._pending_new_view = None
        try:
            await self._apply_new_view(nv)
        except Exception:
            # An apply failure must not lose the NEW-VIEW forever (it was
            # already captured, so it is never redelivered) — especially on
            # the batch-end path, where this runs in a fire-and-forget task
            # and the exception would otherwise vanish.  _apply_new_view
            # may itself have re-deferred (set a fresh pending) before
            # raising; only restore if it didn't.
            if self._pending_new_view is None:
                self._pending_new_view = nv
            raise

    # ------------------------------------------------------------------
    # View-change protocol steps (beyond reference — core/viewchange.py).

    async def _process_req_view_change(self, msg: ReqViewChange) -> bool:
        cur, _ = await self.view_state.hold_view()
        if not self.view_change_state.in_window(msg.new_view, cur):
            return False  # stale, or absurdly far ahead (memory bound)
        if self.view_change_state.record_demand(msg.replica_id, msg.new_view):
            await self._start_transition(msg.new_view)
        return True

    async def _start_transition(self, new_view: int) -> None:
        """f+1 demands reached: stop applying current-view messages and
        broadcast this replica's certified VIEW-CHANGE."""
        vcs = self.view_change_state
        if new_view in vcs.sent_view_change:
            return
        vcs.sent_view_change.add(new_view)
        await self.view_state.advance_expected_view(new_view)
        self.metrics.inc("view_changes_started")

        # If the new primary is faulty too, its NEW-VIEW never arrives:
        # demand the next view after the view-change timeout.
        def on_expiry() -> None:
            async def escalate() -> None:
                cur, _ = await self.view_state.hold_view()
                if cur < new_view:
                    self.metrics.inc("timeouts_viewchange")
                    await self.request_view_change(new_view + 1)

            self._spawn_bg(escalate())

        # Re-arm only forward: demand quorums can complete out of order,
        # and a late lower-view transition must not silence the timer
        # guarding a higher pending one (mirrors the NEW-VIEW cancel
        # guard in _apply_new_view).
        if self._viewchange_timeout > 0 and new_view >= self._viewchange_timer_view:
            if self._viewchange_timer is not None:
                self._viewchange_timer.cancel()
            self._viewchange_timer = self._timer_provider.after(
                self._viewchange_timeout, on_expiry
            )
            self._viewchange_timer_view = new_view

        await self.emit_view_change(new_view)

    async def emit_view_change(self, new_view: int) -> None:
        """Build and broadcast this replica's VIEW-CHANGE.  The log
        snapshot and the UI assignment happen under one UI lock hold, so
        the claimed log is exactly counters log_base+1..k and the
        VIEW-CHANGE gets k+1 — the contiguity every receiver checks.
        Checkpoint truncation scopes the log: counters at or below the
        base are vouched by the attached f+1 certificate (coverage bounds
        >= base), so view-change work is O(checkpoint window), not
        O(history)."""
        async with self._ui_lock:
            base, cert = self._own_log_base
            log = tuple(
                viewchange_mod.trim_log_entry(m)
                for m in self.message_log.snapshot()
                if isinstance(m, CERTIFIED_MESSAGES) and m.ui is not None
            )
            vc = ViewChange(
                replica_id=self.replica_id,
                new_view=new_view,
                log=log,
                log_base=base,
                checkpoint_cert=cert,
            )
            self.assign_ui(vc)
            self.metrics.inc("view_changes_sent")
            self.message_log.append(vc)

    async def _apply_view_change(self, vc: ViewChange) -> bool:
        cur, _ = await self.view_state.hold_view()
        if not self.view_change_state.in_window(vc.new_view, cur):
            return False  # concluded view, or beyond the demand window
        vcs = self.view_change_state
        quorum = vcs.record_view_change(vc)
        # A VIEW-CHANGE is implicitly a demand: a replica that missed the
        # REQ-VIEW-CHANGE quorum still joins the transition once enough
        # peers have moved (prevents stragglers from stalling in the old
        # view while the quorum awaits their VIEW-CHANGE).
        if vcs.record_demand(vc.replica_id, vc.new_view):
            await self._start_transition(vc.new_view)
        if (
            quorum
            and utils.is_primary(vc.new_view, self.replica_id, self.n)
            and vc.new_view not in vcs.sent_new_view
        ):
            vcs.sent_new_view.add(vc.new_view)
            nv = NewView(
                replica_id=self.replica_id,
                new_view=vc.new_view,
                view_changes=tuple(vcs.quorum_for(vc.new_view)),
            )
            await self.handle_generated(nv)
        return True

    async def _apply_new_view(self, nv: NewView) -> bool:
        """Enter ``nv.new_view``: derive the re-proposal set S, arm its
        enforcement, register the new primary's counter base, advance the
        view, and (as the new primary) certify S before any fresh
        proposal."""
        cur, _ = await self.view_state.hold_view()
        if nv.new_view <= cur:
            return False
        anchor_count, av, acv, anchor_cert = viewchange_mod.quorum_anchor(
            nv.view_changes
        )
        if anchor_cert:
            # The quorum's best certified checkpoint: batches at or below
            # it are NOT re-proposed — every replica entering the view
            # must hold that state.  If we are behind it, fetch it first
            # and re-enter once installed (the NEW-VIEW is already
            # captured, so it won't be redelivered).
            self._adopt_cert(anchor_cert)
            if self.checkpoint_emitter.count < anchor_count:
                self._pending_new_view = nv
                self.log.info(
                    "NEW-VIEW %d anchored at count %d ahead of local %d: "
                    "state transfer before entering",
                    nv.new_view,
                    anchor_count,
                    self.checkpoint_emitter.count,
                )
                await self._request_state(anchor_cert)
                return False
        s_prepares = viewchange_mod.compute_new_view_set(
            nv.view_changes, nv.new_view
        )
        batches = [viewchange_mod.batch_key(p) for p in s_prepares]
        self.view_change_state.arm_reproposals(nv.new_view, list(batches))
        self.commitment_collector.set_view_base(nv.new_view, nv.ui.counter)

        self._prepare_batcher.suspend()
        try:
            await self.view_state.advance_expected_view(nv.new_view)
            if not await self.view_state.advance_current_view(nv.new_view):
                return False
            if (
                self._viewchange_timer is not None
                and self._viewchange_timer_view <= nv.new_view
            ):
                # Only disarm an escalation this NEW-VIEW satisfies — a
                # late NEW-VIEW for an older view must not silence the
                # timer still guarding a higher pending transition.
                self._viewchange_timer.cancel()
                self._viewchange_timer = None
            self.view_change_state.prune_through(nv.new_view)
            self.commitment_collector.prune_view_bases(nv.new_view)
            self.metrics.inc("view_changes_completed")
            # Health surface (ISSUE 14): the scrape-side minbft_health_view
            # gauge reads this stamp instead of suspending on view_state.
            self.metrics.note_view(nv.new_view)
            reproposal_ids = [
                [seq for _, seq in viewchange_mod.batch_key(p)]
                for p in s_prepares
            ]
            self.log.info(
                "entered view %d (%d re-proposals: %s)",
                nv.new_view,
                len(s_prepares),
                reproposal_ids,
            )
            if utils.is_primary(nv.new_view, self.replica_id, self.n):
                for p in s_prepares:
                    await self.handle_generated(
                        Prepare(
                            replica_id=self.replica_id,
                            view=nv.new_view,
                            requests=p.requests,
                        )
                    )
        finally:
            cur_after, _ = await self.view_state.hold_view()
            self._prepare_batcher.resume(cur_after)

        # Re-apply pending requests in the new view (the primary proposes
        # them; backups restart prepare timers) — skipping those S already
        # re-proposed.
        reproposed = {key for b in batches for key in b}
        for req in self.pending.all():
            if (req.client_id, req.seq) in reproposed:
                continue
            async with self.view_state.hold_view_lease() as (view, _):
                if view == nv.new_view:
                    await self.apply_request(req, view)
        return True

    # ------------------------------------------------------------------
    # Top-level handlers (reference handleClientMessage / handlePeerMessage /
    # handleOwnMessage, core/message-handling.go:352-403).

    async def handle_client_message(
        self, msg: Message, turn=None
    ) -> Optional[Reply]:
        if not isinstance(msg, Request):
            raise api.AuthenticationError("client stream accepts only REQUEST")
        self.metrics.inc("messages_handled")
        self.metrics.inc("requests_received")
        tr = self.trace
        if tr is not None:
            tr.note(obs_trace.R_RECV, msg.client_id, msg.seq)
        sl = self.slo
        if sl is not None:
            sl.arrive(msg.client_id, msg.seq)
        await self.validate_message(msg)
        if tr is not None:
            tr.note(obs_trace.R_VERIFY_DONE, msg.client_id, msg.seq)
        if msg.is_fast_read:
            # Fast path: answered from committed state, no ordering, no
            # seq capture, no USIG — the caller's finally releases the
            # arrival-order ticket (never waited on here).  Ordered reads
            # (read_mode=2, the fallback) ride the normal pipeline below
            # and execute via consumer.query at their slot.
            return await self._reply_read_only(msg)
        if turn is not None:
            # Concurrent validations may complete out of order; capture
            # must happen in arrival order (see _TurnSequencer).  The turn
            # is released the moment processing ends — holding it across
            # the reply quorum wait below would serialize the pipeline to
            # one request per client.
            sequencer, t = turn
            await sequencer.wait_turn(t)
            try:
                await self.process_message(msg)
            finally:
                sequencer.finish(t)
            return await self.reply_request(msg)
        await self.process_message(msg)
        # Reply once executed (even to a duplicate request — the client may
        # be retrying a lost reply, reference message-handling.go:396-403).
        # None for a stale retry of a superseded seq: only the client's
        # LAST reply is buffered (reference reply.go:25-60), so there is
        # nothing to send (the reference closes the reply channel without
        # sending, reply.go:74-79).
        return await self.reply_request(msg)

    async def _reply_read_only(self, req: Request) -> Optional[Reply]:
        """Answer a read-only REQUEST from committed state without
        ordering it (the reference lists read-only requests as roadmap,
        README.md:503-504).  Correctness: the client accepts the fast
        read only when ALL n replies match — with n=2f+1, any smaller
        read quorum cannot be guaranteed to intersect a write quorum in
        a correct replica — and otherwise falls back to an ordered
        request.  A consumer without query() support drops the request
        into the same fallback."""
        # Feature probe, not an identity check on the method object: a
        # delegating wrapper consumer advertises ``supports_query`` and
        # keeps the fast-read path (api.consumer_supports_query).
        if not api.consumer_supports_query(self.consumer):
            self.metrics.inc("readonly_unsupported")
            return None
        error = False
        try:
            result = await self.consumer.query(req.operation)
        except NotImplementedError:
            # A consumer that overrides query but refuses at runtime:
            # answer a signed error (like the ordered path) so the client
            # fails fast with the typed error instead of burning its
            # read_timeout on an all-n quorum that can never form.
            self.metrics.inc("readonly_unsupported")
            error = True
            result = b""
        except Exception as e:
            # The operation bytes are CLIENT-CONTROLLED: a consumer bug
            # on crafted input must cost this read, not detonate in the
            # stream processor as an internal error.  Answer a SIGNED
            # error reply (one WARNING line, not a traceback — the log
            # rate is attacker-chosen): an all-n error quorum raises
            # ReadOnlyQueryError at the client without burning its
            # read_timeout.
            self.log.warning(
                "read-only query failed: %r (op %r...)", e, req.operation[:32]
            )
            self.metrics.inc("readonly_query_errors")
            error = True
            result = b""
        reply = Reply(
            replica_id=self.replica_id,
            client_id=req.client_id,
            seq=req.seq,
            result=result,
            read_only=True,
            error=error,
        )
        # Fast reads arrive many-at-once under load: co-batch their REPLY
        # signatures on the sign queue like the ordered executor does.
        await self.sign_message_async(reply)
        tr = self.trace
        if tr is not None:
            tr.note(obs_trace.R_REPLY_SIGN, reply.client_id, reply.seq)
        if not error:
            self.metrics.inc("readonly_served")
        return reply

    async def handle_peer_message(self, msg: Message) -> None:
        if isinstance(
            msg,
            (
                *CERTIFIED_MESSAGES,
                ReqViewChange,
                Request,
                Checkpoint,
                LogBase,
                SnapshotReq,
                SnapshotResp,
                StateReq,
                StateChunk,
                StateDone,
            ),
        ):
            self.metrics.inc("messages_handled")
            try:
                await self.validate_message(msg)
            except api.EmbeddedRequestAuthError:
                # A UI-certified proposal embeds a request this replica
                # cannot authenticate (MAC asymmetry / faulty client or
                # primary).  The primary's counter has moved past a
                # message we will never accept, so every later message
                # from it would park on the gap — demand a view change
                # instead of wedging; with f+1 peers demanding, the full
                # view-change protocol (core/viewchange.py) deposes the
                # primary.
                view = (
                    msg.view
                    if isinstance(msg, Prepare)
                    else msg.prepare.view if isinstance(msg, Commit) else None
                )
                if view is not None:
                    await self.request_view_change(view + 1)
                raise
            await self.process_message(msg)
        else:
            raise api.AuthenticationError(
                f"unexpected peer message {stringify(msg)}"
            )

    async def handle_own_message(self, msg: Message) -> None:
        """Own messages replayed from the log are trusted — no validation
        (reference handleOwnMessage, core/message-handling.go:352-361).
        Own REQ-VIEW-CHANGE/VIEW-CHANGE/NEW-VIEW count toward our own
        quorums the same way peers' do.  Own CHECKPOINTs were already
        recorded at emission (the collector's newest-claim rule dedups
        the replay); own LOG-BASE heads are for peers."""
        if isinstance(msg, CERTIFIED_MESSAGES):
            await self._process_peer_message(msg)
        elif isinstance(msg, ReqViewChange):
            await self._process_req_view_change(msg)
        elif isinstance(msg, Checkpoint):
            self._process_checkpoint(msg)


# ---------------------------------------------------------------------------
# Stream pumps.


def _wire_bytes(msg: Message) -> bytes:
    """Marshal with per-object memo.  Only used for messages already in a
    message log (final — UIs/signatures assigned), which are re-marshalled
    once per subscribed peer stream."""
    cached = msg.__dict__.get("_wire_bytes")
    if cached is None:
        cached = marshal(msg)
        msg.__dict__["_wire_bytes"] = cached
    return cached


# Upper bound on concurrently-processed messages per incoming stream: enough
# that per-peer in-order UI capture (which may briefly park a task) never
# stalls the pipeline, small enough to bound memory under a message flood.
_STREAM_CONCURRENCY = 1024

# A run of this many consecutive NON-authentication processing failures on
# one peer stream closes the connection (see run_peer_connection).
_MAX_CONSECUTIVE_INTERNAL_ERRORS = 32


class _ConcurrentStreamProcessor:
    """Handle each incoming message in its own task.

    The reference dedicates one goroutine per stream and processes messages
    serially (core/message-handling.go:204-246).  Serial processing defeats
    batched verification: message k+1's (stateless) validation cannot start
    until message k's full validate+process finishes, so verification
    batches never fill.  Here validation runs concurrently across messages
    — per-peer processing *order* is still enforced downstream by the
    in-order UI capture (peerstate) and per-client seq capture
    (clientstate), exactly the batching-vs-ordering split of SURVEY.md §7.
    """

    def __init__(self, handle, on_error, on_success=None):
        self._handle = handle
        self._on_error = on_error
        self._on_success = on_success
        self._sem = asyncio.Semaphore(_STREAM_CONCURRENCY)
        self._tasks: set = set()

    async def submit(self, data: bytes) -> None:
        await self._sem.acquire()
        task = asyncio.get_running_loop().create_task(self._run(data, None))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def submit_msg(self, msg: Message) -> None:
        await self._sem.acquire()
        task = asyncio.get_running_loop().create_task(self._run(None, msg))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def try_submit_msg(self, msg: Message) -> bool:
        """Non-blocking :meth:`submit_msg`: False when the concurrency
        bound is exhausted instead of awaiting a slot.  The grouped
        client drain (minbft_tpu/groups) uses this so ONE saturated
        group's processor sheds ITS OWN messages — client retransmission
        heals the loss — rather than head-of-line blocking every other
        group's traffic on the shared stream (the same drop-on-full
        isolation contract as the transport's per-group rx queues).
        The locked() probe and the acquire are loop-atomic: with a free
        slot, Semaphore.acquire returns without suspending."""
        if self._sem.locked():
            return False
        await self._sem.acquire()
        task = asyncio.get_running_loop().create_task(self._run(None, msg))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return True

    async def try_submit(self, data: bytes) -> bool:
        """Non-blocking :meth:`submit` (the grouped per-frame fallback
        path's variant of :meth:`try_submit_msg`)."""
        if self._sem.locked():
            return False
        await self._sem.acquire()
        task = asyncio.get_running_loop().create_task(self._run(data, None))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return True

    async def _run(self, data: Optional[bytes], msg: Optional[Message]) -> None:
        try:
            if msg is None:
                msg = unmarshal(data)
            await self._handle(msg)
            if self._on_success is not None:
                self._on_success()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._on_error(e)
        finally:
            self._sem.release()

    async def drain(self) -> None:
        """Wait for every in-flight message task to finish."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
            # Awaiting already-done tasks does NOT suspend, but the
            # done-callbacks that prune _tasks ride call_soon — yield one
            # loop turn so they run, or this spins forever (and a spinning
            # coroutine starves the event loop, so no wait_for timeout can
            # ever rescue the caller).
            await asyncio.sleep(0)

    def cancel(self) -> None:
        # Snapshot: cancelling a task that is already FINISHING can run
        # its done-callback synchronously and mutate the set mid-iteration.
        for t in list(self._tasks):
            t.cancel()


# Bundle-ingest knobs.  MINBFT_BUNDLE_INGEST=0 reverts every stream pump
# to the per-frame-task path (the A/B lever perf/BATCH_RUNTIME.md uses);
# MINBFT_INGEST_MAX bounds the flat frames drained into one tick's bundle
# (the bench's ingest-batch-size sweep axis).  Read per stream setup, so
# tests and the bench sweep can toggle without reimporting.
_BUNDLE_ENV = "MINBFT_BUNDLE_INGEST"
_INGEST_MAX_ENV = "MINBFT_INGEST_MAX"
# Transport frames buffered between the stream pump and the tick loop:
# when full, the pump's put() blocks and the transport sees backpressure
# (the same role the submit semaphore plays for in-flight tasks).
_INGEST_RX_BOUND = 256
_INGEST_EOF = object()


def bundle_ingest_enabled() -> bool:
    return os.environ.get(_BUNDLE_ENV, "").lower() not in ("0", "false", "no")


def _ingest_max_frames() -> int:
    try:
        return max(1, int(os.environ.get(_INGEST_MAX_ENV, "1024")))
    except ValueError:
        return 1024


class _BundleIngestor:
    """Tick-driven bundle ingest for one incoming stream.

    Replaces per-frame task spawning on the stream's decode/validate hot
    path: a pump task moves transport frames into a bounded queue, and
    the tick loop drains EVERYTHING buffered per iteration into one flat
    frame bundle — the ``drain_multi`` write-side pattern mirrored on
    read.  The bundle is decoded in one vectorized call
    (``messages.codec.unmarshal_batch``, item-wise errors), its
    signature checks are SEEDED to the engine verify queue in one call
    (client streams; see :meth:`Handlers.preverify_requests` — the
    per-message validations coalesce onto the seeded lanes), and the
    messages fan out to the ordered processing pipeline — per-peer UI
    capture and per-client seq capture stay the ordering boundary,
    exactly the batching-vs-ordering split documented on
    :class:`_ConcurrentStreamProcessor`.

    Concurrency: every attribute is confined to the owning event loop
    (the pump and tick tasks of ONE stream; LD-spec'd in
    tools/analyze/project.py).  ``_eof_pending`` is the pump's non-edge
    EOF signal: the sentinel put can be dropped by a full queue, the
    flag cannot — the tick loop checks it whenever the queue runs dry.
    """

    def __init__(
        self,
        handlers: Handlers,
        on_error,
        submit,
        preverify=None,
        max_frames: Optional[int] = None,
    ):
        self._handlers = handlers
        self._on_error = on_error
        self._submit = submit  # async callable(Message)
        self._preverify = preverify  # sync callable(list[Message]) -> int
        self._max_frames = max_frames or _ingest_max_frames()
        self._rx: asyncio.Queue = asyncio.Queue(maxsize=_INGEST_RX_BOUND)
        self._eof_pending = False

    async def run(self, in_stream: AsyncIterator[bytes]) -> None:
        """Pump + tick until the stream ends (returns) or the caller
        cancels (propagates)."""
        pump = asyncio.get_running_loop().create_task(self._pump(in_stream))
        try:
            await self._ticks()
        finally:
            pump.cancel()
            pump.add_done_callback(lambda t: t.cancelled() or t.exception())

    async def _pump(self, in_stream: AsyncIterator[bytes]) -> None:
        rx = self._rx
        try:
            async for data in in_stream:
                await rx.put(data)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # An abnormal stream end (transport reset, protocol error in
            # the generator) must stay visible: the tick loop treats the
            # latched EOF as a clean end either way — the caller's redial
            # machinery handles recovery — but the CAUSE belongs in the
            # log, not the unretrieved-exception void.
            self._handlers.metrics.inc("ingest_stream_errors")
            self._handlers.log.warning("ingest stream failed: %r", e)
        finally:
            # One-way latch, loop-atomic store: the only write anywhere,
            # and the tick loop only reads it between awaits — no
            # read-modify-write spans a suspension.
            self._eof_pending = True  # noqa: LD001
            try:
                rx.put_nowait(_INGEST_EOF)
            except asyncio.QueueFull:
                # The tick loop cannot be parked in get() while the queue
                # is full — it will drain, see the flag, and stop.
                pass

    def _split_into(self, data: bytes, flat: list) -> None:
        try:
            flat.extend(split_multi(data))
        except CodecError as e:
            self._on_error(e)

    async def _ticks(self) -> None:
        rx = self._rx
        metrics = self._handlers.metrics
        while True:
            if self._eof_pending and rx.empty():
                return
            data = await rx.get()
            if data is _INGEST_EOF:
                return
            # Admission gauge: rx occupancy as this tick wakes (+1 for
            # the frame just popped) — the saturation signal the BUSY
            # retry-after hint scales by, and the high-water mark the
            # overload tests assert bounded (metrics.note_admission_rx).
            metrics.note_admission_rx(rx.qsize() + 1, rx.maxsize)
            flat: list = []
            self._split_into(data, flat)
            saw_eof = False
            while len(flat) < self._max_frames and not rx.empty():
                nxt = rx.get_nowait()
                if nxt is _INGEST_EOF:
                    saw_eof = True
                    break
                self._split_into(nxt, flat)
            await self._ingest(flat)
            if saw_eof:
                return

    async def _ingest(self, frames: list) -> None:
        if not frames:
            return
        h = self._handlers
        h.metrics.observe_ingest(len(frames))
        decoded = []
        for m in unmarshal_batch(frames):
            if isinstance(m, CodecError):
                self._on_error(m)
            else:
                decoded.append(m)
        if not decoded:
            return
        if self._preverify is not None:
            tr = h.trace
            if tr is not None:
                for m in decoded:
                    if isinstance(m, Request):
                        tr.note(obs_trace.R_INGEST, m.client_id, m.seq)
            sl = h.slo
            if sl is not None:
                for m in decoded:
                    if isinstance(m, Request):
                        sl.arrive(m.client_id, m.seq)
            self._preverify(decoded)
        for m in decoded:
            await self._submit(m)


class _TurnSequencer:
    """Restores ARRIVAL order between concurrent per-message tasks.

    Client-stream messages are validated concurrently (so verification
    co-batches on the engine), but per-client seq capture assumes seqs
    arrive in order — the client enqueues them in seq order and the
    stream is FIFO, yet validation completes out of order, and a higher
    seq reaching capture first makes the retire watermark jump past the
    lower one (silently wedging it; observed at ~1 in 10 flagship bench
    runs).  Each message takes a ticket at arrival; after validating, it
    waits its turn before the stateful processing step and releases the
    turn right after (never across the reply quorum wait, which would
    serialize the pipeline).  A ticket is released on EVERY exit —
    including validation failure — so a rejected message never wedges
    the queue behind it."""

    def __init__(self):
        self._issue = 0
        self._next = 0
        self._completed: set = set()
        self._events: Dict[int, asyncio.Event] = {}

    def ticket(self) -> int:
        t = self._issue
        self._issue += 1
        return t

    async def wait_turn(self, t: int) -> None:
        if self._next == t:
            return
        ev = self._events.setdefault(t, asyncio.Event())
        await ev.wait()

    def finish(self, t: int) -> None:
        """Idempotent: the happy path finishes right after processing
        (before the reply wait) and the error path finishes again from
        its finally."""
        if t < self._next or t in self._completed:
            return
        self._completed.add(t)
        while self._next in self._completed:
            self._completed.discard(self._next)
            self._events.pop(self._next, None)
            self._next += 1
        ev = self._events.get(self._next)
        if ev is not None:
            ev.set()


class PeerStreamHandler(api.MessageStreamHandler):
    """Server side of a peer connection: expect HELLO, then stream the
    broadcast log + the hello sender's unicast log
    (reference makeHelloHandler, core/message-handling.go:316-350).

    The HELLO's replica signature is verified BEFORE the claimed id is
    bound to a unicast-log subscription — the reference trusts the id
    unauthenticated (round-4 verdict weak #6).  Replays of a captured
    signed HELLO are accepted by design: see the harmlessness argument on
    :class:`minbft_tpu.messages.Hello`."""

    def __init__(self, handlers: Handlers):
        self.handlers = handlers

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        first = await _anext(in_stream)
        if first is None:
            return
        hello = unmarshal(first)
        if not isinstance(hello, Hello):
            raise api.AuthenticationError("peer stream must start with HELLO")
        h = self.handlers
        if not (0 <= hello.replica_id < h.n) or hello.replica_id == h.replica_id:
            raise api.AuthenticationError(
                f"HELLO claims invalid replica id {hello.replica_id}"
            )
        await h.verify_signature(hello)  # raises on an id-spoofing peer
        peer_id = hello.replica_id

        queue: asyncio.Queue = asyncio.Queue()
        done = asyncio.Event()

        async def pump(log: MessageLog, resume: int = 0) -> None:
            async for msg in log.stream(done):
                if resume:
                    # Resumable replay: the subscriber has already
                    # captured every certified counter below ``resume``
                    # — skip those entries instead of shipping them
                    # through a possibly-lossy link just to be dedup'd
                    # at capture.  Non-certified kinds (CHECKPOINT,
                    # REQ-VIEW-CHANGE, LOG-BASE heads) always replay:
                    # they are few (the log truncates at checkpoints)
                    # and dedup receiver-side.
                    ui = getattr(msg, "ui", None)
                    if ui is not None and ui.counter < resume:
                        continue
                await queue.put(msg)

        loop = asyncio.get_running_loop()
        tasks = [loop.create_task(pump(h.message_log, hello.resume_counter))]
        ulog = h.unicast_logs.get(peer_id)
        if ulog is not None:
            tasks.append(loop.create_task(pump(ulog)))

        # Also consume (and process) any further messages the peer sends on
        # this stream (the reference's separate incoming direction) — each
        # in its own task so their validations co-batch.
        def _drop_peer(e: Exception) -> None:
            h.metrics.inc("messages_dropped")
            h.log.warning("dropping peer message: %s", e)

        proc = _ConcurrentStreamProcessor(h.handle_peer_message, _drop_peer)

        async def consume_incoming() -> None:
            if bundle_ingest_enabled():
                # Peer bundles batch the DECODE (vectorized, item-wise
                # errors) and the per-tick drain; validation stays
                # per-message — PREPARE/COMMIT checks are UI-certificate
                # work that already co-batches across the concurrent
                # handler tasks.
                await _BundleIngestor(h, _drop_peer, proc.submit_msg).run(
                    in_stream
                )
                return
            async for data in in_stream:
                try:
                    frames = split_multi(data)
                except CodecError as e:
                    _drop_peer(e)
                    continue
                for fr in frames:
                    await proc.submit(fr)

        tasks.append(loop.create_task(consume_incoming()))

        try:
            while True:
                msg = await queue.get()
                # Coalesce whatever else is already queued into ONE stream
                # frame: under load the per-frame transport cost (gRPC +
                # asyncio plumbing) dominates the multi-process cluster's
                # throughput, and bursts (a PREPARE plus the COMMIT wave it
                # triggers) are common.
                data, _ = drain_multi(_wire_bytes(msg), queue, encode=_wire_bytes)
                yield data
        finally:
            done.set()
            proc.cancel()
            for t in tasks:
                t.cancel()


class ClientStreamHandler(api.MessageStreamHandler):
    """Server side of a client connection: REQUESTs in, REPLYs out
    (reference ClientMessageStreamHandler, core/replica.go:97-104)."""

    def __init__(self, handlers: Handlers):
        self.handlers = handlers

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        h = self.handlers
        out_queue: asyncio.Queue = asyncio.Queue()
        FIN = object()
        turns = _TurnSequencer()

        async def handle_one(msg: Message) -> None:
            t = turns.ticket()
            try:
                reply = await h.handle_client_message(msg, turn=(turns, t))
            finally:
                # Every exit — validation failure included — releases the
                # turn, or every later message on this stream would wedge
                # behind it.
                turns.finish(t)
            if reply is None:
                # Stale retry of a superseded seq: the last-reply buffer
                # skipped past it (reference ReplyChannel closes without
                # sending, reply.go:74-79).
                return
            data = marshal(reply)
            tr = h.trace
            if tr is not None:
                # reply_sent = the REPLY is marshaled and queued on the
                # stream (the last point this replica controls).
                tr.note(obs_trace.R_REPLY_SENT, reply.client_id, reply.seq)
            await out_queue.put(data)

        # Requests are handled concurrently (replies may take a quorum
        # round-trip each, and a pipelined client sends many requests per
        # stream), bounded + pruned by the stream processor so a request
        # flood cannot grow replica memory without bound.
        def _drop_client(e: Exception) -> None:
            h.metrics.inc("messages_dropped")
            h.log.warning("dropping client message: %s", e)

        proc = _ConcurrentStreamProcessor(handle_one, _drop_client)
        # Admission boundary (ISSUE 15): when the processor's concurrency
        # bound is exhausted, shed with a signed BUSY on out_queue instead
        # of blocking the ingest tick (open-loop offered load would wedge
        # the rx queue at its bound while the generator keeps pushing).
        # MINBFT_ADMISSION=0 reverts to the blocking backpressure path.
        if admission_mod.admission_enabled():
            adm = admission_mod.AdmissionController(h, proc, out_queue)
            submit_msg, submit_frame = adm.submit_msg, adm.submit
        else:
            submit_msg, submit_frame = proc.submit_msg, proc.submit

        async def consume() -> None:
            if bundle_ingest_enabled():
                # Bundle-ingest hot path: drain everything buffered per
                # tick, decode it as ONE vectorized batch, seed the
                # engine with the bundle's signature checks in one call,
                # then fan out in arrival order (the _TurnSequencer
                # tickets are issued in fan-out order, so the ordering
                # boundary is unchanged).
                await _BundleIngestor(
                    h,
                    _drop_client,
                    submit_msg,
                    preverify=h.preverify_requests,
                ).run(in_stream)
            else:
                async for data in in_stream:
                    try:
                        frames = split_multi(data)
                    except CodecError as e:
                        _drop_client(e)
                        continue
                    for fr in frames:
                        await submit_frame(fr)
            await proc.drain()
            await out_queue.put(FIN)

        consumer_task = asyncio.get_running_loop().create_task(consume())
        try:
            while True:
                item = await out_queue.get()
                if item is FIN:
                    break
                # Coalesce ready replies into one frame (see the peer pump).
                data, fin = drain_multi(item, out_queue, stop=FIN)
                yield data
                if fin:
                    break
        finally:
            # Cancel-and-await: a consume() failure (not just
            # cancellation) re-raises here instead of rotting as an
            # unretrieved task exception.
            consumer_task.cancel()
            try:
                await consumer_task
            except asyncio.CancelledError:
                pass


async def _anext(ait: AsyncIterator[bytes]) -> Optional[bytes]:
    try:
        return await ait.__anext__()
    except StopAsyncIteration:
        return None


async def run_own_message_loop(handlers: Handlers, done: asyncio.Event) -> None:
    """Self-delivery of own generated messages (reference
    handleOwnPeerMessages, core/message-handling.go:294-302): this is how
    the primary counts its own PREPARE and a backup its own COMMIT.

    Each own message is processed in its own task: an own COMMIT embeds the
    *primary's* PREPARE, whose in-order capture may need to wait for an
    earlier primary message still in flight — that wait must not
    head-of-line-block self-delivery of subsequent own messages (own-CV
    order is still enforced by peerstate capture on our own UIs)."""

    async def handle(msg: Message) -> None:
        await handlers.handle_own_message(msg)

    proc = _ConcurrentStreamProcessor(
        handle,
        lambda e: handlers.log.error("own-message processing failed: %r", e),
    )
    try:
        async for msg in handlers.message_log.stream(done):
            await proc.submit_msg(msg)
    finally:
        proc.cancel()


async def run_peer_connection(
    handlers: Handlers,
    peer_id: int,
    stream_handler: api.MessageStreamHandler,
    done: asyncio.Event,
) -> None:
    """Client side of a peer connection: send HELLO, process the peer's
    reply stream (reference startPeerConnection,
    core/message-handling.go:269-290).

    Messages are handled concurrently (one bounded task each), like the
    server-side pumps: this stream carries the peer's whole broadcast log —
    the primary's PREPAREs and every peer's COMMITs — and serial handling
    here would head-of-line-block on each quorum round-trip, starving the
    verification batches.  Per-peer processing *order* is still enforced
    downstream by in-order UI capture.

    The dial loop RECONNECTS with backoff when the stream ends or fails
    (network blip, peer crash/restart): without it a survivor would
    permanently stop receiving this peer's broadcast log — peer A's
    messages reach B only over B's dial to A, so a single dropped
    connection silently halves the link forever.  Reconnection is safe by
    design: the peer's HELLO replay re-streams its retained log, already-
    captured messages dedup at capture, and the validated-check memo makes
    re-validation cheap.  A run of consecutive INTERNAL errors still tears
    the connection down permanently (a local bug would loop forever)."""

    async def outgoing() -> AsyncIterator[bytes]:
        # Resumable replay: everything below next_expected() is already
        # captured, so tell the publisher to skip it.  Stamped at dial
        # time (the generator body runs on first iteration), so every
        # redial resumes from the CURRENT capture frontier — through a
        # lossy link this heals a counter gap with one short tail replay
        # instead of re-traversing the whole log (which re-gaps with
        # probability 1-(1-p)^N, the chaos soak's redial storm).
        hello = Hello(
            replica_id=handlers.replica_id,
            resume_counter=peer_state.next_expected(),
        )
        handlers.sign_message(hello)
        yield marshal(hello)
        # Keep the stream open until shutdown.
        await done.wait()

    # Expected per-message failures (bad tag, malformed bytes) are drops;
    # anything else is an internal error.  A persistent internal bug must
    # not degrade into an endless silently-dropping stream — after a run of
    # consecutive internal errors the connection is torn down loudly (the
    # pre-concurrency behavior, where one such exception killed the
    # stream).
    internal = {"consecutive": 0}

    def _drop(e: Exception) -> None:
        handlers.metrics.inc("messages_dropped")
        if isinstance(e, (api.AuthenticationError, CodecError)):
            internal["consecutive"] = 0
            handlers.log.warning("peer %d message rejected: %s", peer_id, e)
        else:
            internal["consecutive"] += 1
            handlers.log.error("peer %d message failed: %r", peer_id, e)

    def _ok() -> None:
        # Successful handling breaks an error run — only genuinely
        # CONSECUTIVE internal failures (a wedged handler) tear the
        # connection down; sporadic transients never accumulate.
        internal["consecutive"] = 0

    # Capture-gap watchdog: a certified message lost on a LIVE stream (a
    # lossy or partitioned link — a faithful transport only loses frames
    # by dropping the connection) leaves this peer's counter sequence
    # gapped, parking every later message forever; only a redial's HELLO
    # replay can redeliver the missing counter.  When a gap sits parked
    # with NO capture progress (gap_stalled_for — progress resets the
    # clock, so a long replay actively healing the gap is never torn
    # down) past the bound, AND the current stream has had a full bound
    # of its own to deliver (a fresh redial inherits parked captures
    # from the last stream's drain — judging it by their age would kill
    # every replay mid-flight, a redial storm), the dialer tears its own
    # stream down and lets the normal redial loop heal the gap.  The
    # bound rides the view-change timeout (the gap's worst casualty is
    # the VIEW-CHANGE quorum the transition is waiting on) with a floor
    # well above any healthy capture reorder.
    vc_t = getattr(handlers, "_viewchange_timeout", 8.0)
    gap_redial_s = max(1.0, min(vc_t if vc_t > 0 else 8.0, 8.0))
    # Idle-refresh watchdog: a lossy link can drop the TAIL of a burst —
    # a NEW-VIEW with no follow-on traffic leaves no counter gap to park
    # on, no frame to time out, nothing: the subscriber just sits in the
    # old view forever (the chaos soak's silent-wedge signature).  The
    # only cure is asking the publisher again, so a stream that has
    # delivered NOTHING for a full idle window is torn down and redialed
    # immediately (no redial-ladder backoff — a refresh, not a failure).
    # Resumable HELLO replay makes the refresh nearly free: an
    # up-to-date subscriber replays an empty tail.  The WINDOW itself
    # backs off, though: on a genuinely quiescent cluster every refresh
    # finds nothing (the stream only ever delivered the dial-time replay
    # burst), and a fixed window would churn teardown+HELLO handshakes
    # forever — consecutive find-nothing refreshes double the window up
    # to 8x, and a stream that keeps delivering past its replay burst
    # (real traffic) resets it, so the next silent-tail loss under load
    # still heals within the base window.
    idle_redial_base_s = max(2.0 * gap_redial_s, 3.0)
    idle_redial_s = idle_redial_base_s
    peer_state = handlers.peer_states.peer(peer_id)

    backoff = ReconnectBackoff()
    ingest = bundle_ingest_enabled()
    while not done.is_set():
        proc = _ConcurrentStreamProcessor(handlers.handle_peer_message, _drop, _ok)
        attempt_start = time.monotonic()
        last_rx = attempt_start
        idle_refresh = False
        cancelled = False
        # Per-STREAM counter (see _MAX_CONSECUTIVE_INTERNAL_ERRORS): errors
        # accumulated across redials must not add up to a permanent
        # teardown — that would rebuild the silent link-halving wedge
        # reconnection exists to prevent.
        internal["consecutive"] = 0
        stream = stream_handler.handle_message_stream(outgoing())
        ait = stream.__aiter__()
        nxt: Optional[asyncio.Future] = None

        def _gap_wedged() -> bool:
            return (
                time.monotonic() - attempt_start > gap_redial_s
                and peer_state.gap_stalled_for() > gap_redial_s
            )

        try:
            while True:
                # Race the next frame against the gap watchdog so a
                # quiet-but-gapped stream still redials.
                nxt = asyncio.ensure_future(ait.__anext__())
                gap_redial = False
                while not nxt.done():
                    await asyncio.wait({nxt}, timeout=min(gap_redial_s / 2, 1.0))
                    if nxt.done():
                        break
                    if _gap_wedged():
                        gap_redial = True
                        break
                    if time.monotonic() - last_rx > idle_redial_s:
                        idle_refresh = True
                        break
                if idle_refresh:
                    handlers.metrics.inc("idle_redials")
                    handlers.log.info(
                        "peer %d stream idle > %.1fs: refreshing (resumable "
                        "replay)",
                        peer_id,
                        idle_redial_s,
                    )
                    # Replay-burst frames land within ~a gap bound of the
                    # dial; deliveries past that mark real traffic.
                    if last_rx - attempt_start > gap_redial_s:
                        idle_redial_s = idle_redial_base_s
                    else:
                        idle_redial_s = min(
                            idle_redial_s * 2.0, 8.0 * idle_redial_base_s
                        )
                    break
                if gap_redial:
                    handlers.metrics.inc("gap_redials")
                    handlers.log.warning(
                        "peer %d capture gap stalled > %.1fs: redialing for "
                        "log replay",
                        peer_id,
                        gap_redial_s,
                    )
                    break
                try:
                    data = nxt.result()
                except StopAsyncIteration:
                    break
                nxt = None
                last_rx = time.monotonic()
                if done.is_set():
                    break
                if internal["consecutive"] >= _MAX_CONSECUTIVE_INTERNAL_ERRORS:
                    handlers.log.error(
                        "peer %d connection closed: %d consecutive internal "
                        "processing errors",
                        peer_id,
                        internal["consecutive"],
                    )
                    return
                try:
                    frames = split_multi(data)
                except CodecError as e:
                    _drop(e)
                    continue
                if ingest:
                    # The publisher's drain_multi already coalesced this
                    # frame into a bundle — decode it as one vectorized
                    # batch (item-wise errors) and fan the typed messages
                    # out, instead of spawning a decode task per frame.
                    # (The dial loop keeps its own watchdog-raced read
                    # structure, so the rx-queue tick loop is not used
                    # here.)
                    handlers.metrics.observe_ingest(len(frames))
                    for m in unmarshal_batch(frames):
                        if isinstance(m, CodecError):
                            _drop(m)
                        else:
                            await proc.submit_msg(m)
                else:
                    for fr in frames:
                        await proc.submit(fr)
                if _gap_wedged():
                    handlers.metrics.inc("gap_redials")
                    handlers.log.warning(
                        "peer %d capture gap stalled > %.1fs: redialing for "
                        "log replay",
                        peer_id,
                        gap_redial_s,
                    )
                    break
        except asyncio.CancelledError:
            cancelled = True
            raise
        except Exception:
            handlers.log.exception("peer %d connection failed", peer_id)
        finally:
            if nxt is not None:
                if nxt.done():
                    try:
                        nxt.exception()  # retrieve, or asyncio logs it
                    except asyncio.CancelledError:
                        pass
                else:
                    # cancel() can lose the race against the asend
                    # completing (StopAsyncIteration on a stream that
                    # just ended) — retrieve whatever lands so asyncio
                    # never logs "exception was never retrieved".
                    nxt.cancel()
                    nxt.add_done_callback(
                        lambda t: t.cancelled() or t.exception()
                    )
            # Close the manually-iterated stream so the handler's own
            # finally (pump teardown) runs now, not at GC.  Transport
            # teardown errors are noise here, but a CANCELLATION landing
            # while suspended in aclose must propagate — swallowing it
            # would return this supposedly-cancelled task to the redial
            # loop and stall the stop() awaiting it.
            aclose_cancel = False
            try:
                await ait.aclose()
            except asyncio.CancelledError:
                # Finish the teardown first (proc.cancel below rides the
                # `cancelled` flag), then re-raise at the end of this
                # finally so the cancellation wins.
                cancelled = True
                aclose_cancel = True
            except Exception:
                pass
            # Lived time is the STREAM's lifetime: measured before the
            # drain, which can add up to 30s a crash-looping peer never
            # earned toward the ladder's lived-connection reset.
            lived = time.monotonic() - attempt_start
            # A dropped stream must not cancel handlers mid-flight: a task
            # cancelled between UI capture and apply loses that message
            # FOREVER (the reconnect replay dedups at capture), so let
            # in-flight work finish first — bounded, because a handler
            # parked on a pathological wait must not stall the redial.
            # Skipped entirely on shutdown/cancellation: replay-loss no
            # longer matters and stop() must not stall 30s behind a
            # handler parked on a wait its dying peers can never resolve.
            if cancelled or done.is_set():
                proc.cancel()
            else:
                # The drain bound tracks the view-change timeout instead
                # of a flat 30s: chaos soaks (tests/test_chaos.py) showed
                # that after a lossy stream dies, the tasks still in
                # flight are mostly parked PRE-capture on a counter gap a
                # dropped certified message left — work that can only
                # complete once the redial's HELLO replay redelivers the
                # gap, so a long drain delays the very recovery it is
                # waiting for.  Genuine mid-apply work still gets a
                # multiple of the cluster's own patience knob.
                vc = getattr(handlers, "_viewchange_timeout", 8.0)
                drain_s = min(30.0, max(1.0, 2.0 * vc)) if vc > 0 else 1.0
                try:
                    await asyncio.wait_for(asyncio.shield(proc.drain()), drain_s)
                except asyncio.TimeoutError:
                    pass
                except asyncio.CancelledError:
                    # Cancelled mid-drain by a cancel-only caller: the
                    # cancellation must win, not be eaten into a redial.
                    proc.cancel()
                    raise
                proc.cancel()
            if aclose_cancel:
                raise asyncio.CancelledError()
        if done.is_set():
            return
        if idle_refresh:
            # A refresh is not a failure: redial immediately and leave
            # the ladder alone (its pace is bounded by idle_redial_s, so
            # skipping the backoff cannot storm).
            continue
        delay = backoff.next_delay(lived)
        handlers.metrics.inc("peer_reconnects")
        handlers.log.warning(
            "peer %d stream ended: reconnecting in %.1fs", peer_id, delay
        )
        try:
            await asyncio.wait_for(done.wait(), delay)
            return  # shutdown during the backoff
        except asyncio.TimeoutError:
            pass
