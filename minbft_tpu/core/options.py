"""Functional options for replica construction (reference core/options.go:25-58).

The reference configures its logger through functional options passed to
``minbft.New`` (``WithLogLevel``, ``WithLogFile``; default DEBUG to
stdout).  Here options are callables applied to an :class:`Options` holder;
``new_replica(..., opts=[...])`` uses the result to build the per-replica
logger (and to inject a test timer provider).

    replica = new_replica(0, cfg, auth, conn, ledger,
                          opts=[with_log_level(logging.DEBUG),
                                with_log_file("replica0.log")])
"""

from __future__ import annotations

import dataclasses
import logging
import sys
from typing import Callable, List, Optional

Option = Callable[["Options"], None]


@dataclasses.dataclass
class Options:
    log_level: int = logging.INFO
    log_file: Optional[str] = None
    log_stream: object = None  # defaults to stderr
    logger: Optional[logging.Logger] = None
    timer_provider: object = None


def with_log_level(level: int) -> Option:
    """Set the logging level (reference WithLogLevel, options.go:36-41)."""

    def apply(o: Options) -> None:
        o.log_level = level

    return apply


def with_log_file(path: str) -> Option:
    """Log to ``path`` instead of the console (reference WithLogFile,
    options.go:43-48)."""

    def apply(o: Options) -> None:
        o.log_file = path

    return apply


def with_log_stream(stream) -> Option:
    """Log to an open stream (stdout, a StringIO, ...)."""

    def apply(o: Options) -> None:
        o.log_stream = stream

    return apply


def with_logger(logger: logging.Logger) -> Option:
    """Use a fully caller-configured logger (bypasses the other log opts)."""

    def apply(o: Options) -> None:
        o.logger = logger

    return apply


def with_timer_provider(provider) -> Option:
    """Inject a timer provider (tests pass FakeTimerProvider,
    the reference's mock timer mechanism)."""

    def apply(o: Options) -> None:
        o.timer_provider = provider

    return apply


def resolve(
    replica_id: int,
    opts: Optional[List[Option]],
    materialize_logger: bool = True,
) -> Options:
    """Apply ``opts`` and (unless the caller already has a logger)
    materialize one — skipping materialization avoids side effects on the
    registry-global logger and stray open file handles."""
    o = Options()
    for opt in opts or ():
        opt(o)
    if o.logger is None and materialize_logger:
        logger = logging.getLogger(f"minbft.replica{replica_id}")
        logger.setLevel(o.log_level)
        # Attach exactly one handler owned by these options (repeat
        # construction in one process must not stack handlers).
        fmt = logging.Formatter(
            f"%(asctime)s [replica {replica_id}] %(levelname)s %(message)s"
        )
        for h in list(logger.handlers):
            if getattr(h, "_minbft_owned", False):
                logger.removeHandler(h)
                h.close()
        if o.log_file is not None:
            handler: logging.Handler = logging.FileHandler(o.log_file)
        else:
            handler = logging.StreamHandler(o.log_stream or sys.stderr)
        handler.setFormatter(fmt)
        handler._minbft_owned = True  # type: ignore[attr-defined]
        logger.addHandler(handler)
        logger.propagate = False
        o.logger = logger
    return o
