"""Replica assembly (reference core/replica.go:50-104).

``new_replica`` validates n >= 2f+1, builds the message log and per-peer
unicast logs, wires the handler graph, and returns an :class:`api.Replica`
whose ``start`` opens peer connections and launches the own-message loop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from .. import api
from . import message_handling
from .internal.clientstate import ClientStates
from .internal.messagelog import MessageLog
from .internal.timer import TimerProvider
from .utils import make_logger


class Stack(api.Authenticator, api.ReplicaConnector, api.RequestConsumer):
    """The external-modules union the core consumes
    (reference core/replica.go:37-41)."""


class _Replica(api.Replica):
    def __init__(
        self,
        replica_id: int,
        configer: api.Configer,
        authenticator: api.Authenticator,
        connector: api.ReplicaConnector,
        consumer: api.RequestConsumer,
        timer_provider: Optional[TimerProvider] = None,
        logger: Optional[logging.Logger] = None,
        group: Optional[int] = None,
        state_dir: Optional[str] = None,
    ):
        n, f = configer.n, configer.f
        if n < 2 * f + 1:
            # reference core/replica.go:54-56
            raise ValueError(f"n must be at least 2f+1 (n={n}, f={f})")
        if not 0 <= replica_id < n:
            raise ValueError(f"replica id {replica_id} out of range for n={n}")
        self.id = replica_id
        self.n = n
        self.f = f
        self.group = group
        self._connector = connector
        self._done = asyncio.Event()
        self._tasks: list = []
        self._lag_sampler = None

        message_log = MessageLog()
        unicast_logs: Dict[int, MessageLog] = {
            p: MessageLog() for p in range(n) if p != replica_id
        }
        client_states = ClientStates(timer_provider)
        # Durable crash recovery (minbft_tpu.recovery): a state dir gets
        # this replica a durable checkpoint store plus the recovery
        # telemetry manager; without one both stay off (recovery=None).
        recovery = None
        if state_dir:
            from ..recovery import DurableStore, RecoveryManager, store_path

            recovery = RecoveryManager(
                DurableStore(
                    store_path(state_dir, replica_id, group=group), replica_id
                ),
                group=group,
            )
        self.recovery = recovery
        self.handlers = message_handling.Handlers(
            replica_id,
            n,
            f,
            configer,
            authenticator,
            consumer,
            message_log,
            unicast_logs,
            client_states,
            logger or make_logger(replica_id),
            group=group,
            recovery=recovery,
        )

    @property
    def metrics(self):
        """Protocol counters + latency (minbft_tpu.utils.metrics)."""
        return self.handlers.metrics

    @property
    def trace(self):
        """Flight recorder (minbft_tpu.obs.trace), or None when off."""
        return self.handlers.trace

    def peer_message_stream_handler(self) -> api.MessageStreamHandler:
        return message_handling.PeerStreamHandler(self.handlers)

    def client_message_stream_handler(self) -> api.MessageStreamHandler:
        return message_handling.ClientStreamHandler(self.handlers)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        # Crash-consistent restore BEFORE any connection or replay: peers
        # must see the restored position in our HELLOs and LOG-BASE
        # handling, and the own-log replay must start from installed
        # state.  A corrupted store raises CorruptStoreError out of here
        # — deliberately fatal, never a silent fresh start.
        await self.handlers.restore_from_store()
        self._tasks.append(
            loop.create_task(
                message_handling.run_own_message_loop(self.handlers, self._done)
            )
        )
        for peer in range(self.n):
            if peer == self.id:
                continue
            sh = self._connector.replica_message_stream_handler(peer)
            if sh is None:
                raise ValueError(f"no connection for peer {peer}")
            self._tasks.append(
                loop.create_task(
                    message_handling.run_peer_connection(
                        self.handlers, peer, sh, self._done
                    )
                )
            )
        # Event-loop lag sampler (obs/looplag.py): scheduled-vs-actual
        # wakeup delta into metrics.loop_lag — GIL/loop saturation as a
        # scrapeable histogram and a trace-dump extra.
        from ..obs.looplag import maybe_sampler

        self._lag_sampler = maybe_sampler(self.handlers.metrics.loop_lag)
        if self._lag_sampler is not None:
            self._lag_sampler.start()
        # Crash forensics: a protocol task dying with an exception must
        # not take the flight-recorder trace with it — the dump fires on
        # the fatal error, not only on a clean stop() (a crashed soak
        # otherwise loses exactly the trace that explains it).
        for t in self._tasks:
            t.add_done_callback(self._on_task_done)

    def trace_dump_extra(self) -> dict:
        """Cluster-merge context carried in this replica's trace dump:
        n/f (the critpath quorum rank) and the sampled loop-lag
        histogram (the critpath loop_lag segment)."""
        extra = {
            "n": self.n,
            "f": self.f,
            "loop_lag": self.handlers.metrics.loop_lag.to_dict(),
        }
        if self.group is not None:
            extra["group"] = self.group
        return extra

    def dump_trace(self, base=None):
        """Write this replica's flight-recorder dump (None when tracing
        is off or no dump base is configured)."""
        if self.handlers.trace is None:
            return None
        from ..obs import trace as obs_trace

        return obs_trace.dump_recorder(
            self.handlers.trace, base=base, extra=self.trace_dump_extra()
        )

    def _on_task_done(self, task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is None:
            return
        self.handlers.log.error(
            "replica %d task %s died: %r", self.id, task.get_name(), exc
        )
        try:
            self.dump_trace()
        except OSError:  # dump target gone — the crash itself still logs
            pass

    async def stop(self) -> None:
        self._done.set()
        if self._lag_sampler is not None:
            self._lag_sampler.stop()
            self._lag_sampler = None
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        # JSON trace dump on shutdown (no-op unless MINBFT_TRACE_DUMP is
        # set): one file per replica, bench.py ingests them.  A crash
        # dump may already exist — this overwrites it with the complete
        # ring (same path, fuller data).
        self.dump_trace()


def new_replica(
    replica_id: int,
    configer: api.Configer,
    authenticator: api.Authenticator,
    connector: api.ReplicaConnector,
    consumer: api.RequestConsumer,
    timer_provider: Optional[TimerProvider] = None,
    logger: Optional[logging.Logger] = None,
    opts=None,
    group: Optional[int] = None,
    state_dir: Optional[str] = None,
) -> api.Replica:
    """Create a replica (reference minbft.New, core/replica.go:50).

    ``opts`` takes functional options from :mod:`minbft_tpu.core.options`
    (reference core/options.go); the explicit ``timer_provider``/``logger``
    keywords remain as shortcuts and win over options."""
    if opts:
        from . import options as options_mod

        resolved = options_mod.resolve(
            replica_id, opts, materialize_logger=logger is None
        )
        timer_provider = timer_provider or resolved.timer_provider
        logger = logger or resolved.logger
    return _Replica(
        replica_id, configer, authenticator, connector, consumer,
        timer_provider, logger, group=group, state_dir=state_dir,
    )
