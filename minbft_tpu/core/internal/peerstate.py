"""Per-peer UI-counter capture: the protocol's sequencing backbone.

Reference core/internal/peerstate/peerstate.go:63-109: each peer's certified
messages must be processed **exactly once, in counter order**.  ``capture_ui``
returns False for an already-captured (replayed) counter value; if the
counter is ahead of the next expected value, it *waits* until the gap closes
(the reference blocks on a condvar).  ``release_ui`` is not needed —
capture itself advances the sequence exactly as the reference's
combined capture does when processing is strictly ordered; we keep the
two-phase capture/release shape anyway so a failed processing attempt can
retreat (reference returns a release closure).

Batching interplay: *verification* of a UI happens **before** capture
(stateless, batched on TPU); capture/processing stays sequential per peer.
This is the ordering-vs-batching resolution from SURVEY.md §7.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional


class PeerState:
    def __init__(self):
        self._next_cv = 1  # USIG counters start at 1
        self._cond = asyncio.Condition()
        # cv -> monotonic time its capture started gap-parking.  A
        # parked capture means a LOWER counter never arrived — on a
        # faithful transport that only happens around a connection drop
        # (healed by the redial's HELLO replay), but a lossy/partitioned
        # link can drop a certified frame while the stream stays up, and
        # the gap then wedges every later message from this peer FOREVER
        # (the chaos soak's view-change livelock).  The dial loop
        # (message_handling.run_peer_connection) watches gap_stalled_for
        # and forces a redial when a gap persists with no progress.
        self._parked: Dict[int, float] = {}
        # Monotonic time the capture sequence last ADVANCED (a capture
        # applied or a LOG-BASE fast-forward landed).  gap_stalled_for
        # measures parked time from here, not from when the oldest
        # capture first parked: a redial's log replay heals a gap by
        # capturing hundreds of counters in order, and judging the new
        # stream by the OLD park timestamp would tear it down mid-replay
        # — before the replay reaches the gap — forever (a redial storm
        # the chaos soak hit live).
        self._last_advance = time.monotonic()

    async def capture_ui(self, cv: int) -> bool:
        """True once ``cv`` is ours to process (in order); False if ``cv``
        was already captured (duplicate/replayed message)."""
        async with self._cond:
            if cv > self._next_cv:
                self._parked.setdefault(cv, time.monotonic())
                try:
                    while cv > self._next_cv:
                        await self._cond.wait()
                finally:
                    self._parked.pop(cv, None)
            if cv < self._next_cv:
                return False
            self._next_cv += 1
            self._last_advance = time.monotonic()
            self._cond.notify_all()
            return True

    def next_expected(self) -> int:
        """The next UI counter this peer state will capture — everything
        below it is already captured and applied.  Stamped into the
        dialer's HELLO as ``resume_counter`` so a redial's log replay
        skips the captured prefix (plain read: all protocol code runs on
        one loop, and a stale-low read only costs extra replay)."""
        return self._next_cv

    def gap_stalled_for(self, now: Optional[float] = None) -> float:
        """Seconds a capture gap has been parked with NO capture progress
        at all — 0.0 while nothing is parked OR while captures keep
        applying (a replay is actively healing the gap).  The redial
        watchdog keys on this, not on raw parked time (see
        ``_last_advance``)."""
        if not self._parked:
            return 0.0
        if now is None:
            now = time.monotonic()
        return max(0.0, now - max(min(self._parked.values()), self._last_advance))

    async def retreat_ui(self, cv: int) -> None:
        """Undo a capture after failed processing (rare; keeps the
        exactly-once promise intact for a retry)."""
        async with self._cond:
            if cv == self._next_cv - 1:
                self._next_cv = cv
            self._cond.notify_all()

    async def fast_forward(self, next_cv: int) -> None:
        """Jump the capture sequence ahead to ``next_cv`` (never back):
        the peer announced a checkpoint-certified LOG-BASE, so counters
        below it are intentionally absent from its log — waiting for them
        would wedge forever.  Wakes gap-parked captures (their counters
        become replays or ready, per the new base)."""
        async with self._cond:
            if next_cv > self._next_cv:
                self._next_cv = next_cv
                self._last_advance = time.monotonic()
            self._cond.notify_all()


class PeerStates:
    """Lazily-populated per-peer map (reference peerstate.go Provider)."""

    def __init__(self):
        self._peers: Dict[int, PeerState] = {}

    def peer(self, replica_id: int) -> PeerState:
        st = self._peers.get(replica_id)
        if st is None:
            st = PeerState()
            self._peers[replica_id] = st
        return st
