"""Per-peer UI-counter capture: the protocol's sequencing backbone.

Reference core/internal/peerstate/peerstate.go:63-109: each peer's certified
messages must be processed **exactly once, in counter order**.  ``capture_ui``
returns False for an already-captured (replayed) counter value; if the
counter is ahead of the next expected value, it *waits* until the gap closes
(the reference blocks on a condvar).  ``release_ui`` is not needed —
capture itself advances the sequence exactly as the reference's
combined capture does when processing is strictly ordered; we keep the
two-phase capture/release shape anyway so a failed processing attempt can
retreat (reference returns a release closure).

Batching interplay: *verification* of a UI happens **before** capture
(stateless, batched on TPU); capture/processing stays sequential per peer.
This is the ordering-vs-batching resolution from SURVEY.md §7.
"""

from __future__ import annotations

import asyncio
from typing import Dict


class PeerState:
    def __init__(self):
        self._next_cv = 1  # USIG counters start at 1
        self._cond = asyncio.Condition()

    async def capture_ui(self, cv: int) -> bool:
        """True once ``cv`` is ours to process (in order); False if ``cv``
        was already captured (duplicate/replayed message)."""
        async with self._cond:
            while cv > self._next_cv:
                await self._cond.wait()
            if cv < self._next_cv:
                return False
            self._next_cv += 1
            self._cond.notify_all()
            return True

    async def retreat_ui(self, cv: int) -> None:
        """Undo a capture after failed processing (rare; keeps the
        exactly-once promise intact for a retry)."""
        async with self._cond:
            if cv == self._next_cv - 1:
                self._next_cv = cv
            self._cond.notify_all()

    async def fast_forward(self, next_cv: int) -> None:
        """Jump the capture sequence ahead to ``next_cv`` (never back):
        the peer announced a checkpoint-certified LOG-BASE, so counters
        below it are intentionally absent from its log — waiting for them
        would wedge forever.  Wakes gap-parked captures (their counters
        become replays or ready, per the new base)."""
        async with self._cond:
            if next_cv > self._next_cv:
                self._next_cv = next_cv
            self._cond.notify_all()


class PeerStates:
    """Lazily-populated per-peer map (reference peerstate.go Provider)."""

    def __init__(self):
        self._peers: Dict[int, PeerState] = {}

    def peer(self, replica_id: int) -> PeerState:
        st = self._peers.get(replica_id)
        if st is None:
            st = PeerState()
            self._peers[replica_id] = st
        return st
