"""Pending-request set (reference core/internal/requestlist/
request-list.go:36-80).

The reference keeps ONE slot per client — sound for its strictly serial
clients, where a new request genuinely supersedes the previous one.
This build's clients pipeline: with a single slot, each captured request
OVERWRITES the previous still-in-flight one, so a view change re-applies
only the newest pending request per client and the rest silently starve
(the chaos soak wedged on this — 1 of 6 pipelined requests survived the
transition).  The set therefore tracks every in-flight (client, seq),
bounded per client by ``_PER_CLIENT`` (evicting the oldest — the
reference's overwrite semantic, widened from depth 1 to any sane
pipeline depth)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List


class RequestList:
    _PER_CLIENT = 128  # >= any sane client pipeline depth

    def __init__(self):
        self._by_client: Dict[int, "OrderedDict[int, object]"] = {}

    def add(self, request) -> None:
        d = self._by_client.setdefault(request.client_id, OrderedDict())
        d[request.seq] = request
        d.move_to_end(request.seq)
        while len(d) > self._PER_CLIENT:
            d.popitem(last=False)

    def remove(self, request) -> bool:
        d = self._by_client.get(request.client_id)
        if d is not None and request.seq in d:
            del d[request.seq]
            if not d:
                del self._by_client[request.client_id]
            return True
        return False

    def all(self) -> List[object]:
        return [r for d in self._by_client.values() for r in d.values()]

    def __len__(self) -> int:
        return sum(len(d) for d in self._by_client.values())
