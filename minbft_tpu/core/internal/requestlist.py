"""Pending-request set, one slot per client (reference
core/internal/requestlist/request-list.go:36-80)."""

from __future__ import annotations

from typing import Dict, List


class RequestList:
    def __init__(self):
        self._by_client: Dict[int, object] = {}

    def add(self, request) -> None:
        self._by_client[request.client_id] = request

    def remove(self, request) -> bool:
        cur = self._by_client.get(request.client_id)
        if cur is not None and cur.seq == request.seq:
            del self._by_client[request.client_id]
            return True
        return False

    def all(self) -> List[object]:
        return list(self._by_client.values())

    def __len__(self) -> int:
        return len(self._by_client)
