"""View number state (reference core/internal/viewstate/view-state.go:50-105).

Tracks the current and expected view under an async RW discipline:
``hold_view_lease`` is the read-lease held across view-sensitive
processing (the reference takes a read lock and returns a release
closure, view-state.go:50-74) — message processing that suspends between
the view check and apply cannot be overtaken by a view advancement;
``advance_current_view`` takes the write side and waits out active
leases.  View change processing itself is a stub in the reference
(core/message-handling.go:419 "Not implemented"), so only the
demand/advance edges are exercised here too.
"""

from __future__ import annotations

import asyncio
from contextlib import asynccontextmanager
from typing import Tuple


class ViewState:
    def __init__(self):
        self._current = 0
        self._expected = 0
        self._lock = asyncio.Lock()
        self._readers = 0
        self._no_readers = asyncio.Event()
        self._no_readers.set()

    async def hold_view(self) -> Tuple[int, int]:
        """-> (current_view, expected_view) snapshot (no lease).  For
        view-sensitive *processing*, use :meth:`hold_view_lease` — a
        snapshot can go stale across an await."""
        async with self._lock:
            return self._current, self._expected

    @asynccontextmanager
    async def hold_view_lease(self):
        """Read-lease: yields (current, expected); the current view cannot
        advance until every active lease is released (reference HoldView's
        RLock, view-state.go:50-74).  Leases are shared — concurrent
        message processing proceeds in parallel."""
        async with self._lock:  # writers hold _lock while draining readers,
            self._readers += 1  # which blocks new leases (writer priority)
            self._no_readers.clear()
            cur, exp = self._current, self._expected
        try:
            yield cur, exp
        finally:
            self._readers -= 1
            if self._readers == 0:
                self._no_readers.set()

    async def advance_expected_view(self, view: int) -> bool:
        """Demand a view change to ``view``; False if not ahead
        (reference view-state.go:74-88)."""
        async with self._lock:
            if view <= self._expected:
                return False
            self._expected = view
            return True

    async def advance_current_view(self, view: int) -> bool:
        """Enter ``view`` (completes a view change; reference
        view-state.go:90-105).  Waits for in-flight read leases, so a
        message mid-apply in the old view finishes before the view moves."""
        async with self._lock:
            while self._readers:
                await self._no_readers.wait()
            if view <= self._current or view > self._expected:
                return False
            self._current = view
            return True
