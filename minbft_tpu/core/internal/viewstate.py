"""View number state (reference core/internal/viewstate/view-state.go:50-105).

Tracks the current and expected view under an async RW discipline:
``hold_view_lease`` is the read-lease held across view-sensitive
processing (the reference takes a read lock and returns a release
closure, view-state.go:50-74) — message processing that suspends between
the view check and apply cannot be overtaken by a view advancement;
``advance_current_view`` takes the write side and waits out active
leases.  The reference never advances the current view (its view-change
processing is a stub, core/message-handling.go:419); here the full
view-change protocol (core/viewchange.py) drives every edge, including
``wait_current_at_least`` for messages from views still being entered.
"""

from __future__ import annotations

import asyncio
from typing import Tuple


class _Lease:
    """Shared read-lease handle (``async with view_state.hold_view_lease()``).

    The hot path — no view change draining — takes and releases the lease
    with plain counter arithmetic, no locks and no context-manager
    machinery: on the single-threaded event loop nothing can interleave
    between the writer-gate check and the counter increment.  Only while a
    writer is draining does entry await the gate (writer priority: new
    leases queue behind a pending advance)."""

    __slots__ = ("_vs",)

    def __init__(self, vs: "ViewState"):
        self._vs = vs

    async def __aenter__(self) -> Tuple[int, int]:
        vs = self._vs
        while vs._writer_waiting:
            await vs._write_gate.wait()
        vs._readers += 1
        return vs._current, vs._expected

    async def __aexit__(self, *exc) -> bool:
        vs = self._vs
        vs._readers -= 1
        if vs._readers == 0 and vs._writer_waiting:
            vs._no_readers.set()
        return False


class ViewState:
    def __init__(self):
        self._current = 0
        self._expected = 0
        self._write_lock = asyncio.Lock()  # serializes writers only
        self._readers = 0
        self._writer_waiting = False
        self._no_readers = asyncio.Event()
        self._write_gate = asyncio.Event()
        self._write_gate.set()
        self._advanced = asyncio.Event()  # swapped on every current-advance

    async def wait_current_at_least(self, view: int) -> None:
        """Park until the current view reaches ``view`` — how processing of
        a message from a *future* view waits for the local view transition
        to catch up instead of dropping it (the reference errors such
        messages out, core/message-handling.go "unexpected view")."""
        while True:
            ev = self._advanced  # capture BEFORE the check: an advance
            if self._current >= view:  # between check and wait() sets the
                return  # captured event, so the wakeup cannot be missed
            await ev.wait()

    @property
    def current(self) -> int:
        """Synchronous current-view read (for non-suspending call sites
        like the checkpoint emitter's primary check)."""
        return self._current

    async def hold_view(self) -> Tuple[int, int]:
        """-> (current_view, expected_view) snapshot (no lease).  For
        view-sensitive *processing*, use :meth:`hold_view_lease` — a
        snapshot can go stale across an await."""
        return self._current, self._expected

    def hold_view_lease(self) -> _Lease:
        """Read-lease: yields (current, expected); the current view cannot
        advance until every active lease is released (reference HoldView's
        RLock, view-state.go:50-74).  Leases are shared — concurrent
        message processing proceeds in parallel."""
        return _Lease(self)

    async def advance_expected_view(self, view: int) -> bool:
        """Demand a view change to ``view``; False if not ahead
        (reference view-state.go:74-88)."""
        if view <= self._expected:
            return False
        self._expected = view
        return True

    async def advance_current_view(self, view: int) -> bool:
        """Enter ``view`` (completes a view change; reference
        view-state.go:90-105).  Waits for in-flight read leases, so a
        message mid-apply in the old view finishes before the view moves;
        new leases queue behind the drain on the write gate."""
        async with self._write_lock:
            self._writer_waiting = True
            self._write_gate.clear()
            try:
                while self._readers:
                    self._no_readers.clear()
                    await self._no_readers.wait()
                if view <= self._current or view > self._expected:
                    return False
                self._current = view
                ev, self._advanced = self._advanced, asyncio.Event()
                ev.set()
                return True
            finally:
                self._writer_waiting = False
                self._write_gate.set()
