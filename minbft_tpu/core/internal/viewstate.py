"""View number state (reference core/internal/viewstate/view-state.go:50-105).

Tracks the current and expected view under an async RW-style discipline:
``hold_view`` is the read-lease used by message processing (the reference
takes a read lock and returns a release closure), ``advance_expected_view``
/ ``advance_current_view`` move the view-change machinery forward.  View
change processing itself is a stub in the reference (core/message-
handling.go:419 "Not implemented"), so only the demand/advance edges are
exercised here too.
"""

from __future__ import annotations

import asyncio
from typing import Tuple


class ViewState:
    def __init__(self):
        self._current = 0
        self._expected = 0
        self._lock = asyncio.Lock()

    async def hold_view(self) -> Tuple[int, int]:
        """-> (current_view, expected_view) snapshot.

        The asyncio engine processes view-sensitive steps on one loop, so a
        snapshot (not a held lock) is sufficient; mutators are serialized
        with the internal lock."""
        async with self._lock:
            return self._current, self._expected

    async def advance_expected_view(self, view: int) -> bool:
        """Demand a view change to ``view``; False if not ahead
        (reference view-state.go:74-88)."""
        async with self._lock:
            if view <= self._expected:
                return False
            self._expected = view
            return True

    async def advance_current_view(self, view: int) -> bool:
        """Enter ``view`` (completes a view change; reference
        view-state.go:90-105)."""
        async with self._lock:
            if view <= self._current or view > self._expected:
                return False
            self._current = view
            return True
