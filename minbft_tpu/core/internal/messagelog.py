"""Append-only message log with multi-subscriber replay streams and
checkpoint truncation.

Reference core/internal/messagelog/messagelog.go:40-109: ``append`` never
blocks; each ``stream()`` first replays everything logged so far, then
follows new appends until the ``done`` event is set (or the consuming task
is cancelled).  Used for the broadcast log (every certified own-message)
and the per-peer unicast logs; the HELLO handshake streams these logs to a
connecting peer (reference core/message-handling.go:316-350).

Beyond the reference (whose log grows forever — GC is its top roadmap
item, README.md:492-493), the log supports **checkpoint truncation**:

- :meth:`truncate` drops a prefix and installs a head entry (the LOG-BASE
  announcement carrying the checkpoint certificate) in its place.
  Positions are absolute, so live subscribers past the cut are
  unaffected; a subscriber still inside the dropped prefix resumes at the
  head entry — it sees the LOG-BASE *before* the retained suffix and can
  fast-forward its per-peer capture instead of wedging on the counter
  gap.
- :meth:`replace` swaps a retained entry for its checkpoint-covered stub
  (same authen bytes, payload dropped — messages.Prepare.requests_digest)
  so retained history costs O(1) per counter instead of O(batch).

Wake-ups are synchronous event sets on append (all protocol code runs on
one loop — the asyncio analogue of the reference's per-replica goroutine
ownership); idle streams park on an Event instead of polling.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, List, Optional


class MessageLog:
    def __init__(self):
        self._entries: List[object] = []
        self._seq0 = 0  # absolute position of _entries[0]
        self._waiters: List[asyncio.Event] = []

    def append(self, msg) -> None:
        """Non-blocking append (reference messagelog.go:60-72)."""
        self._entries.append(msg)
        waiters, self._waiters = self._waiters, []
        for ev in waiters:
            ev.set()

    def snapshot(self) -> List[object]:
        return list(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    def truncate(self, n_drop: int, head: Optional[object] = None) -> None:
        """Drop the first ``n_drop`` entries; if ``head`` is given, place
        it where the dropped prefix was.  A subscriber whose position lies
        inside the dropped range resumes at ``head`` (then the suffix);
        one already past the range sees nothing."""
        if n_drop <= 0 and head is None:
            return
        n_drop = min(max(n_drop, 0), len(self._entries))
        suffix = self._entries[n_drop:]
        self._seq0 += n_drop
        if head is not None:
            # The head occupies the last dropped slot, so lagging
            # subscribers (position <= _seq0) receive it first while
            # up-to-date ones skip it.
            self._seq0 -= 1
            self._entries = [head] + suffix
        else:
            self._entries = suffix

    def replace(self, index: int, entry: object) -> None:
        """Swap the entry at list position ``index`` (into the current
        ``snapshot()``) for ``entry`` — used to stub checkpoint-covered
        history.  Subscribers already past it saw the original; later
        replays see the stub."""
        self._entries[index] = entry

    async def stream(
        self, done: Optional[asyncio.Event] = None
    ) -> AsyncIterator[object]:
        """Replay all entries, then follow new ones (reference
        messagelog.go:74-109).  Terminates when ``done`` is set."""
        idx = self._seq0
        while True:
            while True:
                # Re-check the base every iteration: a yield suspends the
                # stream, and a truncate may land before it resumes.
                if idx < self._seq0:
                    idx = self._seq0  # truncated past us: resume at head
                if idx - self._seq0 >= len(self._entries):
                    break
                yield self._entries[idx - self._seq0]
                idx += 1
            if done is not None and done.is_set():
                return
            ev = asyncio.Event()
            # Atomic loop-side registration; the re-check below (and the
            # drain-either-way continue) absorbs an append/truncate racing
            # this suspension point.
            self._waiters.append(ev)  # noqa: LD001
            if idx - self._seq0 < len(self._entries) or idx < self._seq0:
                # An append/truncate raced our registration; the event may
                # stay set or unset — loop and drain either way.
                continue
            if done is None:
                await ev.wait()
            else:
                ev_task = asyncio.ensure_future(ev.wait())
                done_task = asyncio.ensure_future(done.wait())
                try:
                    await asyncio.wait(
                        [ev_task, done_task],
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    ev_task.cancel()
                    done_task.cancel()
