"""Append-only message log with multi-subscriber replay streams.

Reference core/internal/messagelog/messagelog.go:40-109: ``append`` never
blocks; each ``stream()`` first replays everything logged so far, then
follows new appends until the ``done`` event is set (or the consuming task
is cancelled).  Used for the broadcast log (every certified own-message)
and the per-peer unicast logs; the HELLO handshake streams these logs to a
connecting peer (reference core/message-handling.go:316-350).

Wake-ups are synchronous event sets on append (all protocol code runs on
one loop — the asyncio analogue of the reference's per-replica goroutine
ownership); idle streams park on an Event instead of polling.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, List, Optional


class MessageLog:
    def __init__(self):
        self._entries: List[object] = []
        self._waiters: List[asyncio.Event] = []

    def append(self, msg) -> None:
        """Non-blocking append (reference messagelog.go:60-72)."""
        self._entries.append(msg)
        waiters, self._waiters = self._waiters, []
        for ev in waiters:
            ev.set()

    def snapshot(self) -> List[object]:
        return list(self._entries)

    async def stream(
        self, done: Optional[asyncio.Event] = None
    ) -> AsyncIterator[object]:
        """Replay all entries, then follow new ones (reference
        messagelog.go:74-109).  Terminates when ``done`` is set."""
        idx = 0
        while True:
            while idx < len(self._entries):
                yield self._entries[idx]
                idx += 1
            if done is not None and done.is_set():
                return
            ev = asyncio.Event()
            self._waiters.append(ev)
            if idx < len(self._entries):
                # An append raced our registration; the event may stay set
                # or unset — loop and drain either way.
                continue
            if done is None:
                await ev.wait()
            else:
                ev_task = asyncio.ensure_future(ev.wait())
                done_task = asyncio.ensure_future(done.wait())
                try:
                    await asyncio.wait(
                        [ev_task, done_task],
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    ev_task.cancel()
                    done_task.cancel()
