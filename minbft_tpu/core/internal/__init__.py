"""Internal state machines of the core protocol engine (reference
core/internal/): per-client request/reply state, per-peer UI sequencing,
view state, the replayable message log, the pending-request list, and an
injectable timer abstraction."""
