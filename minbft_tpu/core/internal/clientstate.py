"""Per-client state: request-seq lifecycle, reply buffer, timers.

Reference core/internal/clientstate/: three sub-machines per client —

- request-seq lifecycle captured→released→prepared→retired with a blocking
  capture (reference request-seq.go:47-112): this is the per-client
  pipelining/dedup gate — one request in flight per client, strictly
  increasing sequence numbers, parallel across clients;
- reply buffer with per-seq subscription (reference reply.go:41-90);
- restartable single-slot request/prepare timers (reference timeout.go:40-71),
  injectable for tests (reference timer mock).
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from typing import Callable, Dict, Optional

from .timer import TimerProvider, StandardTimerProvider


class ClientState:
    """All containers here are O(1) per client — a long-lived replica's
    memory must not grow with the number of requests served (the
    reference keeps a single last-reply slot, reply.go:25-60, and scalar
    seq watermarks, request-seq.go:28-45)."""

    # Out-of-order tolerance window: completed-but-unretired seqs above
    # the retire watermark are remembered individually so a LOWER seq
    # arriving late is not mistaken for a duplicate.  Bounded at roughly
    # any sane client pipeline depth; beyond it, oldest entries fall out
    # (dedup degrades to the watermark for ancient seqs — the reply
    # window's philosophy).
    _DONE_WINDOW = 1024

    def __init__(self, timer_provider: TimerProvider):
        self._timers = timer_provider
        # Request-seq state machine.  The reference keeps scalar
        # captured/released watermarks (request-seq.go:28-45) — sound
        # there because its client is strictly serial (requestbuffer's
        # single slot), so seqs ARRIVE in order.  This build's clients
        # pipeline many requests, and concurrent per-message tasks mean a
        # higher seq can reach capture first; a scalar watermark would
        # then silently DROP the lower seq as a "duplicate" — never
        # proposed, and later retired past by the watermark jump (a
        # liveness hole observed live at ~1 in 10 flagship bench runs).
        # Capture instead tracks the single ACTIVE seq plus a bounded set
        # of completed seqs above the retire watermark.
        self._last_captured = 0  # max captured (diagnostic watermark)
        self._active = 0  # captured, not yet released (0 = none)
        self._done: set = set()  # released seqs > _retired
        # Everything at or below this floor is treated as a duplicate:
        # when the done-set overflows, evicted seqs RAISE the floor
        # instead of silently losing their dedup (a dropped dedup would
        # let a retransmit re-execute an already-processed request —
        # safety; a floor refusing a very late lower seq costs only
        # liveness, and only beyond a 1024-deep reorder).
        self._done_floor = 0
        self._last_prepared = 0
        self._retired = 0
        self._cond = asyncio.Condition()
        # Reply buffer: a bounded WINDOW of recent replies.  The reference
        # keeps exactly one last-reply slot (reply.go:25-38) — sound there
        # because its clients are strictly serial (requestbuffer's
        # single-capacity slot).  This build's clients pipeline up to
        # max_inflight requests, so replies k and k+1 can both land before
        # the waiter for k wakes; a single slot would skip k and strand
        # the client.  The window (insertion = execution = seq order)
        # bounds memory at O(_REPLY_WINDOW) per client while covering any
        # sane pipeline depth; the event is swapped on each add so waiters
        # from any earlier add are woken exactly once.
        self._last_replied_seq = 0
        self._replies: "OrderedDict[int, object]" = OrderedDict()
        self._reply_event = asyncio.Event()
        # timers (reference timeout.go)
        self._request_timer = None
        self._prepare_timer = None

    # -- request sequence lifecycle -----------------------------------------

    def _is_dup(self, seq: int) -> bool:
        return (
            seq <= self._retired
            or seq <= self._done_floor
            or seq == self._active
            or seq in self._done
        )

    async def capture_request_seq(self, seq: int) -> bool:
        """Capture ``seq`` for processing.

        Returns False if ``seq`` was already captured/retired (duplicate).
        Blocks while a DIFFERENT capture is unreleased (the per-client
        serialization of reference request-seq.go:47-82).  Out-of-order
        arrivals are fine: a lower seq arriving after a higher one still
        captures (see the constructor note)."""
        # Duplicate fast path: on the single-threaded event loop nothing
        # changes between this check and the return — the condvar is only
        # needed to *capture*.  (Duplicates dominate: every peer message
        # re-offers its embedded requests.)
        if self._is_dup(seq):
            return False
        async with self._cond:
            while True:
                if self._is_dup(seq):
                    return False
                if self._active == 0:
                    self._active = seq
                    if seq > self._last_captured:
                        self._last_captured = seq
                    return True
                await self._cond.wait()

    async def release_request_seq(self, seq: int) -> None:
        """Finish processing a captured seq (reference request-seq.go:84-97)."""
        async with self._cond:
            if seq != self._active:
                raise ValueError("release of non-captured request seq")
            self._active = 0
            if seq > self._retired:
                self._done.add(seq)
                if len(self._done) > self._DONE_WINDOW:
                    evicted = min(self._done)
                    self._done.discard(evicted)
                    if evicted > self._done_floor:
                        self._done_floor = evicted
            self._cond.notify_all()

    def prepare_request_seq(self, seq: int) -> None:
        """Mark ``seq`` prepared (reference request-seq.go:99-106).
        NOTE: with the out-of-order capture model, MANY seqs can sit
        between prepared and retired, so this scalar watermark cannot
        enumerate prepared-but-unexecuted requests — anything built on it
        (e.g. a view-change retransmission of prepared requests) must use
        the pending request list, not this field.  Nothing reads it yet;
        kept for reference parity."""
        if seq > self._last_prepared:
            self._last_prepared = seq

    @property
    def last_prepared_seq(self) -> int:
        return self._last_prepared

    def retire_request_seq(self, seq: int) -> bool:
        """Mark ``seq`` executed; returns False if already retired
        (reference request-seq.go:108-112).  The watermark-jump semantics
        are preserved — the collector executes in a deterministic global
        order, so seqs below an executed one are genuinely superseded —
        and completed seqs at or below the new watermark leave the done
        set (memory stays O(pipeline depth))."""
        if seq <= self._retired:
            return False
        self._retired = seq
        if self._done:
            self._done = {s for s in self._done if s > seq}
        return True

    @property
    def last_captured_seq(self) -> int:
        return self._last_captured

    @property
    def retired_seq(self) -> int:
        return self._retired

    def install_retired_seq(self, seq: int) -> None:
        """State transfer: adopt a certified retire watermark.  The other
        lifecycle watermarks advance to match so a re-offered old request
        dedups instead of re-capturing."""
        if seq <= self._retired:
            return
        self._retired = seq
        if self._done:
            self._done = {s for s in self._done if s > seq}
        if self._last_captured < seq:
            self._last_captured = seq
        if self._last_prepared < seq:
            self._last_prepared = seq

    # -- reply buffer --------------------------------------------------------

    _REPLY_WINDOW = 128  # >= any client pipeline depth; O(1) per client

    def add_reply(self, seq: int, reply) -> None:
        """Store the reply in the bounded window and wake subscribers
        (reference reply.go:41-60, generalized for pipelined clients —
        see the constructor comment)."""
        if seq <= self._last_replied_seq and seq not in self._replies:
            return  # stale (reference AddReply "old request ID")
        self._replies[seq] = reply
        if seq > self._last_replied_seq:
            self._last_replied_seq = seq
        while len(self._replies) > self._REPLY_WINDOW:
            self._replies.popitem(last=False)
        ev, self._reply_event = self._reply_event, asyncio.Event()
        ev.set()

    async def reply_for(self, seq: int) -> Optional[object]:
        """Await the reply for ``seq`` (reference reply.go:62-80
        ReplyChannel): waits until the client's replied watermark reaches
        ``seq``; returns None if ``seq`` was pruned out of the window (a
        stale retry far behind the pipeline — the reference closes the
        channel without sending)."""
        while self._last_replied_seq < seq:
            await self._reply_event.wait()
        return self._replies.get(seq)

    # -- timers --------------------------------------------------------------

    def start_request_timer(self, timeout: float, on_expiry: Callable[[], None]) -> None:
        """(Re)start the single-slot request timer (reference timeout.go:40-56)."""
        self.stop_request_timer()
        if timeout > 0:
            self._request_timer = self._timers.after(timeout, on_expiry)

    def stop_request_timer(self) -> None:
        if self._request_timer is not None:
            self._request_timer.cancel()
            self._request_timer = None

    def start_prepare_timer(self, timeout: float, on_expiry: Callable[[], None]) -> None:
        self.stop_prepare_timer()
        if timeout > 0:
            self._prepare_timer = self._timers.after(timeout, on_expiry)

    def stop_prepare_timer(self) -> None:
        if self._prepare_timer is not None:
            self._prepare_timer.cancel()
            self._prepare_timer = None


class ClientStates:
    """Lazily-populated per-client provider (reference client-state.go:36-55)."""

    def __init__(self, timer_provider: Optional[TimerProvider] = None):
        self._timers = timer_provider or StandardTimerProvider()
        self._clients: Dict[int, ClientState] = {}

    @property
    def timers(self) -> TimerProvider:
        """The injected timer provider (shared with replica-level timers
        like the view-change timer, so fake-timer tests control both)."""
        return self._timers

    def client(self, client_id: int) -> ClientState:
        st = self._clients.get(client_id)
        if st is None:
            st = ClientState(self._timers)
            self._clients[client_id] = st
        return st

    def all(self):
        return self._clients.items()

    def retire_watermarks(self):
        """Deterministic snapshot of per-client retire watermarks (sorted
        (client_id, retired_seq), zero entries omitted) — part of the
        composite checkpoint digest: the retired set is a pure function of
        the executed history, so correct replicas agree on it at every
        batch boundary."""
        return tuple(
            (cid, st.retired_seq)
            for cid, st in sorted(self._clients.items())
            if st.retired_seq > 0
        )

    def install_retire_watermarks(self, marks) -> None:
        """State transfer: adopt certified retire watermarks."""
        for cid, seq in marks:
            self.client(cid).install_retired_seq(seq)
