"""Per-client state: request-seq lifecycle, reply buffer, timers.

Reference core/internal/clientstate/: three sub-machines per client —

- request-seq lifecycle captured→released→prepared→retired with a blocking
  capture (reference request-seq.go:47-112): this is the per-client
  pipelining/dedup gate — one request in flight per client, strictly
  increasing sequence numbers, parallel across clients;
- reply buffer with per-seq subscription (reference reply.go:41-90);
- restartable single-slot request/prepare timers (reference timeout.go:40-71),
  injectable for tests (reference timer mock).
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from typing import Callable, Dict, Optional

from .timer import TimerProvider, StandardTimerProvider


class ClientState:
    """All containers here are O(1) per client — a long-lived replica's
    memory must not grow with the number of requests served (the
    reference keeps a single last-reply slot, reply.go:25-60, and scalar
    seq watermarks, request-seq.go:28-45)."""

    # Out-of-order tolerance window: completed-but-unretired seqs above
    # the retire floor are remembered individually so a LOWER seq
    # arriving late is not mistaken for a duplicate.  Bounded at roughly
    # any sane client pipeline depth; beyond it, oldest entries fall out
    # (dedup degrades to the floor for ancient seqs — the reply
    # window's philosophy).
    _DONE_WINDOW = 1024

    # Executed-seq dedup window (see retire_request_seq): retirement is
    # EXACT per seq, bounded by this window; evicted seqs raise the
    # retire floor (conservative refusal, like the done-floor).
    _RETIRE_WINDOW = 1024

    def __init__(self, timer_provider: TimerProvider):
        self._timers = timer_provider
        # Request-seq state machine.  The reference keeps scalar
        # captured/released watermarks (request-seq.go:28-45) — sound
        # there because its client is strictly serial (requestbuffer's
        # single slot), so seqs ARRIVE in order.  This build's clients
        # pipeline many requests, and concurrent per-message tasks mean a
        # higher seq can reach capture first; a scalar watermark would
        # then silently DROP the lower seq as a "duplicate" — never
        # proposed, and later retired past by the watermark jump (a
        # liveness hole observed live at ~1 in 10 flagship bench runs).
        # Capture instead tracks the single ACTIVE seq plus a bounded set
        # of completed seqs above the retire watermark.
        self._last_captured = 0  # max captured (diagnostic watermark)
        self._active = 0  # captured, not yet released (0 = none)
        self._done: set = set()  # released seqs > _retired
        # Everything at or below this floor is treated as a duplicate:
        # when the done-set overflows, evicted seqs RAISE the floor
        # instead of silently losing their dedup (a dropped dedup would
        # let a retransmit re-execute an already-processed request —
        # safety; a floor refusing a very late lower seq costs only
        # liveness, and only beyond a 1024-deep reorder).
        self._done_floor = 0
        self._last_prepared = 0
        # Executed-seq state: the reference retires by WATERMARK JUMP
        # (executing seq k marks every lower seq of the client retired,
        # request-seq.go:108-112) — sound for its strictly serial
        # clients, where a lower seq after a higher one can only be a
        # stale retry.  This build's clients pipeline: under network
        # reordering a higher seq can commit FIRST, and a jump would
        # silently supersede the still-live lower request — never
        # executed, never replied, the client wedged until timeout (the
        # chaos soak caught this live).  Retirement is therefore exact:
        # a bounded set of executed seqs over a floor raised by eviction.
        self._retire_floor = 0
        self._retired_set: set = set()
        self._cond = asyncio.Condition()
        # Reply buffer: a bounded WINDOW of recent replies.  The reference
        # keeps exactly one last-reply slot (reply.go:25-38) — sound there
        # because its clients are strictly serial (requestbuffer's
        # single-capacity slot).  This build's clients pipeline up to
        # max_inflight requests, so replies k and k+1 can both land before
        # the waiter for k wakes; a single slot would skip k and strand
        # the client.  The window (insertion = execution = seq order)
        # bounds memory at O(_REPLY_WINDOW) per client while covering any
        # sane pipeline depth; the event is swapped on each add so waiters
        # from any earlier add are woken exactly once.
        self._reply_floor = 0  # highest seq pruned out of the window
        self._replies: "OrderedDict[int, object]" = OrderedDict()
        self._reply_event = asyncio.Event()
        # Timers (reference timeout.go) — PER-SEQ, not the reference's
        # single slot per client: pipelined clients keep many requests
        # in flight, and a shared slot means every newly-applied request
        # DISARMS the watchdog guarding the previous one (and executing
        # any request disarms them all) — under faults the unguarded
        # requests then starve with no view-change demand ever fired
        # (the chaos soak wedged on this).  Bounded by requests in
        # flight: entries leave on expiry, stop, or execution.
        self._request_timers: Dict[int, object] = {}
        self._prepare_timers: Dict[int, object] = {}

    # -- request sequence lifecycle -----------------------------------------

    def _is_retired(self, seq: int) -> bool:
        return seq <= self._retire_floor or seq in self._retired_set

    def _is_dup(self, seq: int) -> bool:
        return (
            self._is_retired(seq)
            or seq <= self._done_floor
            or seq == self._active
            or seq in self._done
        )

    async def capture_request_seq(self, seq: int) -> bool:
        """Capture ``seq`` for processing.

        Returns False if ``seq`` was already captured/retired (duplicate).
        Blocks while a DIFFERENT capture is unreleased (the per-client
        serialization of reference request-seq.go:47-82).  Out-of-order
        arrivals are fine: a lower seq arriving after a higher one still
        captures (see the constructor note)."""
        # Duplicate fast path: on the single-threaded event loop nothing
        # changes between this check and the return — the condvar is only
        # needed to *capture*.  (Duplicates dominate: every peer message
        # re-offers its embedded requests.)
        if self._is_dup(seq):
            return False
        async with self._cond:
            while True:
                if self._is_dup(seq):
                    return False
                if self._active == 0:
                    self._active = seq
                    if seq > self._last_captured:
                        self._last_captured = seq
                    return True
                await self._cond.wait()

    async def release_request_seq(self, seq: int) -> None:
        """Finish processing a captured seq (reference request-seq.go:84-97)."""
        async with self._cond:
            if seq != self._active:
                raise ValueError("release of non-captured request seq")
            self._active = 0
            if not self._is_retired(seq):
                self._done.add(seq)
                if len(self._done) > self._DONE_WINDOW:
                    evicted = min(self._done)
                    self._done.discard(evicted)
                    if evicted > self._done_floor:
                        self._done_floor = evicted
            self._cond.notify_all()

    def prepare_request_seq(self, seq: int) -> None:
        """Mark ``seq`` prepared (reference request-seq.go:99-106).
        NOTE: with the out-of-order capture model, MANY seqs can sit
        between prepared and retired, so this scalar watermark cannot
        enumerate prepared-but-unexecuted requests — anything built on it
        (e.g. a view-change retransmission of prepared requests) must use
        the pending request list, not this field.  Nothing reads it yet;
        kept for reference parity."""
        if seq > self._last_prepared:
            self._last_prepared = seq

    @property
    def last_prepared_seq(self) -> int:
        return self._last_prepared

    def retire_request_seq(self, seq: int) -> bool:
        """Mark ``seq`` executed; returns False if already retired
        (reference request-seq.go:108-112).

        EXACT per-seq retirement, NOT the reference's watermark jump: the
        collector executes in a deterministic global (view, cv) order,
        and with pipelined clients plus a reordering network a higher seq
        legitimately commits before a lower one — jumping would silently
        drop the lower request (never executed, never replied; the chaos
        soak wedged on exactly this).  The set is a pure function of the
        executed history — identical on every correct replica, so the
        checkpoint watermark digest stays aligned — and bounded: evicted
        seqs raise the floor (an ancient retransmit below the floor is
        refused as a duplicate, a liveness-only loss beyond a
        _RETIRE_WINDOW-deep reorder)."""
        if self._is_retired(seq):
            return False
        self._retired_set.add(seq)
        self._fold_retire_floor()
        while len(self._retired_set) > self._RETIRE_WINDOW:
            evicted = min(self._retired_set)
            self._retired_set.discard(evicted)
            if evicted > self._retire_floor:
                self._retire_floor = evicted
            self._fold_retire_floor()
        self._done.discard(seq)
        return True

    def _fold_retire_floor(self) -> None:
        """Collapse the contiguous executed prefix into the floor: floor
        semantics ("everything at or below is retired") are EXACT for a
        contiguous run, so keeping those seqs individually would only
        bloat every checkpoint digest and snapshot with up to
        _RETIRE_WINDOW (client, seq) pairs per client.  Clients allocate
        seqs serially from seq_start, so once an eviction (or in-order
        execution from a floor-adjacent start) lands the floor inside
        the run, the set stays near-empty.  Deterministic — a pure
        function of the set — so replicas' watermark digests stay
        aligned."""
        while self._retire_floor + 1 in self._retired_set:
            self._retire_floor += 1
            self._retired_set.discard(self._retire_floor)

    @property
    def last_captured_seq(self) -> int:
        return self._last_captured

    @property
    def retired_seq(self) -> int:
        """Highest executed seq (diagnostic)."""
        return max(self._retired_set, default=self._retire_floor)

    @property
    def retire_state(self):
        """(floor, sorted retired seqs above it) — the exact executed-seq
        state carried by checkpoints and state transfer."""
        return self._retire_floor, tuple(sorted(self._retired_set))

    def install_retired(self, floor: int, seqs) -> None:
        """State transfer: adopt a certified retire state.  Union with
        local facts (an executed seq stays executed), then advance the
        other lifecycle watermarks so a re-offered old request dedups
        instead of re-capturing."""
        if floor > self._retire_floor:
            self._retire_floor = floor
        self._retired_set.update(seqs)
        self._retired_set = {
            s for s in self._retired_set if s > self._retire_floor
        }
        self._fold_retire_floor()
        top = max(self._retired_set, default=self._retire_floor)
        if self._done:
            self._done = {s for s in self._done if not self._is_retired(s)}
        if self._last_captured < top:
            self._last_captured = top
        if self._last_prepared < top:
            self._last_prepared = top

    # -- reply buffer --------------------------------------------------------

    _REPLY_WINDOW = 128  # >= any client pipeline depth; O(1) per client

    def add_reply(self, seq: int, reply) -> None:
        """Store the reply in the bounded window and wake subscribers
        (reference reply.go:41-60, generalized for pipelined clients —
        see the constructor comment).  Out-of-order seqs are accepted:
        with exact retirement a lower seq legitimately EXECUTES after a
        higher one (reordered commits), so its first reply arriving
        "late" is fresh, not a stale retry — only seqs already replied or
        pruned below the window floor are dropped."""
        if seq in self._replies or seq <= self._reply_floor:
            return  # duplicate / pruned (reference "old request ID")
        self._replies[seq] = reply
        while len(self._replies) > self._REPLY_WINDOW:
            old, _ = self._replies.popitem(last=False)
            if old > self._reply_floor:
                self._reply_floor = old
        ev, self._reply_event = self._reply_event, asyncio.Event()
        ev.set()

    async def reply_for(self, seq: int) -> Optional[object]:
        """Await the reply for ``seq`` (reference reply.go:62-80
        ReplyChannel): waits until the reply lands in the window; returns
        None if ``seq`` was pruned out of it (a stale retry far behind
        the pipeline — the reference closes the channel without
        sending)."""
        while True:
            reply = self._replies.get(seq)
            if reply is not None:
                return reply
            if seq <= self._reply_floor:
                return None
            await self._reply_event.wait()

    # -- timers --------------------------------------------------------------

    def _start_timer(
        self,
        timers: Dict[int, object],
        seq: int,
        timeout: float,
        on_expiry: Callable[[], None],
    ) -> None:
        self._stop_timer(timers, seq)
        if timeout > 0:

            def fire() -> None:
                timers.pop(seq, None)
                on_expiry()

            timers[seq] = self._timers.after(timeout, fire)

    @staticmethod
    def _stop_timer(timers: Dict[int, object], seq: int) -> None:
        t = timers.pop(seq, None)
        if t is not None:
            t.cancel()

    def start_request_timer(
        self, seq: int, timeout: float, on_expiry: Callable[[], None]
    ) -> None:
        """(Re)start the request timer for ``seq`` (reference
        timeout.go:40-56, per-seq — see the constructor note)."""
        self._start_timer(self._request_timers, seq, timeout, on_expiry)

    def stop_request_timer(self, seq: int) -> None:
        self._stop_timer(self._request_timers, seq)

    def start_prepare_timer(
        self, seq: int, timeout: float, on_expiry: Callable[[], None]
    ) -> None:
        self._start_timer(self._prepare_timers, seq, timeout, on_expiry)

    def stop_prepare_timer(self, seq: int) -> None:
        self._stop_timer(self._prepare_timers, seq)


class ClientStates:
    """Lazily-populated per-client provider (reference client-state.go:36-55)."""

    def __init__(self, timer_provider: Optional[TimerProvider] = None):
        self._timers = timer_provider or StandardTimerProvider()
        self._clients: Dict[int, ClientState] = {}

    @property
    def timers(self) -> TimerProvider:
        """The injected timer provider (shared with replica-level timers
        like the view-change timer, so fake-timer tests control both)."""
        return self._timers

    def client(self, client_id: int) -> ClientState:
        st = self._clients.get(client_id)
        if st is None:
            st = ClientState(self._timers)
            self._clients[client_id] = st
        return st

    def all(self):
        return self._clients.items()

    def retire_watermarks(self):
        """Deterministic snapshot of the per-client retire state — part
        of the composite checkpoint digest: the retired set is a pure
        function of the executed history, so correct replicas agree on it
        at every batch boundary.

        Encoding: flat sorted (client_id, seq) pairs — the wire/digest
        shape predating exact retirement — where each client's FIRST pair
        carries its retire floor and the following pairs its individually
        retired seqs above the floor, ascending (all > floor, so the pair
        stream stays sorted).  Clients with no executed history are
        omitted.  Exactness matters: encoding only a max watermark would
        make a state-transferred replica refuse a still-live lower seq
        that up-to-date replicas later execute — a ledger fork."""
        out = []
        for cid, st in sorted(self._clients.items()):
            floor, seqs = st.retire_state
            if floor == 0 and not seqs:
                continue
            out.append((cid, floor))
            out.extend((cid, s) for s in seqs)
        return tuple(out)

    def install_retire_watermarks(self, marks) -> None:
        """State transfer: adopt a certified retire state (the
        :meth:`retire_watermarks` encoding — per client, floor first,
        then the retired seqs above it)."""
        by_client: Dict[int, list] = {}
        for cid, seq in marks:
            by_client.setdefault(cid, []).append(seq)
        for cid, seqs in by_client.items():
            self.client(cid).install_retired(seqs[0], seqs[1:])
