"""Per-client state: request-seq lifecycle, reply buffer, timers.

Reference core/internal/clientstate/: three sub-machines per client —

- request-seq lifecycle captured→released→prepared→retired with a blocking
  capture (reference request-seq.go:47-112): this is the per-client
  pipelining/dedup gate — one request in flight per client, strictly
  increasing sequence numbers, parallel across clients;
- reply buffer with per-seq subscription (reference reply.go:41-90);
- restartable single-slot request/prepare timers (reference timeout.go:40-71),
  injectable for tests (reference timer mock).
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from typing import Callable, Dict, Optional

from .timer import TimerProvider, StandardTimerProvider


class ClientState:
    """All containers here are O(1) per client — a long-lived replica's
    memory must not grow with the number of requests served (the
    reference keeps a single last-reply slot, reply.go:25-60, and scalar
    seq watermarks, request-seq.go:28-45)."""

    def __init__(self, timer_provider: TimerProvider):
        self._timers = timer_provider
        # request-seq state machine (reference request-seq.go:28-45)
        self._last_captured = 0
        self._last_released = 0
        self._last_prepared = 0
        self._retired = 0
        self._cond = asyncio.Condition()
        # Reply buffer: a bounded WINDOW of recent replies.  The reference
        # keeps exactly one last-reply slot (reply.go:25-38) — sound there
        # because its clients are strictly serial (requestbuffer's
        # single-capacity slot).  This build's clients pipeline up to
        # max_inflight requests, so replies k and k+1 can both land before
        # the waiter for k wakes; a single slot would skip k and strand
        # the client.  The window (insertion = execution = seq order)
        # bounds memory at O(_REPLY_WINDOW) per client while covering any
        # sane pipeline depth; the event is swapped on each add so waiters
        # from any earlier add are woken exactly once.
        self._last_replied_seq = 0
        self._replies: "OrderedDict[int, object]" = OrderedDict()
        self._reply_event = asyncio.Event()
        # timers (reference timeout.go)
        self._request_timer = None
        self._prepare_timer = None

    # -- request sequence lifecycle -----------------------------------------

    async def capture_request_seq(self, seq: int) -> bool:
        """Capture ``seq`` for processing.

        Returns False if ``seq`` was already captured (duplicate).  Blocks
        while a prior capture is unreleased (the per-client serialization of
        reference request-seq.go:47-82)."""
        # Duplicate fast path: ``_last_captured`` only grows, and on the
        # single-threaded event loop it cannot change between this check
        # and the return — the condvar is only needed to *capture*.
        # (Duplicates dominate: every peer message re-offers its embedded
        # requests.)
        if seq <= self._last_captured:
            return False
        async with self._cond:
            while self._last_captured != self._last_released:
                if seq <= self._last_captured:
                    return False
                await self._cond.wait()
            if seq <= self._last_captured:
                return False
            self._last_captured = seq
            return True

    async def release_request_seq(self, seq: int) -> None:
        """Finish processing a captured seq (reference request-seq.go:84-97)."""
        async with self._cond:
            if seq != self._last_captured or self._last_released == seq:
                raise ValueError("release of non-captured request seq")
            self._last_released = seq
            self._cond.notify_all()

    def prepare_request_seq(self, seq: int) -> None:
        """Mark ``seq`` prepared (reference request-seq.go:99-106).  A
        scalar watermark suffices: seqs are captured one-at-a-time per
        client, so at most one seq is between captured and retired.
        Nothing reads the watermark yet — like the reference's prepared
        flag it exists for the view-change path (retransmitting prepared-
        but-unexecuted requests), which is roadmap in both builds."""
        if seq > self._last_prepared:
            self._last_prepared = seq

    @property
    def last_prepared_seq(self) -> int:
        return self._last_prepared

    def retire_request_seq(self, seq: int) -> bool:
        """Mark ``seq`` executed; returns False if already retired
        (reference request-seq.go:108-112)."""
        if seq <= self._retired:
            return False
        self._retired = seq
        return True

    @property
    def last_captured_seq(self) -> int:
        return self._last_captured

    @property
    def retired_seq(self) -> int:
        return self._retired

    def install_retired_seq(self, seq: int) -> None:
        """State transfer: adopt a certified retire watermark.  The other
        lifecycle watermarks advance to match so a re-offered old request
        dedups instead of re-capturing."""
        if seq <= self._retired:
            return
        self._retired = seq
        if self._last_captured < seq:
            if self._last_released == self._last_captured:
                self._last_released = seq
            self._last_captured = seq
        if self._last_prepared < seq:
            self._last_prepared = seq

    # -- reply buffer --------------------------------------------------------

    _REPLY_WINDOW = 128  # >= any client pipeline depth; O(1) per client

    def add_reply(self, seq: int, reply) -> None:
        """Store the reply in the bounded window and wake subscribers
        (reference reply.go:41-60, generalized for pipelined clients —
        see the constructor comment)."""
        if seq <= self._last_replied_seq and seq not in self._replies:
            return  # stale (reference AddReply "old request ID")
        self._replies[seq] = reply
        if seq > self._last_replied_seq:
            self._last_replied_seq = seq
        while len(self._replies) > self._REPLY_WINDOW:
            self._replies.popitem(last=False)
        ev, self._reply_event = self._reply_event, asyncio.Event()
        ev.set()

    async def reply_for(self, seq: int) -> Optional[object]:
        """Await the reply for ``seq`` (reference reply.go:62-80
        ReplyChannel): waits until the client's replied watermark reaches
        ``seq``; returns None if ``seq`` was pruned out of the window (a
        stale retry far behind the pipeline — the reference closes the
        channel without sending)."""
        while self._last_replied_seq < seq:
            await self._reply_event.wait()
        return self._replies.get(seq)

    # -- timers --------------------------------------------------------------

    def start_request_timer(self, timeout: float, on_expiry: Callable[[], None]) -> None:
        """(Re)start the single-slot request timer (reference timeout.go:40-56)."""
        self.stop_request_timer()
        if timeout > 0:
            self._request_timer = self._timers.after(timeout, on_expiry)

    def stop_request_timer(self) -> None:
        if self._request_timer is not None:
            self._request_timer.cancel()
            self._request_timer = None

    def start_prepare_timer(self, timeout: float, on_expiry: Callable[[], None]) -> None:
        self.stop_prepare_timer()
        if timeout > 0:
            self._prepare_timer = self._timers.after(timeout, on_expiry)

    def stop_prepare_timer(self) -> None:
        if self._prepare_timer is not None:
            self._prepare_timer.cancel()
            self._prepare_timer = None


class ClientStates:
    """Lazily-populated per-client provider (reference client-state.go:36-55)."""

    def __init__(self, timer_provider: Optional[TimerProvider] = None):
        self._timers = timer_provider or StandardTimerProvider()
        self._clients: Dict[int, ClientState] = {}

    @property
    def timers(self) -> TimerProvider:
        """The injected timer provider (shared with replica-level timers
        like the view-change timer, so fake-timer tests control both)."""
        return self._timers

    def client(self, client_id: int) -> ClientState:
        st = self._clients.get(client_id)
        if st is None:
            st = ClientState(self._timers)
            self._clients[client_id] = st
        return st

    def all(self):
        return self._clients.items()

    def retire_watermarks(self):
        """Deterministic snapshot of per-client retire watermarks (sorted
        (client_id, retired_seq), zero entries omitted) — part of the
        composite checkpoint digest: the retired set is a pure function of
        the executed history, so correct replicas agree on it at every
        batch boundary."""
        return tuple(
            (cid, st.retired_seq)
            for cid, st in sorted(self._clients.items())
            if st.retired_seq > 0
        )

    def install_retire_watermarks(self, marks) -> None:
        """State transfer: adopt certified retire watermarks."""
        for cid, seq in marks:
            self.client(cid).install_retired_seq(seq)
