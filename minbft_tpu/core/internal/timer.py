"""Injectable timer abstraction (reference core/internal/timer/timer.go:30-87).

Exists so protocol timeouts can be tested without real time elapsing: tests
inject :class:`FakeTimerProvider` and fire timers explicitly (the reference
injects a gomock timer provider, core/internal/clientstate/timeout_test.go).
"""

from __future__ import annotations

import asyncio
from typing import Callable, List


class Timer:
    def cancel(self) -> None:
        raise NotImplementedError


class TimerProvider:
    def after(self, delay: float, callback: Callable[[], None]) -> Timer:
        raise NotImplementedError


class _StandardTimer(Timer):
    def __init__(self, handle: asyncio.TimerHandle):
        self._handle = handle

    def cancel(self) -> None:
        self._handle.cancel()


class StandardTimerProvider(TimerProvider):
    """Real-time timers on the running event loop."""

    def after(self, delay: float, callback: Callable[[], None]) -> Timer:
        loop = asyncio.get_running_loop()
        return _StandardTimer(loop.call_later(delay, callback))


class FakeTimer(Timer):
    def __init__(self, provider: "FakeTimerProvider", delay: float, callback):
        self.provider = provider
        self.delay = delay
        self.callback = callback
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def fire(self) -> None:
        if not self.cancelled:
            self.cancelled = True
            self.callback()


class FakeTimerProvider(TimerProvider):
    """Manual-fire timers for tests (no real time elapses)."""

    def __init__(self):
        self.timers: List[FakeTimer] = []

    def after(self, delay: float, callback: Callable[[], None]) -> FakeTimer:
        t = FakeTimer(self, delay, callback)
        self.timers.append(t)
        return t

    def fire_all(self) -> None:
        for t in list(self.timers):
            t.fire()
