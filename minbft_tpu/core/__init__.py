"""Core replica protocol engine.

An asyncio re-design of the reference ``core`` package (reference
core/replica.go, core/message-handling.go): the goroutine-per-stream +
closure-graph architecture becomes asyncio tasks over async streams, with
the same layering — validators (stateless, side-effect-free), processors
(stateful, idempotent), appliers (protocol actions) — and the same internal
state machines (clientstate, peerstate, viewstate, messagelog).

The one deliberate restructuring (the BASELINE.json north star): validators
*await* batched verification futures from
:class:`minbft_tpu.parallel.BatchVerifier` instead of verifying serially,
so all in-flight PREPARE/COMMIT/REQUEST authentication coalesces into
fixed-shape TPU kernel dispatches.  Stateful capture/apply stays strictly
sequential per peer (reference peerstate semantics), preserving the
protocol's exactly-once, in-counter-order guarantees.
"""

from .replica import new_replica

__all__ = ["new_replica"]
