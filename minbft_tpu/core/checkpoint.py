"""Checkpoint certificates — phase 1 of the checkpointing roadmap item
(reference README.md:492-493 lists checkpointing/GC as unimplemented; its
``checkpointPeriod``/``logsize`` config knobs are reserved,
api/api.go:40-43).

Every ``checkpoint_period`` executed requests, a replica certifies a
CHECKPOINT carrying its execution count and the state-machine digest
(:meth:`api.RequestConsumer.state_digest`).  A checkpoint becomes
**stable** once f+1 distinct replicas certified the same (count, digest):
at least one of them is correct, so the state at that count is durable
evidence.  The f+1 messages form the checkpoint certificate — retained so
the next phase (log truncation + VIEW-CHANGE log scoping, which also
needs a state-transfer path for lagging replicas) can anchor on it.

Execution order is identical on every correct replica (the commitment
collector releases strictly in primary-CV order and batches execute in
batch order), so the execution COUNT is a deterministic global sequence
number — two correct replicas always agree on the digest at a count, and
a certified mismatch at the same count is hard evidence of divergence
(or of a faulty replica's lie about its state), surfaced loudly.

Off by default: ``checkpoint_period = 0`` (the config default) emits
nothing and changes no behavior.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..messages import Checkpoint


class CheckpointCollector:
    """Tracks peers' certified checkpoints and the stable watermark.

    Memory is O(n): exactly one outstanding claim — the newest — is kept
    per replica (a faulty replica certifying absurd counts can replace
    its own claim but never grow state; cf. the repo's protocol-memory
    bounds).  Quorums still form through stragglers because every honest
    replica emits every period in order: f+1 replicas' newest claims
    meet at each period boundary before the frontier moves on."""

    def __init__(self, f: int, logger=None):
        self.f = f
        self.log = logger
        self._claims: Dict[int, Checkpoint] = {}  # replica -> newest claim
        self.stable_count = 0
        self.stable_digest: bytes = b""
        self._stable_cert: List[Checkpoint] = []

    @property
    def stable_certificate(self) -> List[Checkpoint]:
        """The f+1 CHECKPOINT messages proving the stable watermark."""
        return list(self._stable_cert)

    def record(self, cp: Checkpoint) -> bool:
        """Account one certified CHECKPOINT; True if it (now) makes its
        (count, digest) stable.  Divergence — certified different digests
        for one count — is logged loudly: it means a diverged state
        machine or a lying replica, and an operator must look."""
        if cp.count <= self.stable_count:
            return False  # already stable or below the watermark
        prev = self._claims.get(cp.replica_id)
        if prev is not None and prev.count >= cp.count:
            return False  # older (or duplicate) claim from this replica
        self._claims[cp.replica_id] = cp
        matching = [
            c
            for c in self._claims.values()
            if c.count == cp.count and c.digest == cp.digest
        ]
        divergent = sorted(
            c.replica_id
            for c in self._claims.values()
            if c.count == cp.count and c.digest != cp.digest
        )
        if divergent and self.log is not None:
            self.log.error(
                "checkpoint divergence at count %d: %s vs replicas %s",
                cp.count,
                cp.digest.hex()[:16],
                divergent,
            )
        if len(matching) < self.f + 1:
            return False
        self.stable_count = cp.count
        self.stable_digest = cp.digest
        self._stable_cert = matching[: self.f + 1]
        for rid in [
            r for r, c in self._claims.items() if c.count <= cp.count
        ]:
            del self._claims[rid]
        return True


def make_checkpoint_emitter(
    replica_id: int,
    period: int,
    consumer,
    emit_certified,
):
    """Closure run after each executed request: every ``period``
    executions, certify a CHECKPOINT of the consumer's state digest and
    hand it to ``emit_certified`` (the Handlers sink, which assigns the
    UI under its lock and applies the primary gate — see there).
    ``period <= 0`` disables emission entirely."""

    executed = {"n": 0}

    async def maybe_emit_checkpoint() -> None:
        executed["n"] += 1
        if period <= 0 or executed["n"] % period:
            return
        await emit_certified(
            Checkpoint(
                replica_id=replica_id,
                count=executed["n"],
                digest=consumer.state_digest(),
            )
        )

    return maybe_emit_checkpoint
