"""Checkpointing — certificates, log truncation, and the coverage-bound
audit that makes truncation safe at n = 2f+1.

Phase 1 (certificates) + phase 2 (GC/state transfer) of the reference's
top roadmap item (reference README.md:492-493 lists checkpointing/GC as
unimplemented; its ``checkpointPeriod``/``logsize`` config knobs are
reserved, reference api/api.go:40-43).

Protocol
--------

Execution is deterministic across correct replicas (the commitment
collector releases strictly in primary-CV order, batches execute in
batch order, and views advance monotonically), so the triple
``(count, view, cv)`` at a batch boundary — total requests delivered,
through which batch — is a deterministic global position.  Whenever
``count`` crosses a multiple of ``checkpoint_period`` at a batch end,
every replica (primary included) broadcasts a **signed** CHECKPOINT
claiming ``(count, view, cv, digest)`` where ``digest`` is the composite
:func:`checkpoint_digest` over the application state digest and the
per-client retire watermarks.  f+1 matching claims (own included — any
f+1 distinct replicas contain a correct one) make the checkpoint
**stable**: durable, transferable evidence of the state at that
position.

Checkpoints are signed rather than USIG-certified deliberately: they
consume no USIG counter, so the primary's prepare-CV sequence stays
contiguous (it can emit freely — closing the liveness margin where f
crashed backups left only f claims), and checkpoint claims never occupy
slots in the certified log that the view-change completeness argument
counts.

Truncation and the coverage-bound audit
---------------------------------------

The VIEW-CHANGE safety argument at n = 2f+1 needs *forced completeness*:
a quorum member — even a Byzantine one — must be unable to hide commit
evidence from its log, which the counters 1..k contiguity check
enforces.  Truncation must therefore be **validator-checkable**: a
replica may only drop a log prefix that provably holds no evidence
beyond a stable checkpoint.

Each CHECKPOINT therefore carries ``bounds``: for every peer p, the
highest own-counter b such that *all* of p's certified messages with
counters <= b that the emitter processed are **covered** by this
checkpoint — a PREPARE/COMMIT is covered iff its batch (view, cv) is <=
the checkpoint's (lexicographically; execution order is lexicographic in
(view, cv)), a VIEW-CHANGE/NEW-VIEW iff its transition concluded at a
view <= the checkpoint's.  Every replica already processes every peer's
log in strict counter order (peerstate capture), so these attestations
cost nothing extra.

Replica p may truncate its log prefix ``1..β`` once f+1 checkpoints
matching on (count, view, cv, digest) each attest ``bounds[p] >= β``:
at least one attester is correct, so the dropped prefix really is
covered.  The certificate travels with the truncated VIEW-CHANGE (and
with the LOG-BASE announcement on log replay), and validators check the
bounds — a Byzantine replica can *understate* its base (keeping more
history) but never overstate it to hide evidence.

Covered entries that cannot be dropped yet (the prefix rule: only a
contiguous prefix may go, or retained counters would gap) are **stubbed**
instead: the batch payload is replaced by its digest (same authen bytes,
so the UI certificate still verifies and the (view, cv) coverage claim is
itself USIG-authenticated — see ``messages.Prepare.requests_digest``).

Off by default: ``checkpoint_period = 0`` emits nothing and changes no
behavior.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..messages import Checkpoint, Commit, NewView, Prepare, ViewChange

_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")

# A checkpoint position: (count, view, cv).
Position = Tuple[int, int, int]


def checkpoint_digest(
    app_digest: bytes,
    count: int,
    view: int,
    cv: int,
    watermarks: Sequence[Tuple[int, int]],
) -> bytes:
    """Composite digest a CHECKPOINT claims: application state plus the
    deterministic protocol watermarks (per-client retired seqs).  Covering
    the watermarks makes state transfer self-verifying — a snapshot
    provider cannot hand a rejoining replica understated watermarks (which
    would double-execute re-proposed requests) without breaking the f+1
    certified digest."""
    h = hashlib.sha256()
    h.update(b"CPDIGEST")
    h.update(_U64.pack(count))
    h.update(_U64.pack(view))
    h.update(_U64.pack(cv))
    h.update(app_digest)
    for client, seq in watermarks:
        h.update(_U32.pack(client) + _U64.pack(seq))
    return h.digest()


def entry_coverage(entry) -> Optional[Tuple[str, Tuple[int, int]]]:
    """Classify a certified-log entry for coverage accounting.

    Returns ``("batch", (view, cv))`` for PREPARE/COMMIT (covered once the
    checkpoint position passes that batch), ``("view", (new_view, 0))``
    for VIEW-CHANGE/NEW-VIEW (covered once checkpoints run in a view >=
    new_view, i.e. the transition concluded), or None for entries that
    never block coverage."""
    if isinstance(entry, Prepare):
        if entry.ui is None:
            return None
        return ("batch", (entry.view, entry.ui.counter))
    if isinstance(entry, Commit):
        p = entry.prepare
        if p.ui is None:
            return None
        return ("batch", (p.view, p.ui.counter))
    if isinstance(entry, (ViewChange, NewView)):
        return ("view", (entry.new_view, 0))
    return None


def is_covered(coverage, view: int, cv: int) -> bool:
    """Is a classified entry covered by checkpoint position (view, cv)?"""
    if coverage is None:
        return True
    kind, key = coverage
    if kind == "batch":
        return key <= (view, cv)
    return key[0] <= view  # a concluded transition


def _bounds_dominate(new: Checkpoint, prev: Checkpoint) -> bool:
    """True iff ``new``'s coverage bounds are >= ``prev``'s for every peer
    prev attests, and strictly better somewhere (or attest new peers)."""
    prev_b = dict(prev.bounds)
    new_b = dict(new.bounds)
    # An absent peer must never dominate a present bound — even a present
    # 0 (bounds are attacker-chosen; absence == -1 keeps two claims that
    # differ only in a 0-bound entry from alternately replacing each
    # other and churning cert_version).
    if any(new_b.get(p, -1) < b for p, b in prev_b.items()):
        return False
    return new_b != prev_b


class CheckpointCollector:
    """Tracks signed checkpoint claims, the stable watermark, and the
    growing stable certificate the truncation audit draws bounds from.

    Memory is O(n): one outstanding claim per replica plus the stable
    certificate (at most one claim per replica).  Claims for the *stable*
    position keep accumulating after stabilization — late matching claims
    raise the per-peer truncation bounds the certificate can prove."""

    def __init__(self, f: int, logger=None):
        self.f = f
        self.log = logger
        self._claims: Dict[int, Checkpoint] = {}  # replica -> newest claim
        self.stable_count = 0
        self.stable_view = 0
        self.stable_cv = 0
        self.stable_digest: bytes = b""
        self._stable_cert: Dict[int, Checkpoint] = {}  # replica -> claim
        # Bumped whenever the stable certificate changes — lets callers
        # re-attempt truncation only when a claim actually changed it.
        self.cert_version = 0

    @property
    def stable_position(self) -> Position:
        return (self.stable_count, self.stable_view, self.stable_cv)

    @property
    def stable_certificate(self) -> List[Checkpoint]:
        """All collected claims proving the stable watermark (>= f+1)."""
        return list(self._stable_cert.values())

    def certificate_for_bound(
        self, replica_id: int, quorum: int
    ) -> Tuple[int, List[Checkpoint]]:
        """The best truncation base the stable certificate can prove for
        ``replica_id``, with the ``quorum`` claims proving it: β is the
        quorum-th largest of the attested bounds (every claim in the
        returned certificate attests >= β)."""
        claims = sorted(
            self._stable_cert.values(),
            key=lambda c: c.bound_for(replica_id),
            reverse=True,
        )[:quorum]
        if len(claims) < quorum:
            return 0, []
        beta = claims[-1].bound_for(replica_id)
        return beta, claims

    def record(self, cp: Checkpoint) -> bool:
        """Account one signature-verified CHECKPOINT; True if it (now)
        makes its position stable.  Divergence — different digests
        certified for one position — is logged loudly: it means a
        diverged state machine or a lying replica, and an operator must
        look."""
        if cp.count < self.stable_count:
            return False
        if cp.count == self.stable_count:
            # A late claim for the already-stable position: grow the
            # certificate (its bounds raise what truncation can prove).
            if cp.digest == self.stable_digest and (
                cp.view,
                cp.cv,
            ) == (self.stable_view, self.stable_cv):
                prev = self._stable_cert.get(cp.replica_id)
                # Replace only when the new claim's bounds DOMINATE the
                # stored one's: signed claims are replayable, and an
                # older replayed claim must neither shrink the provable
                # truncation base nor churn cert_version (a Byzantine
                # peer alternating two replays would otherwise force a
                # full log scan per message).
                if prev is None or _bounds_dominate(cp, prev):
                    self._stable_cert[cp.replica_id] = cp
                    self.cert_version += 1
            elif self.log is not None:
                # A conflicting claim at an f+1-certified position is
                # hard evidence of a diverged state machine or a lying
                # replica — surface it as loudly as pre-stability
                # divergence.
                self.log.error(
                    "checkpoint divergence at stable count %d: replica %d "
                    "certified %s vs stable %s",
                    cp.count,
                    cp.replica_id,
                    cp.digest.hex()[:16],
                    self.stable_digest.hex()[:16],
                )
            return False
        prev = self._claims.get(cp.replica_id)
        if prev is not None and prev.count >= cp.count:
            return False  # older (or duplicate) claim from this replica
        self._claims[cp.replica_id] = cp
        key = (cp.count, cp.view, cp.cv, cp.digest)
        matching = [
            c
            for c in self._claims.values()
            if (c.count, c.view, c.cv, c.digest) == key
        ]
        divergent = sorted(
            c.replica_id
            for c in self._claims.values()
            if c.count == cp.count
            and (c.view, c.cv, c.digest) != (cp.view, cp.cv, cp.digest)
        )
        if divergent and self.log is not None:
            self.log.error(
                "checkpoint divergence at count %d: %s vs replicas %s",
                cp.count,
                cp.digest.hex()[:16],
                divergent,
            )
        if len(matching) < self.f + 1:
            return False
        self._stabilize(matching)
        return True

    def _stabilize(self, matching: List[Checkpoint]) -> None:
        """Adopt ``matching`` (>= f+1 verified claims on one position) as
        the stable certificate — shared by local stabilization and
        external adoption so the two can never diverge."""
        cp = matching[0]
        self.stable_count = cp.count
        self.stable_view = cp.view
        self.stable_cv = cp.cv
        self.stable_digest = cp.digest
        self._stable_cert = {c.replica_id: c for c in matching}
        self.cert_version += 1
        for rid in [
            r for r, c in self._claims.items() if c.count <= cp.count
        ]:
            del self._claims[rid]

    def install(self, cert: Iterable[Checkpoint]) -> None:
        """Adopt an externally received stable certificate (from a
        LOG-BASE or NEW-VIEW) if it is ahead of the local watermark.  The
        caller has already validated it (f+1 distinct matching verified
        claims)."""
        cert = list(cert)
        if not cert or cert[0].count <= self.stable_count:
            return
        self._stabilize(cert)


class CoverageTracker:
    """Per-peer coverage bookkeeping feeding a checkpoint's ``bounds``.

    For each peer: the highest captured counter, and the still-uncovered
    entries (counter -> coverage key).  Everything is O(messages since the
    last stable checkpoint) — covered entries are popped whenever bounds
    are computed."""

    def __init__(self):
        self._hi: Dict[int, int] = {}
        self._open: Dict[int, Dict[int, tuple]] = {}

    def track(self, peer_id: int, counter: int, entry) -> None:
        """Record a captured certified message (called post-capture, so
        exactly once per (peer, counter))."""
        if counter > self._hi.get(peer_id, 0):
            self._hi[peer_id] = counter
        cov = entry_coverage(entry)
        if cov is not None:
            self._open.setdefault(peer_id, {})[counter] = cov

    def bounds_at(self, view: int, cv: int) -> Tuple[Tuple[int, int], ...]:
        """Per-peer coverage bounds for a checkpoint at (view, cv); also
        prunes entries that position covers."""
        out = []
        for peer, hi in sorted(self._hi.items()):
            open_ = self._open.get(peer)
            if open_:
                for c in [
                    c for c, cov in open_.items() if is_covered(cov, view, cv)
                ]:
                    del open_[c]
            if open_:
                bound = min(open_) - 1
            else:
                bound = hi
            out.append((peer, bound))
        return tuple(out)


def make_cert_validator(f: int, verify_signature):
    """Validator for a checkpoint certificate (carried by truncated
    VIEW-CHANGEs and LOG-BASE announcements): at least f+1 claims from
    distinct replicas, all matching on (count, view, cv, digest), each
    signature-verified.  Returns the representative claim.  Any f+1
    distinct replicas include a correct one, so a valid certificate's
    position and digest — and each member's signed coverage bounds — are
    trustworthy evidence."""

    import asyncio as _asyncio

    from .. import api

    async def validate_cert(cert: Sequence[Checkpoint]) -> Checkpoint:
        if len(cert) < f + 1:
            raise api.AuthenticationError(
                "checkpoint certificate needs f+1 claims"
            )
        senders = {c.replica_id for c in cert}
        if len(senders) != len(cert):
            raise api.AuthenticationError(
                "checkpoint certificate has duplicate claimants"
            )
        key = (cert[0].count, cert[0].view, cert[0].cv, cert[0].digest)
        for c in cert[1:]:
            if (c.count, c.view, c.cv, c.digest) != key:
                raise api.AuthenticationError(
                    "checkpoint certificate claims do not match"
                )
        results = await _asyncio.gather(
            *[verify_signature(c) for c in cert], return_exceptions=True
        )
        for res in results:
            if isinstance(res, BaseException):
                raise res
        return cert[0]

    return validate_cert


class CheckpointEmitter:
    """Drives checkpoint emission at executed **batch boundaries** (never
    mid-batch, so (count, view, cv) is a deterministic global position):
    whenever the delivered-request count has crossed a multiple of
    ``period`` at a batch end, sign and broadcast a CHECKPOINT of the
    composite state digest.  ``period <= 0`` disables emission entirely.

    Also retains the application snapshot + watermarks captured at the
    last emissions (``snapshot_for``) so this replica can serve state
    transfer for its certified claims — the snapshot must be taken at the
    checkpoint's exact position, not at request time (execution moves
    on).  Consumers without snapshot support degrade gracefully (no
    retained snapshots; truncation still works)."""

    RETAIN_SNAPSHOTS = 2

    def __init__(
        self, replica_id: int, period: int, consumer, watermarks, bounds_at,
        emit_signed,
    ):
        self.replica_id = replica_id
        self.period = period
        self._consumer = consumer
        self._watermarks = watermarks
        self._bounds_at = bounds_at
        self._emit_signed = emit_signed
        self.count = 0  # requests actually delivered (never re-drains)
        self._last_emit = 0
        self._snapshots: Dict[int, tuple] = {}  # count -> (view, cv, app, marks)

    def on_delivered(self) -> None:
        self.count += 1

    async def on_batch_end(self, view: int, cv: int) -> None:
        if self.period <= 0:
            return
        count = self.count
        if count // self.period <= self._last_emit // self.period:
            return
        self._last_emit = count
        marks = self._watermarks()
        try:
            app = self._consumer.snapshot()
        except NotImplementedError:
            app = None
        if app is not None:
            self._snapshots[count] = (view, cv, app, marks)
            for c in sorted(self._snapshots)[: -self.RETAIN_SNAPSHOTS]:
                del self._snapshots[c]
        await self._emit_signed(
            Checkpoint(
                replica_id=self.replica_id,
                count=count,
                view=view,
                cv=cv,
                digest=checkpoint_digest(
                    self._consumer.state_digest(), count, view, cv, marks
                ),
                bounds=self._bounds_at(view, cv),
            )
        )

    def snapshot_for(self, count: int):
        """(view, cv, app_state, watermarks) captured at emission, or
        None."""
        return self._snapshots.get(count)

    def install(self, count: int) -> None:
        """State transfer: adopt the certified position's count."""
        self.count = count
        self._last_emit = count
