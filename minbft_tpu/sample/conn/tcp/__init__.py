"""Native TCP transport: length-prefixed frames over asyncio streams.

A drop-in alternative to the gRPC connector/server pair (same
``api.ReplicaConnector`` / ``api.MessageStreamHandler`` contract — the
reference's connector abstraction, sample/conn/grpc/connector/connector.go:27-53,
exists exactly so transports can swap).  Purpose: the protocol's
throughput on small hosts is bounded by per-frame transport cost, and
gRPC's HTTP/2 machinery charges a large constant per message; this
transport is a u32-length-prefixed byte stream over raw asyncio TCP —
the cheapest per-frame path Python offers — and composes with the
codec-level frame coalescing (``messages.codec.drain_multi``) the same
way gRPC does.

Wire format, per connection:
  1 byte   chat kind (0x01 peer, 0x02 client)
  then     frames both directions: u32 BE length || payload

Trust model is unchanged from the gRPC transport: transports carry
opaque frames; every protocol message authenticates itself (signatures /
USIG certificates), and the HELLO handshake is verified above this layer.
"""

from __future__ import annotations

import asyncio
import struct
from typing import AsyncIterator, Dict, Optional

from .... import api

PEER_KIND = b"\x01"
CLIENT_KIND = b"\x02"

_LEN = struct.Struct(">I")
# Generous per-frame cap: coalesced frames are bounded at 256 KiB by the
# pumps; anything near 64 MiB is a corrupt or hostile length prefix.
MAX_FRAME = 64 * 1024 * 1024


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise ConnectionError(f"tcp frame length {n} exceeds cap")
    try:
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None


def _write_frame(writer: asyncio.StreamWriter, data: bytes) -> None:
    writer.write(_LEN.pack(len(data)) + data)


async def _close_writer(writer: asyncio.StreamWriter) -> None:
    """Best-effort orderly close: close() only schedules the transport
    teardown — wait_closed() lets the kernel flush/FIN before we drop the
    last reference (bounded: shutdown must never hang on a dead peer)."""
    try:
        writer.close()
        await asyncio.wait_for(writer.wait_closed(), 1.0)
    except (Exception, asyncio.CancelledError):
        pass


class _TcpStreamHandler(api.MessageStreamHandler):
    """Dial side of one chat stream (one TCP connection per stream —
    mirrors gRPC's one-RPC-per-handle_message_stream shape)."""

    def __init__(
        self,
        host: str,
        port: int,
        kind: bytes,
        dial_timeout: float,
        idle_timeout: float = 0.0,
    ):
        self._host = host
        self._port = port
        self._kind = kind
        self._dial_timeout = dial_timeout
        self._idle_timeout = idle_timeout

    async def _connect(self):
        # wait_for_ready semantics (reference grpc.WaitForReady(true)):
        # a cluster starts in any order, so dial retries until the peer
        # binds or the budget runs out.
        deadline = asyncio.get_running_loop().time() + self._dial_timeout
        delay = 0.05
        while True:
            try:
                return await asyncio.open_connection(self._host, self._port)
            except OSError:
                if asyncio.get_running_loop().time() >= deadline:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        reader, writer = await self._connect()
        writer.write(self._kind)

        async def pump_out() -> None:
            try:
                async for data in in_stream:
                    _write_frame(writer, data)
                    await writer.drain()
            except (ConnectionError, asyncio.CancelledError):
                pass

        pump = asyncio.get_running_loop().create_task(pump_out())
        try:
            while True:
                if self._idle_timeout > 0:
                    # Read-idle detection for HALF-OPEN peers: a stalled
                    # link (peer process wedged, or a middlebox silently
                    # dropping the flow) keeps the TCP connection "up"
                    # while frames stop — without a deadline this read
                    # parks forever and the ReconnectBackoff redial loop
                    # above never gets its turn.  Ending the stream here
                    # IS the recovery: the caller tears down and redials,
                    # and the peer's HELLO replay restores the log.  The
                    # broadcast-log stream is never legitimately idle for
                    # long (checkpoints and retransmissions keep flowing),
                    # so operators size this in seconds, well above any
                    # healthy gap; 0 (default) disables.
                    try:
                        frame = await asyncio.wait_for(
                            _read_frame(reader), self._idle_timeout
                        )
                    except asyncio.TimeoutError:
                        return
                else:
                    frame = await _read_frame(reader)
                if frame is None:
                    return
                yield frame
        finally:
            # Cancel-and-await so a pump_out() failure surfaces here
            # instead of rotting as an unretrieved task exception.
            pump.cancel()
            try:
                await pump
            except asyncio.CancelledError:
                pass
            await _close_writer(writer)


class TcpReplicaConnector(api.ReplicaConnector):
    """Dial-side connector over raw TCP (gRPC-connector contract)."""

    def __init__(
        self,
        kind: str = "peer",
        dial_timeout: float = 120.0,
        idle_timeout: float = 0.0,
    ):
        if kind not in ("peer", "client"):
            raise ValueError(f"unknown chat kind {kind!r}")
        self._kind = PEER_KIND if kind == "peer" else CLIENT_KIND
        self._dial_timeout = dial_timeout
        # Per-stream read-idle deadline (seconds; 0 = off): tears down a
        # half-open connection so the redial loop can recover it — see
        # _TcpStreamHandler.handle_message_stream.
        self._idle_timeout = idle_timeout
        self._targets: Dict[int, tuple] = {}

    def connect_replica(self, replica_id: int, target: str) -> None:
        host, port = target.rsplit(":", 1)
        self._targets[replica_id] = (host, int(port))

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        t = self._targets.get(replica_id)
        if t is None:
            return None
        return _TcpStreamHandler(
            t[0], t[1], self._kind, self._dial_timeout, self._idle_timeout
        )

    async def close(self) -> None:
        # Connections are per-stream and owned by their handlers; nothing
        # pooled to tear down here.
        self._targets.clear()


def connect_many_replicas_tcp(
    targets: Dict[int, str], kind: str = "peer", idle_timeout: float = 0.0
) -> TcpReplicaConnector:
    conn = TcpReplicaConnector(kind, idle_timeout=idle_timeout)
    for rid, target in targets.items():
        conn.connect_replica(rid, target)
    return conn


class TcpReplicaServer:
    """Serve a replica's connection handler over raw TCP (the
    ReplicaServer contract of sample/conn/grpc/server.py)."""

    def __init__(self, conn_handler: api.ConnectionHandler):
        self._conn = conn_handler
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        # Live connection tasks: stop() must cancel them — in 3.12+
        # Server.wait_closed() waits for connection handlers to FINISH,
        # and ours run until their stream ends.
        self._tasks: set = set()

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._tasks.add(task)
        try:
            await self._serve_connection_inner(reader, writer)
        finally:
            self._tasks.discard(task)

    async def _serve_connection_inner(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            kind = await reader.readexactly(1)
        except (asyncio.IncompleteReadError, ConnectionError):
            await _close_writer(writer)
            return
        if kind == PEER_KIND:
            handler = self._conn.peer_message_stream_handler()
        elif kind == CLIENT_KIND:
            handler = self._conn.client_message_stream_handler()
        else:
            await _close_writer(writer)
            return

        async def incoming() -> AsyncIterator[bytes]:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    return
                yield frame

        try:
            async for out in handler.handle_message_stream(incoming()):
                _write_frame(writer, out)
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        except Exception:
            # A protocol-level rejection (e.g. an unauthenticated HELLO)
            # closes this connection only.
            pass
        finally:
            await _close_writer(writer)

    async def start(self, address: str = "127.0.0.1:0") -> str:
        host, port = address.rsplit(":", 1)
        self._server = await asyncio.start_server(
            self._serve_connection, host, int(port)
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return f"{host}:{self.port}"

    async def stop(self, grace: float = 0.1) -> None:
        """Stop listening, give live connection handlers ``grace`` seconds
        to drain their streams (the gRPC server-contract semantics — a
        handler mid-reply finishes instead of losing the frame), then
        cancel whatever remains and wait for the sockets to close."""
        if self._server is not None:
            self._server.close()  # no NEW connections during the grace
            live = [t for t in self._tasks if not t.done()]
            if live and grace > 0:
                await asyncio.wait(live, timeout=grace)
            for t in list(self._tasks):
                t.cancel()
            if self._tasks:
                await asyncio.gather(*self._tasks, return_exceptions=True)
            await self._server.wait_closed()
            self._server = None
