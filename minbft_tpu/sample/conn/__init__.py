"""Connectivity backends (reference sample/conn/): in-process (the dummy
connector + replica stub used by integration tests and single-host
benchmarks) and TCP streams for multi-host deployment."""
