"""In-process connectivity: replica stubs and the dummy connector.

Reference sample/conn/common/replicastub (late-binding ConnectionHandler
that buffers stream requests until the replica is assigned — this is what
lets an in-process test network wire circular topologies) and
sample/conn/dummy/connector (same-process connector over the stubs).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, Optional

from ... import api


class ReplicaStub(api.ConnectionHandler):
    """Late-binding connection handler (reference
    sample/conn/common/replicastub/replica-stub.go:26-105)."""

    def __init__(self):
        self._replica: Optional[api.Replica] = None
        self._ready = asyncio.Event()

    def assign_replica(self, replica: api.Replica) -> None:
        self._replica = replica
        self._ready.set()

    def peer_message_stream_handler(self) -> api.MessageStreamHandler:
        return _DeferredHandler(self, "peer")

    def client_message_stream_handler(self) -> api.MessageStreamHandler:
        return _DeferredHandler(self, "client")


class _DeferredHandler(api.MessageStreamHandler):
    def __init__(self, stub: ReplicaStub, kind: str):
        self._stub = stub
        self._kind = kind

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        await self._stub._ready.wait()
        replica = self._stub._replica
        handler = (
            replica.peer_message_stream_handler()
            if self._kind == "peer"
            else replica.client_message_stream_handler()
        )
        async for out in handler.handle_message_stream(in_stream):
            yield out


class InProcessPeerConnector(api.ReplicaConnector):
    """Replica-side connector (reference sample/conn/common/connector.go:62-78
    resolving PeerMessageStreamHandler)."""

    def __init__(self, stubs: Dict[int, ReplicaStub]):
        self._stubs = stubs

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        stub = self._stubs.get(replica_id)
        return stub.peer_message_stream_handler() if stub else None


class InProcessClientConnector(api.ReplicaConnector):
    """Client-side connector resolving ClientMessageStreamHandler."""

    def __init__(self, stubs: Dict[int, ReplicaStub]):
        self._stubs = stubs

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        stub = self._stubs.get(replica_id)
        return stub.client_message_stream_handler() if stub else None


def make_testnet_stubs(n: int) -> Dict[int, ReplicaStub]:
    """Stub per replica, for wiring a circular in-process topology
    (reference core/integration_test.go:166-197)."""
    return {i: ReplicaStub() for i in range(n)}
