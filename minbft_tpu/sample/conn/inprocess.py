"""In-process connectivity: replica stubs and the dummy connector.

Reference sample/conn/common/replicastub (late-binding ConnectionHandler
that buffers stream requests until the replica is assigned — this is what
lets an in-process test network wire circular topologies) and
sample/conn/dummy/connector (same-process connector over the stubs).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, Optional

from ... import api

# Strong refs to scheduled aclose() tasks (TL601): the loop keeps only
# a weak reference to a running task, so without this set a deferred
# close is GC-able before the inner generator finalizes.
_close_tasks: set = set()


class ReplicaStub(api.ConnectionHandler):
    """Late-binding connection handler (reference
    sample/conn/common/replicastub/replica-stub.go:26-105)."""

    def __init__(self):
        self._replica: Optional[api.Replica] = None
        self._ready = asyncio.Event()
        self._crashed = asyncio.Event()

    def assign_replica(self, replica: api.Replica) -> None:
        self._replica = replica
        self._ready.set()

    def crash(self) -> None:
        """Simulate a process crash: every live stream through this stub
        ends and new ones never start (the in-process analogue of killing
        a replica process, reference README.md:411-458) — used by the
        view-change tests to take the primary down for real."""
        self._crashed.set()

    def revive(self) -> None:
        """Undo :meth:`crash` for NEW streams: the restart half of
        crash/restart fault injection (testing/faultnet.py).  Streams
        opened before the crash stay dead (they raced the old event);
        fresh dials reach whatever replica is (re-)assigned — callers
        restart a replica by ``assign_replica``-ing a new instance (or an
        adversarial stand-in) and then reviving."""
        self._crashed = asyncio.Event()

    def peer_message_stream_handler(self) -> api.MessageStreamHandler:
        return _DeferredHandler(self, "peer")

    def client_message_stream_handler(self) -> api.MessageStreamHandler:
        return _DeferredHandler(self, "client")


class _DeferredHandler(api.MessageStreamHandler):
    def __init__(self, stub: ReplicaStub, kind: str):
        self._stub = stub
        self._kind = kind

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        await self._stub._ready.wait()
        if self._stub._crashed.is_set():
            return
        replica = self._stub._replica
        handler = (
            replica.peer_message_stream_handler()
            if self._kind == "peer"
            else replica.client_message_stream_handler()
        )
        agen = handler.handle_message_stream(in_stream)
        crashed = asyncio.ensure_future(self._stub._crashed.wait())
        nxt = None
        try:
            while True:
                nxt = asyncio.ensure_future(agen.__anext__())
                done, _ = await asyncio.wait(
                    {nxt, crashed}, return_when=asyncio.FIRST_COMPLETED
                )
                if crashed in done:
                    break
                try:
                    out = nxt.result()
                except StopAsyncIteration:
                    break
                nxt = None
                yield out
        finally:
            # May run under GeneratorExit (caller closed us), where
            # awaiting is not allowed: cancel the in-flight step (which
            # unwinds the inner generator at its suspend point) and
            # schedule the close instead of awaiting it.
            crashed.cancel()
            if nxt is not None and not nxt.done():
                nxt.cancel()

            async def _close() -> None:
                try:
                    await agen.aclose()
                except BaseException:
                    pass

            t = asyncio.get_running_loop().create_task(_close())
            _close_tasks.add(t)
            t.add_done_callback(_close_tasks.discard)


class InProcessPeerConnector(api.ReplicaConnector):
    """Replica-side connector (reference sample/conn/common/connector.go:62-78
    resolving PeerMessageStreamHandler)."""

    def __init__(self, stubs: Dict[int, ReplicaStub]):
        self._stubs = stubs

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        stub = self._stubs.get(replica_id)
        return stub.peer_message_stream_handler() if stub else None


class InProcessClientConnector(api.ReplicaConnector):
    """Client-side connector resolving ClientMessageStreamHandler."""

    def __init__(self, stubs: Dict[int, ReplicaStub]):
        self._stubs = stubs

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        stub = self._stubs.get(replica_id)
        return stub.client_message_stream_handler() if stub else None


def make_testnet_stubs(n: int) -> Dict[int, ReplicaStub]:
    """Stub per replica, for wiring a circular in-process topology
    (reference core/integration_test.go:166-197)."""
    return {i: ReplicaStub() for i in range(n)}
