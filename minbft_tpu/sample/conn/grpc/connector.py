"""gRPC connector: the dialing side of replica connections.

Reference sample/conn/grpc/connector/: ``ConnectReplica(id, target)`` dials
a replica and exposes a ``MessageStreamHandler`` per chat kind; each
``handle_message_stream`` call opens one bidi-streaming RPC whose request
stream is pumped from the caller's outgoing iterator and whose responses
are yielded back (reference connector/replica.go:49-122 runs a goroutine
pair per stream; grpc.aio drives both directions from the one generator).
``wait_for_ready`` mirrors the reference's ``grpc.WaitForReady(true)`` dial
behavior so a cluster can start in any order.
"""

from __future__ import annotations

from typing import AsyncIterator, Dict, Optional

import grpc
import grpc.aio

from .... import api
from .channel import CLIENT_CHAT, PEER_CHAT, identity


class _GrpcStreamHandler(api.MessageStreamHandler):
    def __init__(self, channel: grpc.aio.Channel, method: str):
        self._rpc = channel.stream_stream(
            method, request_serializer=identity, response_deserializer=identity
        )

    async def handle_message_stream(
        self, in_stream: AsyncIterator[bytes]
    ) -> AsyncIterator[bytes]:
        call = self._rpc(in_stream, wait_for_ready=True)
        try:
            async for resp in call:
                yield resp
        finally:
            call.cancel()


class GrpcReplicaConnector(api.ReplicaConnector):
    """Dial-side connector (reference connector.ReplicaConnector,
    sample/conn/grpc/connector/connector.go:27-53).

    ``kind`` selects which chat the resolved handlers speak:
    ``"peer"`` for replica-to-replica, ``"client"`` for client-to-replica.
    """

    def __init__(self, kind: str = "peer"):
        if kind not in ("peer", "client"):
            raise ValueError(f"unknown chat kind {kind!r}")
        self._method = PEER_CHAT if kind == "peer" else CLIENT_CHAT
        self._channels: Dict[int, grpc.aio.Channel] = {}

    def connect_replica(self, replica_id: int, target: str) -> None:
        """Associate ``replica_id`` with a dialed channel
        (reference connector.go:35-43)."""
        self._channels[replica_id] = grpc.aio.insecure_channel(target)

    def replica_message_stream_handler(
        self, replica_id: int
    ) -> Optional[api.MessageStreamHandler]:
        ch = self._channels.get(replica_id)
        if ch is None:
            return None
        return _GrpcStreamHandler(ch, self._method)

    async def close(self) -> None:
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()


def connect_many_replicas(
    targets: Dict[int, str], kind: str = "peer"
) -> GrpcReplicaConnector:
    """Dial every replica in ``targets`` (reference ConnectManyReplicas,
    sample/conn/grpc/connector/connector.go:45-53)."""
    conn = GrpcReplicaConnector(kind)
    for rid, target in targets.items():
        conn.connect_replica(rid, target)
    return conn
