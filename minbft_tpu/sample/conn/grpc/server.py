"""gRPC server side: bridge incoming streams to a ConnectionHandler.

Reference sample/conn/grpc/server/server.go:88-143: each incoming
``ClientChat``/``PeerChat`` RPC is bridged to the replica's
``MessageStreamHandler`` with a goroutine pair (errgroup); here the bridge
is a single async generator — the RPC's request iterator feeds the handler
and the handler's replies stream back as responses.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

import grpc
import grpc.aio

from .... import api
from .channel import CLIENT_CHAT, PEER_CHAT, SERVICE, identity


def _stream_bridge(get_handler):
    """One stream-stream RPC bound to one MessageStreamHandler factory.

    Must be a plain async-generator *function* (not a callable object):
    grpc.aio introspects the behavior with ``inspect.isasyncgenfunction``
    and would otherwise fall back to its sync-generator thread shim."""

    async def bridge(
        request_iterator: AsyncIterator[bytes], context
    ) -> AsyncIterator[bytes]:
        handler: api.MessageStreamHandler = get_handler()
        async for out in handler.handle_message_stream(request_iterator):
            yield out

    return bridge


class ReplicaServer:
    """Serves a replica's connection handler over gRPC
    (reference server.ReplicaServer, sample/conn/grpc/server/server.go:43-86).

    ``conn_handler`` provides the two stream handlers (an ``api.Replica``
    satisfies the interface)."""

    def __init__(self, conn_handler: api.ConnectionHandler):
        self._conn = conn_handler
        self._server: Optional[grpc.aio.Server] = None
        self.port: Optional[int] = None

    async def start(self, address: str = "127.0.0.1:0") -> str:
        """Bind and start serving; returns the bound address (with the real
        port when ``address`` asked for an ephemeral one)."""
        server = grpc.aio.server()
        rpcs = {
            CLIENT_CHAT.rsplit("/", 1)[1]: grpc.stream_stream_rpc_method_handler(
                _stream_bridge(self._conn.client_message_stream_handler),
                request_deserializer=identity,
                response_serializer=identity,
            ),
            PEER_CHAT.rsplit("/", 1)[1]: grpc.stream_stream_rpc_method_handler(
                _stream_bridge(self._conn.peer_message_stream_handler),
                request_deserializer=identity,
                response_serializer=identity,
            ),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, rpcs),)
        )
        self.port = server.add_insecure_port(address)
        self._server = server
        await server.start()
        host = address.rsplit(":", 1)[0]
        return f"{host}:{self.port}"

    async def stop(self, grace: float = 0.1) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None
