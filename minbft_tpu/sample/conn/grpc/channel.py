"""The wire-level service definition shared by connector and server.

Reference sample/conn/grpc/channel.proto:15-29 defines::

    service Channel {
      rpc ClientChat(stream Message) returns (stream Message);
      rpc PeerChat(stream Message) returns (stream Message);
    }
    message Message { bytes payload = 1; }

Rather than running a schema compiler, both ends register the two
stream-stream methods with **identity serializers**: each gRPC message body
*is* the opaque protocol-message payload (the codec's canonical bytes).
"""

SERVICE = "minbft.Channel"
CLIENT_CHAT = f"/{SERVICE}/ClientChat"
PEER_CHAT = f"/{SERVICE}/PeerChat"


def identity(b: bytes) -> bytes:
    return b
