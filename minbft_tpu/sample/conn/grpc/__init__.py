"""gRPC communication backend (reference sample/conn/grpc/).

Bidirectional ``ClientChat``/``PeerChat`` streams carrying opaque serialized
protocol messages, exactly the reference's wire design
(reference sample/conn/grpc/channel.proto:22-29 — a single ``bytes payload``
field; here the payload rides as the raw request/response body via identity
(de)serializers, so no schema compiler is needed).
"""

from .connector import GrpcReplicaConnector, connect_many_replicas
from .server import ReplicaServer

__all__ = ["GrpcReplicaConnector", "ReplicaServer", "connect_many_replicas"]
