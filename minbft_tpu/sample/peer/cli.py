"""peer — run a replica or submit requests from the command line.

Reference sample/peer: ``peer run <id>`` loads the keystore + consensus
config, assembles the stack (authenticator, ledger, gRPC connector), and
serves (run.go:91-159); ``peer request <args…>`` is the client-side
equivalent, reading operations from argv or stdin (request.go:87-134);
flags layer over ``PEER_*`` environment variables (root.go:73-82).

    # shared flags (--keys/--config/--auth/--log-level) go BEFORE the
    # subcommand; per-subcommand flags (--listen/--batch/...) after it:
    python -m minbft_tpu.sample.peer --keys keys.yaml --config consensus.yaml run 0
    python -m minbft_tpu.sample.peer --keys keys.yaml --config consensus.yaml request "op"
    python -m minbft_tpu.sample.peer selftest   # in-process n=4 smoke test

The replica's COMMIT-phase verification runs through the TPU batching
engine (``--batch``); ``--no-batch`` falls back to serial host crypto.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys


from ..envflags import env_default


def _env(name: str, fallback, choices=None):
    return env_default("PEER", name, fallback, choices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="peer", description="minbft-tpu peer")
    p.add_argument(
        "--keys", default=_env("keys", "keys.yaml"), help="keystore path"
    )
    p.add_argument(
        "--config",
        default=_env("config", "consensus.yaml"),
        help="consensus config path",
    )
    _levels = ("debug", "info", "warning", "error")
    p.add_argument(
        "--log-level",
        default=_env("log_level", "info", choices=_levels),
        choices=_levels,
    )
    p.add_argument("--log-file", default=_env("log_file", "") or None)
    _auths = ("signatures", "mac")
    p.add_argument(
        "--auth",
        choices=_auths,
        default=_env("auth", "signatures", choices=_auths),
        help="message authentication: public-key signatures (default) or "
        "pairwise MACs (keys.yaml needs a macs section: keytool --macs)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    r = sub.add_parser("run", help="run a replica")
    r.add_argument("id", type=int, help="replica id")
    r.add_argument(
        "--listen",
        default=_env("listen", ""),
        help="listen address (default: this id's addr from the config)",
    )
    r.add_argument(
        "--batch",
        type=int,
        default=_env("batch", 512),
        help="max verification batch per kernel launch",
    )
    r.add_argument(
        "--no-batch",
        action="store_true",
        help="serial host-crypto verification (no TPU engine)",
    )
    r.add_argument(
        "--metrics-interval",
        type=float,
        default=_env("metrics_interval", 0.0),
        help="log the protocol counters every N seconds (0 = off)",
    )

    q = sub.add_parser("request", help="submit request(s) as a client")
    q.add_argument("ops", nargs="*", help="operations (default: stdin lines)")
    q.add_argument("--client-id", type=int, default=_env("client_id", 0))
    q.add_argument("--timeout", type=float, default=_env("timeout", 30.0))

    sub.add_parser("selftest", help="in-process n=4 cluster smoke test")

    t = sub.add_parser(
        "testnet", help="scaffold keys.yaml + consensus.yaml for a local cluster"
    )
    t.add_argument("-n", "--replicas", type=int, default=3)
    t.add_argument("-f", "--faults", type=int, default=None, help="default (n-1)//2")
    t.add_argument("--clients", type=int, default=1)
    t.add_argument("--base-port", type=int, default=42600)
    t.add_argument("--host", default="127.0.0.1")
    t.add_argument("-d", "--dir", default=".", help="output directory")
    t.add_argument(
        "--usig",
        choices=("auto", "NATIVE_ECDSA", "SOFT_ECDSA", "HMAC_SHA256"),
        default="auto",
    )
    t.add_argument(
        "--macs", action="store_true",
        default=bool(_env("macs", 0)),
        help="include pairwise-MAC material (enables run/request --auth mac)",
    )
    return p


def _log_opts(args):
    from ...core.options import with_log_file, with_log_level

    opts = [with_log_level(getattr(logging, args.log_level.upper()))]
    if args.log_file:
        opts.append(with_log_file(args.log_file))
    return opts


async def _run_replica(args) -> int:
    from ...core import new_replica
    from ...sample.authentication import KeyStore
    from ...sample.config import load_config
    from ...sample.conn.grpc import GrpcReplicaConnector, ReplicaServer
    from ...sample.requestconsumer import SimpleLedger

    store = KeyStore.load(args.keys)
    cfg = load_config(args.config)
    addrs = {p.id: p.addr for p in cfg.peers}
    if args.id not in addrs:
        raise SystemExit(f"peer: replica {args.id} not in {args.config} peers[]")

    engine = None
    batch_signatures = False
    if not args.no_batch:
        import jax

        # The batch engine only pays off where the limb kernels beat host
        # OpenSSL — i.e. on a real accelerator.  On the CPU backend a
        # single COMMIT would pad to a full unrolled-P256 batch (plus the
        # kernel's large XLA CPU compile), so fall back to serial host
        # crypto there exactly as --no-batch does.
        if jax.default_backend() != "cpu":
            from ...parallel import BatchVerifier

            engine = BatchVerifier(max_batch=args.batch, buckets=(args.batch,))
            batch_signatures = True

    if args.auth == "mac":
        # device_macs follows the signature-placement rule: the HMAC batch
        # kernel only beats host HMAC where the chip isn't remote-attached.
        auth = store.mac_replica_authenticator(
            args.id, engine=engine, device_macs=batch_signatures
        )
    else:
        auth = store.replica_authenticator(
            args.id, engine=engine, batch_signatures=batch_signatures
        )
    conn = GrpcReplicaConnector("peer")
    for rid, addr in addrs.items():
        if rid != args.id:
            conn.connect_replica(rid, addr)
    ledger = SimpleLedger()
    replica = new_replica(
        args.id, cfg, auth, conn, ledger, opts=_log_opts(args)
    )
    server = ReplicaServer(replica)
    listen = args.listen or addrs[args.id]
    bound = await server.start(listen)
    print(f"replica {args.id} serving on {bound}", file=sys.stderr)
    await replica.start()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-Unix
            pass

    async def log_metrics() -> None:
        import json as _json

        while not stop.is_set():
            await asyncio.sleep(args.metrics_interval)
            snap = replica.metrics.snapshot()
            snap["executed_per_sec"] = round(replica.metrics.executed_per_sec(), 2)
            print(f"metrics: {_json.dumps(snap)}", file=sys.stderr)

    metrics_task = (
        loop.create_task(log_metrics()) if args.metrics_interval > 0 else None
    )
    await stop.wait()
    if metrics_task is not None:
        metrics_task.cancel()
    print(f"replica {args.id} shutting down", file=sys.stderr)
    await replica.stop()
    await server.stop()
    await conn.close()
    return 0


async def _run_request(args) -> int:
    from ...client import new_client
    from ...sample.authentication import KeyStore
    from ...sample.config import load_config
    from ...sample.conn.grpc import connect_many_replicas

    store = KeyStore.load(args.keys)
    cfg = load_config(args.config)
    addrs = {p.id: p.addr for p in cfg.peers}
    if len(addrs) < cfg.n:
        raise SystemExit("peer: config peers[] does not cover all replicas")

    ops = [op.encode() for op in args.ops]
    if not ops:
        ops = [line.rstrip("\n").encode() for line in sys.stdin if line.strip()]

    conn = connect_many_replicas(addrs, kind="client")
    if args.auth == "mac":
        client_auth = store.mac_client_authenticator(args.client_id)
    else:
        client_auth = store.client_authenticator(args.client_id)
    client = new_client(args.client_id, cfg.n, cfg.f, client_auth, conn)
    await client.start()
    rc = 0
    try:
        for op in ops:
            result = await asyncio.wait_for(client.request(op), args.timeout)
            print(result.hex())
    except asyncio.TimeoutError:
        print("peer: request timed out", file=sys.stderr)
        rc = 1
    finally:
        await client.stop()
        await conn.close()
    return rc


async def _run_selftest(args) -> int:
    """In-process n=4/f=1 commit through generated keys + the dummy
    connector — a deployment smoke test needing no files or sockets."""
    from ...client import new_client
    from ...core import new_replica
    from ...sample.authentication import generate_testnet_keys
    from ...sample.config import SimpleConfiger
    from ...sample.conn.inprocess import (
        InProcessClientConnector,
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from ...sample.requestconsumer import SimpleLedger

    n, f = 4, 1
    store = generate_testnet_keys(n, n_clients=1)
    cfg = SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=30.0)
    stubs = make_testnet_stubs(n)
    ledgers = [SimpleLedger() for _ in range(n)]
    replicas = []
    for i in range(n):
        r = new_replica(
            i,
            cfg,
            store.replica_authenticator(i),
            InProcessPeerConnector(stubs),
            ledgers[i],
            opts=_log_opts(args),
        )
        stubs[i].assign_replica(r)
        replicas.append(r)
    for r in replicas:
        await r.start()
    client = new_client(
        0, n, f, store.client_authenticator(0), InProcessClientConnector(stubs)
    )
    await client.start()
    result = await asyncio.wait_for(client.request(b"selftest"), 60)
    for _ in range(200):
        if all(lg.length == 1 for lg in ledgers):
            break
        await asyncio.sleep(0.02)
    ok = all(lg.length == 1 for lg in ledgers)
    await client.stop()
    for r in replicas:
        await r.stop()
    if not ok:
        print("selftest FAILED: not all ledgers committed", file=sys.stderr)
        return 1
    print(f"selftest ok: request committed on all {n} replicas "
          f"(usig={store.usig_spec}, result={result.hex()[:16]}…)", file=sys.stderr)
    return 0


def _run_testnet_scaffold(args) -> int:
    """Write keys.yaml + consensus.yaml for an n-replica local cluster
    (the docker-entrypoint key-generation step of the reference,
    sample/docker/docker-entrypoint.sh, as an explicit command)."""
    from ...sample.authentication import generate_testnet_keys

    f = args.faults if args.faults is not None else (args.replicas - 1) // 2
    if args.replicas < 2 * f + 1:
        raise SystemExit(f"peer: n={args.replicas} < 2f+1 with f={f}")
    os.makedirs(args.dir, exist_ok=True)
    store = generate_testnet_keys(
        args.replicas, n_clients=args.clients, usig_spec=args.usig,
        with_macs=args.macs,
    )
    keys_path = os.path.join(args.dir, "keys.yaml")
    store.save(keys_path)
    # Per-replica least-privilege copies: replica i gets only its own
    # private material (and only its rows of the MAC matrix) — handing the
    # full store to every node would let one compromised replica forge
    # other principals' keys/MAC slots.  The full keys.yaml stays for the
    # operator/client side.  All files are written 0600 (KeyStore.save).
    for i in range(args.replicas):
        store.strip_private(keep_replica=i).save(
            os.path.join(args.dir, f"keys.replica{i}.yaml")
        )
    peers = [
        {"id": i, "addr": f"{args.host}:{args.base_port + i}"}
        for i in range(args.replicas)
    ]
    cfg = {
        "protocol": {
            "n": args.replicas,
            "f": f,
            # Checkpointing on by default: every 128 executions the
            # replicas certify state, GC their logs behind the stable
            # certificate, and serve state transfer (override with
            # CONSENSUS_CHECKPOINT_PERIOD; 0 disables).
            "checkpointPeriod": 128,
            "logsize": 0,
            "batchsizePrepare": 64,
            "timeout": {"request": "8s", "prepare": "4s", "viewchange": "8s"},
        },
        "peers": peers,
    }
    import yaml

    cfg_path = os.path.join(args.dir, "consensus.yaml")
    with open(cfg_path, "w") as fh:
        yaml.safe_dump(cfg, fh, sort_keys=False)
    print(
        f"wrote {keys_path} (usig={store.usig_spec}) and {cfg_path} "
        f"(n={args.replicas}, f={f})",
        file=sys.stderr,
    )
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "run":
        return asyncio.run(_run_replica(args))
    if args.command == "request":
        return asyncio.run(_run_request(args))
    if args.command == "selftest":
        return asyncio.run(_run_selftest(args))
    if args.command == "testnet":
        return _run_testnet_scaffold(args)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
